"""Chaos suite for quota enforcement: verdicts stay DETERMINISTIC and
conservative under injected spawn/exec faults. Seed-parameterized via
``CHAOS_SEED`` (CI pins {7, 23, 1337}); every seed replays exactly.

Pinned invariants:
- the admission verdict depends only on the LEDGER (what actually ran and
  billed), never on fault noise: a denied tenant is denied because its
  billed consumption crossed the budget, and the denial threshold is
  exactly reproducible from the wire's own ground-truth accounting;
- denied requests consume NOTHING — no scheduler tickets, no retry-ladder
  attempts against the faulty wire, no sandbox spawns;
- concurrency slots always come back, whatever exit path a faulted request
  took (the release-in-finally discipline under 50% wire drops);
- a violation storm under chaos still quarantines at the door, and the
  quarantined tenant's attempts stop reaching the wire entirely;
- the kill switch holds under fire: with APP_QUOTAS_ENABLED=0 the same
  chaotic workload sees zero quota machinery.
"""

import asyncio
import os
import random

import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.errors import (
    ExecutorError,
    QuotaExceededError,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "7"))


def make_executor(tmp_path, **kwargs):
    kwargs.setdefault("file_storage_path", str(tmp_path / "storage"))
    kwargs.setdefault("executor_pod_queue_target_length", 1)
    kwargs.setdefault("batching_enabled", False)
    config = Config(**kwargs)
    return CodeExecutor(
        FakeBackend(), Storage(config.file_storage_path), config
    )


class SeededWire:
    """Deterministic faulty wire (the usage-chaos harness's shape): each
    /execute draws from the seeded stream — drop (ExecutorError, retried
    by the ladder) or answer with a drawn device-op time. Ground truth for
    what the ledger billed."""

    def __init__(self, executor, seed: int, drop_rate=0.5):
        self.rng = random.Random(seed)
        self.drop_rate = drop_rate
        self.reported_device_op = 0.0
        self.attempts = 0
        executor._post_execute = self.post

    async def post(self, client, base, payload, timeout, sandbox):
        self.attempts += 1
        if self.rng.random() < self.drop_rate:
            raise ExecutorError("chaos: exec connection dropped")
        device_op = round(self.rng.uniform(0.05, 0.3), 6)
        self.reported_device_op += device_op
        return {
            "stdout": "ok\n",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "warm": True,
            "duration_s": device_op,
            "device_op_seconds": device_op,
        }


async def test_quota_verdicts_track_billing_not_fault_noise(tmp_path):
    """Under 50% wire drops, the denial point is exactly where the LEDGER
    crossed the budget — reproducible from the wire's own accounting, not
    from how many attempts the retry ladder happened to burn."""
    budget = 1.0
    executor = make_executor(
        tmp_path,
        quota_chip_seconds_per_window=budget,
        quota_window_seconds=3600.0,
    )
    wire = SeededWire(executor, CHAOS_SEED)
    denied = 0
    served = 0
    try:
        for i in range(30):
            billed_before = (
                executor.usage.snapshot()["tenants"]
                .get("chaos-tenant", {})
                .get("chip_seconds", 0.0)
            )
            try:
                await executor.execute(
                    f"print({i})", tenant="chaos-tenant"
                )
                served += 1
            except QuotaExceededError as e:
                denied += 1
                # The verdict is explained ENTIRELY by billed consumption:
                # denial iff the ledger already held >= budget.
                assert billed_before >= budget
                assert e.reason == "chip_seconds"
            except ExecutorError:
                # The ladder exhausted against the chaotic wire — billed
                # wall time still lands; admission itself never faulted.
                served += 1
        # The seeded ops average ~0.175s, so the 1.0s budget exhausts and
        # everything after is denied — deterministically for this seed.
        assert denied > 0 and served > 0
        row = executor.usage.snapshot()["tenants"]["chaos-tenant"]
        assert row["chip_seconds"] >= budget
        # Denied requests are rejected-outcome rows, never infra errors.
        assert row["outcomes"].get("rejected", 0) == denied
    finally:
        await executor.close()


async def test_denied_requests_never_touch_wire_or_scheduler(tmp_path):
    executor = make_executor(
        tmp_path,
        quota_chip_seconds_per_window=0.2,
        quota_window_seconds=3600.0,
    )
    wire = SeededWire(executor, CHAOS_SEED + 1, drop_rate=0.0)
    try:
        await executor.execute("print(0)", tenant="chaos-tenant")
        attempts_after_first = wire.attempts
        spawns_after_first = executor.backend.spawns
        for i in range(10):
            with pytest.raises(QuotaExceededError):
                await executor.execute(f"print({i})", tenant="chaos-tenant")
        # ZERO wire attempts, zero spawns, zero queue residue for the ten
        # denials — the abuse-control point of admission-side shedding.
        assert wire.attempts == attempts_after_first
        assert executor.backend.spawns == spawns_after_first
        assert executor.scheduler.queued(0) == 0
    finally:
        await executor.close()


async def test_concurrency_slots_survive_faulted_exits(tmp_path):
    """Every exit path — ok, retried-then-ok, ladder-exhausted infra
    error — releases its concurrency slot; 50% drops for 40 requests at a
    cap of 4 never wedges admission."""
    executor = make_executor(
        tmp_path,
        quota_max_concurrent=4,
    )
    SeededWire(executor, CHAOS_SEED + 2, drop_rate=0.5)
    try:
        results = await asyncio.gather(
            *(
                executor.execute(f"print({i})", tenant="chaos-tenant")
                for i in range(40)
            ),
            return_exceptions=True,
        )
        # Concurrency denials are possible mid-burst (the cap is the
        # point); what must NEVER happen is a leaked slot wedging the
        # tenant afterwards:
        win = executor.quotas._windows.get("chaos-tenant")
        assert win is not None and win.in_flight == 0
        result = await executor.execute("print('after')",
                                        tenant="chaos-tenant")
        assert result.exit_code == 0
        infra = [r for r in results if isinstance(r, ExecutorError)]
        quota = [r for r in results if isinstance(r, QuotaExceededError)]
        ok = [r for r in results if not isinstance(r, Exception)]
        assert len(infra) + len(quota) + len(ok) == 40
    finally:
        await executor.close()


async def test_violation_storm_quarantines_under_chaos(tmp_path):
    """A violating tenant under a chaotic wire still hits quarantine at
    the threshold, and its subsequent attempts stop reaching the wire."""
    executor = make_executor(
        tmp_path,
        quota_violations_per_window=3,
        quota_window_seconds=3600.0,
        quota_quarantine_base_seconds=300.0,
    )
    wire = SeededWire(executor, CHAOS_SEED + 3, drop_rate=0.0)
    try:
        await executor.execute("print(0)", tenant="bad-tenant")
        for _ in range(3):
            executor.usage.add(
                "bad-tenant", violation="oom", requests=1,
                outcome="limit_violation",
            )
        attempts_before = wire.attempts
        for i in range(5):
            with pytest.raises(QuotaExceededError) as e:
                await executor.execute(f"print({i})", tenant="bad-tenant")
            assert e.value.reason == "quarantined"
        assert wire.attempts == attempts_before
        # An innocent tenant sails through the same chaotic stack.
        result = await executor.execute("print(1)", tenant="good-tenant")
        assert result.exit_code == 0
    finally:
        await executor.close()


async def test_kill_switch_holds_under_chaos(tmp_path):
    executor = make_executor(
        tmp_path,
        quotas_enabled=False,
        quota_chip_seconds_per_window=0.0001,
        quota_violations_per_window=1,
        quota_max_concurrent=1,
    )
    SeededWire(executor, CHAOS_SEED + 4, drop_rate=0.5)
    try:
        executor.usage.add("chaos-tenant", violation="oom")
        results = await asyncio.gather(
            *(
                executor.execute(f"print({i})", tenant="chaos-tenant")
                for i in range(20)
            ),
            return_exceptions=True,
        )
        # No quota machinery anywhere: every failure is the wire's own.
        assert not any(isinstance(r, QuotaExceededError) for r in results)
        for r in results:
            if not isinstance(r, Exception):
                assert "quota" not in r.phases
    finally:
        await executor.close()
