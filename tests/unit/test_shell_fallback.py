"""Shell-syntax fallback (executor/shellfb.py).

The reference ran user code under xonsh precisely because LLM-emitted
snippets mix Python and shell lines (/root/reference/executor/server.rs:
197-207, examples/escaping.py exercises quoting through it). The TPU build
dropped xonsh for its ~80 ms startup tax; these tests pin the replacement:
a source transform that keeps pure Python untouched and rewrites shell-ish
lines to subprocess calls.
"""

import os
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
EXECUTOR_DIR = REPO_ROOT / "executor"
sys.path.insert(0, str(EXECUTOR_DIR))
import shellfb  # noqa: E402

sys.path.pop(0)


def test_pure_python_untouched():
    src = "x = 1\nprint(x + 1)\n"
    out, changed = shellfb.transform(src)
    assert not changed
    assert out == src


def test_syntax_shell_line_rewritten():
    src = "print('before')\npip install requests\nprint('after')\n"
    out, changed = shellfb.transform(src)
    assert changed
    assert "__shell__('pip install requests')" in out
    assert out.splitlines()[0] == "print('before')"


def test_bare_ls_rewritten():
    # `ls` is VALID Python (a Name) — must still become a shell call.
    src = "open('f.txt','w').write('x')\nls\n"
    out, changed = shellfb.transform(src)
    assert changed
    assert "__shell__('ls')" in out


def test_defined_name_not_rewritten():
    src = "ls = 5\nls\n"
    out, changed = shellfb.transform(src)
    assert not changed


def test_pipe_chain_of_undefined_names():
    src = "ls | wc\n"
    out, changed = shellfb.transform(src)
    assert changed
    assert "__shell__('ls | wc')" in out


def test_genuine_python_syntax_error_surfaces():
    # A broken Python statement (keyword-led) must NOT silently become shell.
    src = "def broken(:\n    pass\n"
    out, changed = shellfb.transform(src)
    assert not changed
    assert out == src


def test_bang_line():
    src = "!echo hi\n"
    out, changed = shellfb.transform(src)
    assert changed
    assert "__shell__('echo hi')" in out


def test_indented_shell_line():
    src = "for i in range(2):\n    echo hello world\n"
    out, changed = shellfb.transform(src)
    assert changed
    assert "    __shell__('echo hello world')" in out


def test_semicolon_mixed_line_not_swallowed():
    # 'x = 1; ls' — rewriting the whole line would delete the assignment.
    out, changed = shellfb.transform("x = 1; ls\nprint(x)\n")
    assert not changed
    out, changed = shellfb.transform("x = 1; echo hi\nprint(x)\n")
    assert not changed  # SyntaxError path: surface original error


def test_cd_persists_across_lines(tmp_path):
    script = tmp_path / "cd.py"
    (tmp_path / "sub").mkdir()
    script.write_text(
        "mkdir -p sub\ncd sub\necho here > inner.txt\n"
        "import os\nprint(os.path.basename(os.getcwd()))\n"
    )
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "sub"
    assert (tmp_path / "sub" / "inner.txt").exists()  # cd affected the echo


def test_export_persists_to_python(tmp_path):
    script = tmp_path / "exp.py"
    script.write_text(
        "export MY_SETTING=hello\nimport os\nprint(os.environ['MY_SETTING'])\n"
    )
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "hello"


def test_dollar_var_expands_in_shell_lines(tmp_path):
    """$VAR inside a shell-ish line resolves against the persisted exports
    (VERDICT r2 #8): the subshell sees os.environ, which `export` mutates."""
    script = tmp_path / "dollar.py"
    script.write_text(
        "export GREETING=bonjour\n"
        "echo $GREETING-monde > out.txt\n"
        "print(open('out.txt').read().strip())\n"
    )
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "bonjour-monde"


def test_cd_expands_env_vars(tmp_path):
    script = tmp_path / "cdvar.py"
    (tmp_path / "deep").mkdir()
    script.write_text(
        "export TARGET=deep\n"
        "cd $TARGET\n"
        "import os\nprint(os.path.basename(os.getcwd()))\n"
    )
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "deep"


def test_undefined_var_expands_empty_like_sh(tmp_path):
    """sh expands undefined $VARs to empty; the cd/export fast paths must
    agree (a literal '$UNSET' leaking into os.environ would mean the same
    reference behaves differently on an export line vs an echo line)."""
    script = tmp_path / "unset.py"
    script.write_text(
        "export FLAGS=$TOTALLY_UNSET_VAR-x\n"
        "import os\nprint(repr(os.environ['FLAGS']))\n"
    )
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "'-x'"


def test_export_expansion_and_single_quote_literal(tmp_path):
    """Shell-style quoting in export values: double quotes / bare expand
    $VAR, single quotes stay literal."""
    script = tmp_path / "expq.py"
    script.write_text(
        "export BASE=/opt/data\n"
        'export FULL="$BASE/run1"\n'
        "export RAW='$BASE/run1'\n"
        "import os\n"
        "print(os.environ['FULL'])\n"
        "print(os.environ['RAW'])\n"
    )
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.splitlines() == ["/opt/data/run1", "$BASE/run1"]


def test_launcher_cleans_up_transformed_file(tmp_path, monkeypatch):
    monkeypatch.setenv("TMPDIR", str(tmp_path / "tmp"))
    (tmp_path / "tmp").mkdir()
    script = tmp_path / "clean.py"
    script.write_text("echo cleanup-check\n")
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
        env={**os.environ, "TMPDIR": str(tmp_path / "tmp")},
    )
    assert proc.returncode == 0
    assert list((tmp_path / "tmp").glob("shellfb-*")) == []


def test_end_to_end_mixed_script(tmp_path):
    """Mirror of the reference examples/escaping.py intent: mixed snippet
    executes, shell lines really run, Python quoting survives."""
    script = tmp_path / "mixed.py"
    script.write_text(
        "msg = \"it's 'quoted'\"\n"
        "echo shell-ran > marker.txt\n"
        "print(open('marker.txt').read().strip())\n"
        "print(msg)\n"
    )
    run_path = shellfb.prepare(str(script))
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == "shell-ran\nit's 'quoted'\n"
    assert run_path != str(script)  # a transformed sibling was produced
    Path(run_path).unlink(missing_ok=True)


def test_failing_shell_line_does_not_stop_script(tmp_path):
    script = tmp_path / "failing.py"
    script.write_text(
        "definitely-not-a-command --flag\nprint('still here')\n"
    )
    proc = subprocess.run(
        [sys.executable, str(EXECUTOR_DIR / "launch.py"), str(script)],
        capture_output=True,
        text=True,
        cwd=tmp_path,
    )
    assert proc.returncode == 0
    assert "still here" in proc.stdout
    assert "not found" in proc.stderr or "not-a-command" in proc.stderr
