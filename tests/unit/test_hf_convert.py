"""HF checkpoint conversion (models/hf_convert.py): logits parity against
transformers' own forward pass on randomly initialized tiny models — the
gold test that this Llama family is Llama-COMPATIBLE, not just
Llama-shaped (incl. the rotate-half → interleaved RoPE unpermute)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

if os.environ.get("CI"):
    # In CI the parity gate is load-bearing: a missing transformers/torch
    # must turn the job RED, not silently skip the one suite that proves
    # Llama-compatibility (VERDICT r4 #4). GitHub Actions always sets CI=true.
    import torch
    import transformers
else:
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")

from bee_code_interpreter_fs_tpu.models import LlamaConfig, forward, greedy_generate
from bee_code_interpreter_fs_tpu.models.hf_convert import from_hf_state_dict


def _parity(hf_model, cfg, tokens_np, rtol=2e-4, atol=2e-4):
    hf_model.eval()
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens_np)).logits.numpy()
    params = from_hf_state_dict(hf_model.state_dict(), cfg, dtype="float32")
    ours = np.asarray(forward(params, jnp.asarray(tokens_np), cfg))
    np.testing.assert_allclose(ours, hf_logits, rtol=rtol, atol=atol)
    return params


def test_llama_gqa_logits_match_transformers():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, mlp_bias=False, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).float()
    cfg = LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=128, max_seq_len=64, dtype="float32",
    )
    tokens = np.random.default_rng(1).integers(0, 256, (2, 12)).astype(np.int64)
    params = _parity(hf_model, cfg, tokens)

    # The converted tree also drives the fused generation path.
    out = greedy_generate(
        params, jnp.asarray(tokens[:, :4], jnp.int32), cfg, max_new_tokens=4
    )
    assert out.shape == (2, 8)


def test_mixtral_moe_logits_match_transformers():
    hf_cfg = transformers.MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = transformers.MixtralForCausalLM(hf_cfg).float()
    cfg = LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=128, max_seq_len=64, dtype="float32",
        n_experts=4, n_experts_per_token=2,
    )
    tokens = np.random.default_rng(2).integers(0, 256, (2, 10)).astype(np.int64)
    _parity(hf_model, cfg, tokens)


def test_bf16_checkpoint_and_tied_embeddings_convert():
    """Published checkpoints ship bfloat16 and small ones tie lm_head to
    the embedding (absent from safetensors dicts) — both must convert."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).to(torch.bfloat16)
    sd = {k: v for k, v in hf_model.state_dict().items() if k != "lm_head.weight"}
    cfg = LlamaConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32, dtype="float32",
    )
    params = from_hf_state_dict(sd, cfg, dtype="float32")
    np.testing.assert_array_equal(
        np.asarray(params["lm_head"]), np.asarray(params["embed"]).T
    )
    tokens = jnp.zeros((1, 6), jnp.int32)
    logits = forward(params, tokens, cfg)
    assert bool(jnp.isfinite(logits).all())
