"""The proto pin: the vendored /proto contract and the checked-in
``*_pb2.py`` modules must describe the same wire format. The carried PR 5
follow-up ("proto frozen — no protoc in the image") is closed by
scripts/genproto_fallback.py, an in-image descriptor compiler; this gate
keeps the pair from drifting either way — edit a .proto without
regenerating (or hand-edit a pb2) and this fails.
"""

import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
SCRIPTS = REPO_ROOT / "scripts"

sys.path.insert(0, str(SCRIPTS))

from genproto_fallback import (  # noqa: E402
    PROTO_DIR,
    checked_in_descriptor,
    compile_proto,
)

PROTOS = sorted(p.stem for p in PROTO_DIR.glob("*.proto"))


@pytest.mark.parametrize("stem", PROTOS)
def test_checked_in_pb2_matches_proto(stem):
    assert compile_proto(PROTO_DIR / f"{stem}.proto") == checked_in_descriptor(
        stem
    ), (
        f"{stem}.proto and {stem}_pb2.py disagree — run "
        "scripts/genproto.sh to regenerate"
    )


def test_genproto_check_mode_passes():
    """The same gate via the script's own CLI (what genproto.sh runs)."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPTS / "genproto_fallback.py"), "--check"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr


def test_truncation_flags_ride_the_wire():
    """The PR 5 carried fields are real wire surface: serialized by one
    side, parsed by the other, distinct tags from their neighbors."""
    from bee_code_interpreter_fs_tpu.proto import code_interpreter_pb2 as pb2

    response = pb2.ExecuteResponse(
        stdout="partial", stdout_truncated=True, session_seq=4
    )
    back = pb2.ExecuteResponse.FromString(response.SerializeToString())
    assert back.stdout_truncated is True
    assert back.stderr_truncated is False
    assert back.session_seq == 4
    fields = pb2.ExecuteResponse.DESCRIPTOR.fields_by_name
    assert fields["stdout_truncated"].number == 7
    assert fields["stderr_truncated"].number == 8
