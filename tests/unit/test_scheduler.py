"""Scheduler invariants (ISSUE 2), all on a fake clock with zero sleeps:

- FIFO within one tenant+priority flow;
- weighted fairness across tenants under sustained two-way backlog;
- `interactive` beats `batch`, but batch is starvation-free (aging bound);
- deadline-aware admission rejects exactly when the deadline cannot beat
  the estimated queue wait (boundary pinned on both sides);
- per-tenant depth bound sheds with a Retry-After monotonic in the lane's
  total queue depth;
- the pending-kick handshake that lets the executor drop its 30s
  safety-net poll (a turnover landing mid-evaluation is never lost);
- acceptance: under 2-tenant contention (one flooding, one trickling) the
  trickling tenant's p95 queue wait stays bounded and within 2x of its
  uncontended value, and infeasible deadlines are rejected AT ADMISSION.
"""

import pytest

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.errors import (
    DeadlineInfeasibleError,
    QueueDepthError,
)
from bee_code_interpreter_fs_tpu.services.scheduler import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    SandboxScheduler,
)


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_scheduler(clock=None, **config_kwargs) -> SandboxScheduler:
    return SandboxScheduler(Config(**config_kwargs), clock=clock or FakeClock())


def granted_one(scheduler: SandboxScheduler, lane: int = 0):
    """The single currently-granted ticket (the sequential-drain discipline
    used throughout: exactly one holder is awake at a time)."""
    state = scheduler._lanes[lane]
    granted = [t for t in state.tickets if t.granted and not t.done]
    assert len(granted) == 1, f"expected one granted ticket, got {len(granted)}"
    return granted[0]


def drain(scheduler: SandboxScheduler, count: int, lane: int = 0):
    """Complete `count` grants in scheduler order; returns the tickets."""
    order = []
    for _ in range(count):
        ticket = granted_one(scheduler, lane)
        order.append(ticket)
        scheduler.complete(ticket)
    return order


# ------------------------------------------------------------------ ordering


def test_fifo_within_tenant_and_priority():
    scheduler = make_scheduler()
    tickets = [scheduler.submit(0, tenant="t") for _ in range(10)]
    assert drain(scheduler, 10) == tickets


def test_weighted_fairness_under_two_tenant_backlog():
    """Sustained backlog from tenants weighted 1:3 -> grants split ~1:3."""
    scheduler = make_scheduler(
        scheduler_tenant_weights={"light": 1, "heavy": 3},
        scheduler_max_queue_depth=100,
    )
    for _ in range(40):
        scheduler.submit(0, tenant="light")
        scheduler.submit(0, tenant="heavy")
    first = drain(scheduler, 40)
    heavy = sum(1 for t in first if t.tenant == "heavy")
    light = sum(1 for t in first if t.tenant == "light")
    assert heavy + light == 40
    # 3x the weight -> ~3x the grants (+/-1 for the auto-granted head).
    assert 29 <= heavy <= 31


def test_idle_tenant_not_penalized_for_unused_history():
    """WFQ start tags clamp to the lane's virtual time: a tenant that sat
    idle while another consumed 20 grants is NOT owed 20 slots of catch-up
    (and conversely owes nothing) — its first request competes at parity."""
    scheduler = make_scheduler(scheduler_max_queue_depth=100)
    for _ in range(20):
        scheduler.submit(0, tenant="busy")
    drain(scheduler, 10)
    late = scheduler.submit(0, tenant="late")
    # The late arrival lands within ~2 grants, not behind the whole backlog.
    assert late in drain(scheduler, 2)


def test_interactive_preferred_over_batch():
    scheduler = make_scheduler()
    batch = [
        scheduler.submit(0, tenant="t", priority=PRIORITY_BATCH) for _ in range(3)
    ]
    interactive = [
        scheduler.submit(0, tenant="t", priority=PRIORITY_INTERACTIVE)
        for _ in range(3)
    ]
    order = drain(scheduler, 6)
    # batch[0] was auto-granted while the queue was empty (a grant is never
    # revoked); every interactive beats the remaining batch work.
    assert order[0] is batch[0]
    assert order[1:4] == interactive
    assert order[4:] == batch[1:]


def test_batch_starvation_freedom_under_interactive_flood():
    limit = 3
    scheduler = make_scheduler(
        scheduler_batch_starvation_limit=limit, scheduler_max_queue_depth=100
    )
    # Keep one interactive ALWAYS waiting; a lone batch request must still
    # be granted within `limit` interactive grants issued while it waits.
    head = scheduler.submit(0, tenant="t", priority=PRIORITY_INTERACTIVE)
    batch = scheduler.submit(0, tenant="t", priority=PRIORITY_BATCH)
    scheduler.submit(0, tenant="t", priority=PRIORITY_INTERACTIVE)
    scheduler.complete(head)  # granted before batch arrived: not counted
    interactive_grants = 0
    for _ in range(limit + 2):
        scheduler.submit(0, tenant="t", priority=PRIORITY_INTERACTIVE)
        ticket = granted_one(scheduler)
        if ticket is batch:
            break
        assert ticket.priority == PRIORITY_INTERACTIVE
        interactive_grants += 1
        scheduler.complete(ticket)
    else:
        pytest.fail("batch ticket starved past the starvation limit")
    assert interactive_grants <= limit


def test_invalid_tenant_and_priority_are_client_errors():
    scheduler = make_scheduler()
    with pytest.raises(ValueError):
        scheduler.submit(0, tenant="bad tenant!")
    with pytest.raises(ValueError):
        scheduler.submit(0, priority="urgent")
    with pytest.raises(ValueError):
        scheduler.submit(0, deadline=-1.0)
    # Defaults: shared tenant, interactive class.
    ticket = scheduler.submit(0)
    assert ticket.tenant == "shared"
    assert ticket.priority == PRIORITY_INTERACTIVE


# ----------------------------------------------------------------- admission


def test_deadline_reject_vs_met_boundary():
    clock = FakeClock()
    scheduler = make_scheduler(clock)
    # Warm the estimators deterministically: one request that waited 4s,
    # and a 5s spawn observation.
    ticket = scheduler.submit(0, tenant="t")
    clock.advance(4.0)
    scheduler.complete(ticket)
    scheduler.observe_spawn(0, 5.0)
    # Queue now empty: estimate = spawn EWMA alone when the pool is empty.
    assert scheduler.estimated_wait(0, pool_ready=0) == pytest.approx(5.0)
    with pytest.raises(DeadlineInfeasibleError) as rejected:
        scheduler.submit(0, tenant="t", deadline=4.9, pool_ready=0)
    assert rejected.value.retry_after == pytest.approx(5.0)
    # Boundary: a deadline that exactly meets the estimate is admitted,
    # as is anything looser.
    met = scheduler.submit(0, tenant="t", deadline=5.0, pool_ready=0)
    scheduler.complete(met)
    # Warm pool + empty queue -> estimate 0: any deadline is feasible.
    assert scheduler.estimated_wait(0, pool_ready=1) == 0.0
    easy = scheduler.submit(0, tenant="t", deadline=0.01, pool_ready=1)
    scheduler.complete(easy)


def test_depth_shed_retry_after_monotonic_in_queue_depth():
    scheduler = make_scheduler(
        scheduler_max_queue_depth=2, scheduler_min_retry_after=1.0
    )
    for _ in range(2):
        scheduler.submit(0, tenant="flood")
    with pytest.raises(QueueDepthError) as shed_shallow:
        scheduler.submit(0, tenant="flood")
    # Other tenants (each under their own bound) deepen the LANE queue; the
    # flood tenant's next shed must advertise a strictly longer back-off.
    for tenant in ("o1", "o1", "o2", "o2"):
        scheduler.submit(0, tenant=tenant)
    with pytest.raises(QueueDepthError) as shed_deep:
        scheduler.submit(0, tenant="flood")
    assert shed_deep.value.retry_after > shed_shallow.value.retry_after
    # The bound is per tenant: o1's own third request sheds too.
    with pytest.raises(QueueDepthError):
        scheduler.submit(0, tenant="o1")
    # Sheds carry the tenant for operator attribution.
    assert shed_deep.value.tenant == "flood"


# ------------------------------------------------------ grant-token liveness


def test_pending_kick_consumed_by_rearm():
    """A turnover landing while the head is mid-evaluation must not be
    lost: kick() with every ticket granted records a pending kick, and the
    next rearm() consumes it and stays awake (the invariant that replaced
    the executor's 30s safety-net poll)."""
    scheduler = make_scheduler()
    ticket = scheduler.submit(0, tenant="t")
    assert ticket.granted
    scheduler.kick(0)  # lands mid-evaluation: everyone already granted
    scheduler.rearm(ticket)
    assert ticket.granted  # consumed the pending kick: stays awake
    scheduler.rearm(ticket)
    assert not ticket.granted  # no pending signal left: back to sleep
    scheduler.kick(0)
    assert ticket.granted  # explicit turnover grant


def test_abandon_passes_grant_and_keeps_estimator_clean():
    clock = FakeClock()
    scheduler = make_scheduler(clock)
    first = scheduler.submit(0, tenant="t")
    second = scheduler.submit(0, tenant="t")
    clock.advance(100.0)
    scheduler.abandon(first)  # cancelled waiter: no EWMA pollution
    assert second.granted
    state = scheduler._lanes[0]
    assert state.queue_wait_ewma.value is None
    scheduler.complete(second)
    assert state.queue_wait_ewma.value == pytest.approx(100.0)


# ------------------------------------------------- acceptance: 2-tenant load


def _p95(samples):
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]


def _run_trickle_sim(contended: bool, steps: int = 120):
    """One-slot service simulation on a fake clock: each step serves the
    granted head for 1s. The trickling tenant keeps exactly one request
    outstanding (submitting the next as soon as the previous is granted);
    when contended, the flooding tenant keeps a 50-deep backlog. Returns
    the trickler's queue waits (submit -> grant)."""
    clock = FakeClock()
    scheduler = make_scheduler(
        clock,
        scheduler_tenant_weights={"trickle": 2},
        scheduler_max_queue_depth=100,
    )
    tickets = []
    grant_time = {}

    def note_grants():
        for ticket in tickets:
            if ticket.granted and not ticket.done and ticket not in grant_time:
                grant_time[ticket] = clock()

    def submit(tenant):
        ticket = scheduler.submit(0, tenant=tenant)
        tickets.append(ticket)
        note_grants()
        return ticket

    def flood_backlog():
        return sum(
            1 for t in tickets if t.tenant == "flood" and not t.done
        )

    if contended:
        while flood_backlog() < 50:
            submit("flood")
    trickle = submit("trickle")
    waits = []
    for _ in range(steps):
        current = granted_one(scheduler)
        if trickle.granted or trickle.done:
            # The previous trickle request reached service: queue the next
            # one NOW, behind whatever is currently being served — so even
            # uncontended, each request waits out one service slot (a
            # nonzero baseline for the 2x comparison).
            trickle = submit("trickle")
        clock.advance(1.0)  # service time
        if current.tenant == "trickle":
            waits.append(grant_time[current] - current.enqueued_at)
        scheduler.complete(current)
        note_grants()
        if contended:
            while flood_backlog() < 50:
                submit("flood")
    # Drop the very first sample: the opening request of the run is granted
    # at submit (empty lane) and waits 0 by construction in both scenarios.
    return waits[1:], scheduler, clock


def test_contention_trickling_tenant_p95_bounded():
    """ISSUE 2 acceptance: one tenant floods, one trickles — the trickler's
    p95 queue wait is bounded and within 2x of its uncontended value, all
    deterministic on the fake clock."""
    uncontended, _, _ = _run_trickle_sim(contended=False)
    contended, scheduler, clock = _run_trickle_sim(contended=True)
    assert len(uncontended) >= 30 and len(contended) >= 10
    baseline = _p95(uncontended)
    assert baseline > 0.0  # the sim keeps one request always in flight
    assert _p95(contended) <= 2.0 * baseline
    assert max(contended) <= 3.0 * baseline  # bounded outright, not just p95

    # ...and a deadline-infeasible request is rejected AT ADMISSION: the
    # clock does not advance, no acquire budget is spent.
    before = clock()
    with pytest.raises(DeadlineInfeasibleError):
        scheduler.submit(0, tenant="trickle", deadline=0.001, pool_ready=0)
    assert clock() == before


def test_queue_depths_by_lane_tenant_priority():
    scheduler = make_scheduler(scheduler_max_queue_depth=100)
    # Acquire once per tenant first: metric labels are claimed by tenants
    # that actually got slots (junk names read as _overflow).
    scheduler.complete(scheduler.submit(0, tenant="a"))
    scheduler.complete(scheduler.submit(0, tenant="b"))
    scheduler.submit(0, tenant="a")
    scheduler.submit(0, tenant="a")
    scheduler.submit(0, tenant="b", priority=PRIORITY_BATCH)
    scheduler.submit(4, tenant="a")
    assert scheduler.queue_depths() == {
        ("0", "a", "interactive"): 2.0,
        ("0", "b", "batch"): 1.0,
        ("4", "a", "interactive"): 1.0,
    }
    assert scheduler.queued(0) == 3
    assert scheduler.queued(4) == 1
    assert scheduler.queued(8) == 0


# ----------------------------------------------------- review-pass hardening


def test_nan_deadline_rejected_as_client_error():
    scheduler = make_scheduler()
    with pytest.raises(ValueError):
        scheduler.submit(0, deadline=float("nan"))
    # +inf is legal: "no deadline" — admitted and never expires.
    ticket = scheduler.submit(0, deadline=float("inf"))
    scheduler.complete(ticket)


def test_metric_tenant_cardinality_capped():
    scheduler = make_scheduler(
        scheduler_max_metric_tenants=3, scheduler_max_queue_depth=2
    )
    # Label slots are claimed by ACQUIRING tenants only ("shared", the
    # default tenant, pre-claims one; two more fit).
    scheduler.complete(scheduler.submit(0, tenant="a"))
    scheduler.complete(scheduler.submit(0, tenant="b"))
    # A tenant that only queues (or sheds) past the cap reads as overflow…
    scheduler.submit(0, tenant="a")
    scheduler.submit(0, tenant="c")
    depths = scheduler.queue_depths()
    assert ("0", "a", "interactive") in depths  # claimed: keeps its label
    assert ("0", "_overflow", "interactive") in depths
    assert not any(key[1] == "c" for key in depths)
    # …and junk-name sheds cannot squat the cap: "c" never claims a slot,
    # so a tenant that later actually acquires past the cap still overflows
    # consistently while a/b/shared stay dedicated.
    scheduler.complete(scheduler.submit(0, tenant="c"))
    assert "c" not in scheduler._metric_tenants


def test_fruitless_batch_grant_does_not_burn_batch_turn():
    """Aging counts slot handoffs, not grants: a batch grant whose holder
    finds nothing (rearms off a net-zero-capacity kick) must leave the
    starvation counter intact, so batch is selected again on the next
    kick instead of waiting out another full interactive run."""
    limit = 3
    scheduler = make_scheduler(
        scheduler_batch_starvation_limit=limit, scheduler_max_queue_depth=100
    )
    batch = scheduler.submit(0, tenant="t", priority=PRIORITY_BATCH)
    # `limit` interactive slot handoffs while batch waits: counter maxes.
    for _ in range(limit):
        scheduler.complete(scheduler.submit(0, tenant="t"))
    assert scheduler._lanes[0].interactive_run == limit
    scheduler.submit(0, tenant="t")  # interactive contender waiting
    scheduler.rearm(batch)  # batch's granted evaluation found nothing
    scheduler.kick(0)  # net-zero turnover
    # Still batch's turn — the fruitless grant burned nothing.
    assert granted_one(scheduler) is batch
    # Only an actual batch ACQUISITION consumes the turn.
    scheduler.complete(batch)
    assert scheduler._lanes[0].interactive_run == 0


def test_wfq_tag_table_resets_with_busy_period():
    """One `last_finish` entry per tenant ever seen would grow without
    bound under client-minted names; the table resets when the lane
    empties (standard SFQ busy-period semantics)."""
    scheduler = make_scheduler(scheduler_max_queue_depth=100)
    for i in range(50):
        scheduler.complete(scheduler.submit(0, tenant=f"tenant-{i}"))
    assert scheduler._lanes[0].last_finish == {}
