"""Lease registry unit tests (services/leases.py): monotonic generation
minting per scope, fence revocation, the recovering state machine (clean
streak, suspect relapse reset, single re-admission), and the snapshot
surface. All on a fake clock — zero sleeps."""

from bee_code_interpreter_fs_tpu.services.leases import Lease, LeaseRegistry


class FakeClock:
    def __init__(self) -> None:
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


def make_registry(streak: int = 3) -> LeaseRegistry:
    return LeaseRegistry(readmit_streak=streak, clock=FakeClock())


def test_mint_is_monotonic_per_scope():
    registry = make_registry()
    a = registry.mint("lane-0", "sb-1")
    b = registry.mint("lane-0", "sb-2")
    other = registry.mint("lane-4", "sb-3")
    assert (a.generation, b.generation) == (1, 2)
    assert other.generation == 1  # scopes are independent counters
    assert a.wire_token == "lane-0:1"
    assert b.wire_token != a.wire_token


def test_fence_revokes_and_marks_scope_recovering():
    registry = make_registry(streak=2)
    lease = registry.mint("lane-0", "sb-1")
    assert not registry.revoked(lease)
    assert not registry.recovering("lane-0")
    registry.fence(lease, reason="attach_stalled")
    assert registry.revoked(lease)
    assert lease.revoke_reason == "attach_stalled"
    assert registry.recovering("lane-0")
    assert registry.fences_total == 1
    # Idempotent: re-fencing (the probe re-asserts every cycle while the
    # dispose is in flight) changes nothing.
    registry.fence(lease)
    assert registry.fences_total == 1
    # The successor's mint is strictly newer than the fenced generation.
    successor = registry.mint("lane-0", "sb-2")
    assert successor.generation > lease.generation
    assert not registry.revoked(successor)


def test_readmission_needs_consecutive_clean_probes():
    registry = make_registry(streak=3)
    lease = registry.mint("lane-0", "sb-1")
    registry.fence(lease)
    assert registry.note_probe("lane-0", clean=True) is False
    assert registry.recovery_progress("lane-0") == (1, 3)
    assert registry.note_probe("lane-0", clean=True) is False
    # Relapse resets the streak — CONSECUTIVE is the contract.
    assert registry.note_probe("lane-0", clean=False) is False
    assert registry.recovery_progress("lane-0") == (0, 3)
    assert registry.note_probe("lane-0", clean=True) is False
    assert registry.note_probe("lane-0", clean=True) is False
    # The completing probe re-admits exactly once.
    assert registry.note_probe("lane-0", clean=True) is True
    assert not registry.recovering("lane-0")
    assert registry.readmissions_total == 1
    # Further notes on a non-recovering scope are no-ops.
    assert registry.note_probe("lane-0", clean=True) is False


def test_revoked_handles_none_and_plain_leases():
    registry = make_registry()
    assert registry.revoked(None) is False
    lease = Lease(scope="s", generation=1)
    assert registry.revoked(lease) is False
    lease.revoked = True
    assert registry.revoked(lease) is True


def test_snapshot_shape():
    registry = make_registry(streak=2)
    lease = registry.mint("lane-0", "sb-1")
    registry.fence(lease, reason="device_op_stalled")
    registry.note_probe("lane-0", clean=True)
    snap = registry.snapshot()
    assert snap["readmit_streak"] == 2
    assert snap["fences_total"] == 1
    assert snap["readmissions_total"] == 0
    assert snap["generations"] == {"lane-0": 1}
    row = snap["recovering"]["lane-0"]
    assert row["streak"] == 1 and row["need"] == 2
    assert row["reason"] == "device_op_stalled"
