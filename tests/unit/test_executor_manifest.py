"""Workspace-manifest protocol tests against the real C++ executor binary:
stream-hashed uploads, GET /workspace-manifest (lazy rehash), conditional
PUT (If-None-Match -> 304), per-file sha256 + deleted reporting on /execute,
manifest wipe on /reset, and the APP_WORKSPACE_MANIFEST=0 legacy mode that
emulates an old binary for the control plane's fallback path.
"""

import hashlib
import os
import re
import subprocess
import time
from pathlib import Path

import httpx
import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
EXECUTOR_DIR = REPO_ROOT / "executor"
BINARY = Path(
    os.environ.get("TEST_EXECUTOR_BINARY", EXECUTOR_DIR / "build" / "executor-server")
)


def _spawn(tmp_root: Path, **env_extra):
    if "TEST_EXECUTOR_BINARY" not in os.environ and not BINARY.exists():
        subprocess.run(
            ["make", "-C", str(EXECUTOR_DIR)], check=True, capture_output=True
        )
    ws = tmp_root / "ws"
    rp = tmp_root / "rp"
    ws.mkdir()
    rp.mkdir()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "APP_LISTEN_ADDR": "127.0.0.1:0",
            "APP_WORKSPACE": str(ws),
            "APP_RUNTIME_PACKAGES": str(rp),
            "APP_WARM_IMPORT_JAX": "0",
            "APP_RUNNER_INTERRUPT_GRACE_S": "2",
        }
    )
    env.update(env_extra)
    proc = subprocess.Popen(
        [str(BINARY)], env=env, stdout=subprocess.PIPE, stderr=None
    )
    line = proc.stdout.readline().decode()
    port = int(re.search(r"port=(\d+)", line).group(1))
    client = httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=30.0)
    for _ in range(200):
        try:
            if client.get("/healthz").json().get("warm"):
                break
        except httpx.TransportError:
            pass
        time.sleep(0.1)
    return proc, client, ws


@pytest.fixture(scope="module")
def executor(tmp_path_factory):
    proc, client, ws = _spawn(tmp_path_factory.mktemp("manifest"))
    yield client, ws
    client.close()
    proc.kill()
    proc.wait()


@pytest.fixture(scope="module")
def legacy_executor(tmp_path_factory):
    """The same binary in legacy wire mode — stands in for an old executor
    build when testing the control plane's full-transfer fallback."""
    proc, client, ws = _spawn(
        tmp_path_factory.mktemp("legacy"), APP_WORKSPACE_MANIFEST="0"
    )
    yield client, ws
    client.close()
    proc.kill()
    proc.wait()


def sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def execute(client, source, **kwargs):
    resp = client.post("/execute", json={"source_code": source, **kwargs})
    assert resp.status_code == 200, resp.text
    return resp.json()


def test_upload_returns_streamed_hash(executor):
    client, _ = executor
    body = b"manifest payload"
    resp = client.put("/workspace/m/one.txt", content=body)
    assert resp.status_code == 200
    assert resp.json()["sha256"] == sha(body)


def test_manifest_reflects_uploads(executor):
    client, _ = executor
    body = b"second file"
    client.put("/workspace/m/two.txt", content=body)
    manifest = client.get("/workspace-manifest").json()["files"]
    assert manifest["m/two.txt"] == sha(body)
    assert manifest["m/one.txt"] == sha(b"manifest payload")


def test_conditional_put_304_skips_body(executor):
    client, ws = executor
    body = b"conditional content"
    client.put("/workspace/cond.txt", content=body)
    before_mtime = (ws / "cond.txt").stat().st_mtime_ns
    resp = client.put(
        "/workspace/cond.txt",
        content=body,
        headers={"If-None-Match": sha(body)},
    )
    assert resp.status_code == 304
    assert resp.content == b""
    # The 304 proved no write happened: the file's mtime is untouched.
    assert (ws / "cond.txt").stat().st_mtime_ns == before_mtime


def test_conditional_put_mismatch_writes_normally(executor):
    client, ws = executor
    new_body = b"conditional content v2"
    resp = client.put(
        "/workspace/cond.txt",
        content=new_body,
        headers={"If-None-Match": sha(new_body)},
    )
    # The manifest held v1's sha, so the claim mismatched: a normal write.
    assert resp.status_code == 200
    assert resp.json()["sha256"] == sha(new_body)
    assert (ws / "cond.txt").read_bytes() == new_body


def test_conditional_put_stale_disk_rewrites(executor):
    """A manifest hit alone is not enough: when the file on disk no longer
    matches the cached signature (user code touched it out of band), the
    conditional PUT must fall through to a write, not 304 against bytes the
    workspace lost."""
    client, ws = executor
    body = b"stale-check content"
    client.put("/workspace/stale.txt", content=body)
    (ws / "stale.txt").write_bytes(b"mutated behind the manifest")
    resp = client.put(
        "/workspace/stale.txt", content=body, headers={"If-None-Match": sha(body)}
    )
    assert resp.status_code == 200
    assert (ws / "stale.txt").read_bytes() == body


def test_execute_reports_hashes_and_deletions(executor):
    client, _ = executor
    client.put("/workspace/doomed.txt", content=b"to be deleted")
    result = execute(
        client,
        "import os\nopen('fresh.txt', 'w').write('fresh')\nos.remove('doomed.txt')",
    )
    by_path = {
        entry["path"]: entry.get("sha256") for entry in result["files"]
    }
    assert by_path["fresh.txt"] == sha(b"fresh")
    assert "doomed.txt" in result["deleted"]
    manifest = client.get("/workspace-manifest").json()["files"]
    assert manifest["fresh.txt"] == sha(b"fresh")
    assert "doomed.txt" not in manifest


def test_manifest_lazy_rehash_on_out_of_band_change(executor):
    """GET /workspace-manifest must reconcile with the disk: a file mutated
    without an upload (size/mtime signature changed) rehashes; everything
    else keeps its cached sha without re-reading bytes."""
    client, ws = executor
    client.put("/workspace/lazy.txt", content=b"original")
    (ws / "lazy.txt").write_bytes(b"mutated out of band")
    manifest = client.get("/workspace-manifest").json()["files"]
    assert manifest["lazy.txt"] == sha(b"mutated out of band")


def test_reset_wipes_manifest(executor):
    client, _ = executor
    client.put("/workspace/resetme.txt", content=b"x")
    assert client.post("/reset").status_code == 200
    assert client.get("/workspace-manifest").json()["files"] == {}
    # A conditional PUT against the wiped generation must re-upload.
    resp = client.put(
        "/workspace/resetme.txt", content=b"x", headers={"If-None-Match": sha(b"x")}
    )
    assert resp.status_code == 200


# ------------------------------------------------------------- legacy mode


def test_legacy_mode_plain_files_and_no_manifest_route(legacy_executor):
    client, _ = legacy_executor
    resp = client.put("/workspace/old.txt", content=b"old-school")
    assert resp.status_code == 200
    assert "sha256" not in resp.json()
    assert client.get("/workspace-manifest").status_code == 404
    result = execute(client, "open('made.txt', 'w').write('y')")
    assert result["files"] == ["made.txt"]
    assert "deleted" not in result


def test_legacy_mode_ignores_if_none_match(legacy_executor):
    client, ws = legacy_executor
    body = b"legacy conditional"
    client.put("/workspace/legacy-cond.txt", content=body)
    resp = client.put(
        "/workspace/legacy-cond.txt",
        content=body,
        headers={"If-None-Match": sha(body)},
    )
    # An old binary knows nothing of conditional uploads: plain 200 write.
    assert resp.status_code == 200
    assert (ws / "legacy-cond.txt").read_bytes() == body
