"""Retryable-error mapping across both API surfaces (ISSUE 1 satellite).

Pins the contract operators and clients depend on:
- `CapacityTimeoutError` (capacity pressure, service healthy) → HTTP 429 /
  gRPC RESOURCE_EXHAUSTED on all three executing servicer methods;
- `CircuitOpenError` (degraded service, backend down) → HTTP 503 +
  ``Retry-After`` / gRPC UNAVAILABLE — deliberately DISTINCT from the 429
  path so dashboards and clients can tell "you sent too much" from
  "the service is sick";
- `/healthz` flips 200→503 with the lane-0 breaker and back.
"""

import asyncio
import json

import grpc
import pytest
from aiohttp.test_utils import TestClient, TestServer
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.proto import code_interpreter_pb2 as pb2
from bee_code_interpreter_fs_tpu.services.circuit_breaker import BreakerBoard
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CapacityTimeoutError,
    CircuitOpenError,
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.grpc_servicers.code_interpreter_servicer import (
    CodeInterpreterServicer,
)
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage

CAPACITY_ERROR = CapacityTimeoutError(
    "no lane-0 sandbox slot freed within 300s; retry later"
)
CIRCUIT_ERROR = CircuitOpenError(
    "lane-0 spawn circuit is open", lane=0, retry_after=17.2
)

TOOL_SOURCE = "def add(a: int, b: int) -> int:\n    return a + b\n"


class FakeClock:
    def __init__(self) -> None:
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


def make_stack(tmp_path, error=None, clock=None):
    """CodeExecutor + CustomToolExecutor with every executing entrypoint
    stubbed to raise `error` (None = leave real paths in place)."""
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
    )
    breakers = BreakerBoard(
        failure_threshold=1, cooldown=30.0, clock=clock or FakeClock()
    )
    executor = CodeExecutor(
        FakeBackend(), Storage(config.file_storage_path), config,
        breakers=breakers,
    )
    tools = CustomToolExecutor(executor)
    if error is not None:
        async def raise_error(*args, **kwargs):
            raise error

        async def raise_error_stream(*args, **kwargs):
            raise error
            yield  # pragma: no cover — makes this an async generator

        executor.execute = raise_error
        executor.execute_stream = raise_error_stream
        tools.execute_with_result = raise_error
    return executor, tools


# ----------------------------------------------------------------- gRPC side


class AbortRaised(Exception):
    def __init__(self, code: grpc.StatusCode, details: str) -> None:
        super().__init__(details)
        self.code = code
        self.details = details


class FakeContext:
    """Minimal grpc.aio context: abort raises (as the real one does)."""

    def __init__(self, metadata=()):
        self.metadata = tuple(metadata)

    def invocation_metadata(self):
        return self.metadata

    async def abort(self, code: grpc.StatusCode, details: str = "") -> None:
        raise AbortRaised(code, details)


async def grpc_status_for(servicer: CodeInterpreterServicer, method: str):
    context = FakeContext()
    if method == "Execute":
        call = servicer.Execute(pb2.ExecuteRequest(source_code="x"), context)
    elif method == "ExecuteStream":
        async def drain():
            async for _ in servicer.ExecuteStream(
                pb2.ExecuteRequest(source_code="x"), context
            ):
                pass

        call = drain()
    elif method == "ExecuteCustomTool":
        call = servicer.ExecuteCustomTool(
            pb2.ExecuteCustomToolRequest(
                tool_source_code=TOOL_SOURCE, tool_input_json="{}"
            ),
            context,
        )
    else:  # pragma: no cover — test bug
        raise AssertionError(method)
    with pytest.raises(AbortRaised) as exc_info:
        await call
    return exc_info.value


@pytest.mark.parametrize(
    "method", ["Execute", "ExecuteStream", "ExecuteCustomTool"]
)
async def test_capacity_timeout_maps_to_resource_exhausted(tmp_path, method):
    executor, tools = make_stack(tmp_path, CAPACITY_ERROR)
    try:
        servicer = CodeInterpreterServicer(executor, tools)
        abort = await grpc_status_for(servicer, method)
        assert abort.code == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert "retry later" in abort.details
    finally:
        await executor.close()


@pytest.mark.parametrize(
    "method", ["Execute", "ExecuteStream", "ExecuteCustomTool"]
)
async def test_circuit_open_maps_to_unavailable(tmp_path, method):
    executor, tools = make_stack(tmp_path, CIRCUIT_ERROR)
    try:
        servicer = CodeInterpreterServicer(executor, tools)
        abort = await grpc_status_for(servicer, method)
        assert abort.code == grpc.StatusCode.UNAVAILABLE
        assert "circuit is open" in abort.details
    finally:
        await executor.close()


# ----------------------------------------------------------------- HTTP side


async def http_client_for(executor, tools):
    app = create_http_app(executor, tools, executor.storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


EXECUTE_BODY = {"source_code": "print('hi')"}
TOOL_BODY = {"tool_source_code": TOOL_SOURCE, "tool_input_json": "{}"}


@pytest.mark.parametrize(
    "path,body",
    [
        ("/v1/execute", EXECUTE_BODY),
        ("/v1/execute/stream", EXECUTE_BODY),
        ("/v1/execute-custom-tool", TOOL_BODY),
    ],
)
async def test_capacity_timeout_maps_to_http_429(tmp_path, path, body):
    executor, tools = make_stack(tmp_path, CAPACITY_ERROR)
    client = await http_client_for(executor, tools)
    try:
        resp = await client.post(path, json=body)
        assert resp.status == 429
        assert "retry later" in (await resp.json())["error"]
    finally:
        await client.close()
        await executor.close()


@pytest.mark.parametrize(
    "path,body",
    [
        ("/v1/execute", EXECUTE_BODY),
        ("/v1/execute/stream", EXECUTE_BODY),
        ("/v1/execute-custom-tool", TOOL_BODY),
    ],
)
async def test_circuit_open_sheds_with_http_503(tmp_path, path, body):
    executor, tools = make_stack(tmp_path, CIRCUIT_ERROR)
    client = await http_client_for(executor, tools)
    try:
        resp = await client.post(path, json=body)
        assert resp.status == 503
        # Retry-After carries the breaker's cooldown remainder, rounded up.
        assert resp.headers["Retry-After"] == "18"
        payload = await resp.json()
        assert payload["degraded"] is True
        assert "circuit is open" in payload["error"]
    finally:
        await client.close()
        await executor.close()


async def test_healthz_flips_with_breaker(tmp_path):
    clock = FakeClock()
    executor, tools = make_stack(tmp_path, clock=clock)
    client = await http_client_for(executor, tools)
    try:
        resp = await client.get("/healthz")
        assert resp.status == 200
        assert (await resp.json())["status"] == "ok"

        executor.breakers.lane(0).record_failure()  # threshold=1 → open
        resp = await client.get("/healthz")
        assert resp.status == 503
        assert resp.headers["Retry-After"] == "30"
        assert (await resp.json())["status"] == "degraded"

        # Cooldown elapsed (half-open): probes must be able to reach the
        # service, so health reports OK again.
        clock.advance(30.1)
        resp = await client.get("/healthz")
        assert resp.status == 200
    finally:
        await client.close()
        await executor.close()


async def test_mid_stream_circuit_error_emits_error_line(tmp_path):
    """A breaker rejection AFTER streaming started cannot become a 503
    (headers are gone): the stream must end with an {"error": ...} line."""
    executor, tools = make_stack(tmp_path)

    async def half_stream(*args, **kwargs):
        yield {"stream": "stdout", "data": "partial"}
        raise CIRCUIT_ERROR

    executor.execute_stream = half_stream
    client = await http_client_for(executor, tools)
    try:
        resp = await client.post("/v1/execute/stream", json=EXECUTE_BODY)
        assert resp.status == 200  # headers were already committed
        lines = [
            json.loads(line)
            for line in (await resp.text()).splitlines()
            if line.strip()
        ]
        assert lines[0] == {"stream": "stdout", "data": "partial"}
        assert "circuit is open" in lines[-1]["error"]
    finally:
        await client.close()
        await executor.close()
