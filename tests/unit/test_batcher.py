"""Unit tests for the batching window (services/batcher.py): compatibility
keying, the bounded window (fake timer — zero sleeps), full-batch immediate
dispatch, partial-batch expiry, and promise lifecycle on close.
"""

import asyncio

import pytest

from bee_code_interpreter_fs_tpu.services.batcher import (
    Batcher,
    BatchJob,
    BatchKey,
    freeze_mapping,
)


class ManualTimer:
    """Injectable window timer: captures callbacks, fires on demand — the
    fake clock for window-expiry tests."""

    def __init__(self):
        self.scheduled = []  # (delay, callback, handle)

    def __call__(self, delay, callback):
        handle = _Handle()
        self.scheduled.append((delay, callback, handle))
        return handle

    def fire_all(self):
        for _delay, callback, handle in list(self.scheduled):
            if not handle.cancelled:
                callback()
        self.scheduled.clear()


class _Handle:
    def __init__(self):
        self.cancelled = False

    def cancel(self):
        self.cancelled = True


KEY = BatchKey(lane=8, tenant="t1", priority="interactive")


def job(source="print(1)", timeout=30.0):
    return BatchJob(source_code=source, timeout=timeout)


def make(dispatched, *, max_jobs=4, window_s=0.01, timer=None):
    async def dispatch(key, jobs):
        dispatched.append((key, jobs))
        for j in jobs:
            j.resolve("ok")

    return Batcher(
        window_s=window_s, max_jobs=max_jobs, dispatch=dispatch, timer=timer
    )


async def test_full_batch_dispatches_immediately_without_window():
    dispatched = []
    timer = ManualTimer()
    batcher = make(dispatched, max_jobs=3, timer=timer)
    jobs = [job(f"j{i}") for i in range(3)]
    for j in jobs:
        await batcher.submit(KEY, j)
    results = await asyncio.gather(*(j.future for j in jobs))
    assert results == ["ok"] * 3
    # ONE dispatch carried all three jobs; the armed window was cancelled.
    assert len(dispatched) == 1
    assert dispatched[0][1] == jobs
    assert all(h.cancelled for _, _, h in timer.scheduled)


async def test_window_expiry_flushes_partial_batch():
    """Fake-clock window expiry with a partial batch: two of four slots
    filled when the timer fires — both jobs dispatch together."""
    dispatched = []
    timer = ManualTimer()
    batcher = make(dispatched, max_jobs=4, timer=timer)
    a, b = job("a"), job("b")
    await batcher.submit(KEY, a)
    await batcher.submit(KEY, b)
    assert dispatched == []  # window still open, nobody dispatched
    assert batcher.pending_jobs(KEY) == 2
    timer.fire_all()
    assert await a.future == "ok"
    assert await b.future == "ok"
    assert len(dispatched) == 1
    assert [j.source_code for j in dispatched[0][1]] == ["a", "b"]
    assert batcher.pending_jobs(KEY) == 0


async def test_incompatible_keys_never_share_a_dispatch():
    """Tenant isolation by construction: different tenants (or lanes, or
    env) are different keys — their jobs never ride one dispatch."""
    dispatched = []
    timer = ManualTimer()
    batcher = make(dispatched, max_jobs=8, timer=timer)
    k1 = BatchKey(lane=8, tenant="alice", priority="interactive")
    k2 = BatchKey(lane=8, tenant="bob", priority="interactive")
    k3 = BatchKey(
        lane=8, tenant="alice", priority="interactive",
        env=freeze_mapping({"X": "1"}),
    )
    jobs = {k: [job(), job()] for k in (k1, k2, k3)}
    for k, js in jobs.items():
        for j in js:
            await batcher.submit(k, j)
    timer.fire_all()
    await asyncio.gather(*(j.future for js in jobs.values() for j in js))
    assert len(dispatched) == 3
    seen = {id(j) for _key, js in dispatched for j in js}
    assert len(seen) == 6
    for key, js in dispatched:
        assert {id(j) for j in js} <= {id(j) for j in jobs[key]}


async def test_one_timer_per_window_not_per_job():
    timer = ManualTimer()
    batcher = make([], max_jobs=8, timer=timer)
    for _ in range(3):
        await batcher.submit(KEY, job())
    assert len(timer.scheduled) == 1  # armed by the FIRST job only


async def test_dispatch_exception_fails_stragglers():
    async def dispatch(key, jobs):
        jobs[0].resolve("ok")
        raise RuntimeError("dispatcher bug")

    batcher = Batcher(window_s=0.0, max_jobs=2, dispatch=dispatch)
    a, b = job("a"), job("b")
    await batcher.submit(KEY, a)
    await batcher.submit(KEY, b)
    assert await a.future == "ok"
    with pytest.raises(RuntimeError, match="dispatcher bug"):
        await b.future


async def test_close_fails_pending_and_rejects_new():
    timer = ManualTimer()
    batcher = make([], max_jobs=8, timer=timer)
    parked = job()
    await batcher.submit(KEY, parked)
    await batcher.close()
    with pytest.raises(RuntimeError, match="shutting down"):
        await parked.future
    with pytest.raises(RuntimeError, match="closed"):
        await batcher.submit(KEY, job())


async def test_flush_stats_count_batches_and_jobs():
    dispatched = []
    timer = ManualTimer()
    batcher = make(dispatched, max_jobs=2, timer=timer)
    for _ in range(4):
        await batcher.submit(KEY, job())
    await asyncio.sleep(0)
    assert batcher.dispatched_batches == 2
    assert batcher.dispatched_jobs == 4
