"""Per-tenant quota enforcement (services/quotas.py): sliding-window
chip-second budgets over the usage ledger, request-rate/concurrency caps,
repeat-offender quarantine with exponential decay, policy-file hot reload,
journal window restore, the admission wiring in CodeExecutor (denial before
any scheduler/pool machinery), the HTTP/gRPC surfaces, and the kill
switch's byte-for-byte restoration of pre-quota behavior.

Every window test runs on a FAKE wall clock (the enforcer's injectable
walltime), so budget refills and quarantine sentences are asserted without
a single sleep.
"""

import asyncio
import json
import os

import grpc
import pytest
from aiohttp.test_utils import TestClient, TestServer
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.proto import code_interpreter_pb2 as pb2
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CodeExecutor,
    QuotaExceededError,
)
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.grpc_servicers.code_interpreter_servicer import (  # noqa: E501
    CodeInterpreterServicer,
)
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.quotas import (
    DENIAL_REASONS,
    QuotaEnforcer,
    QuotaPolicy,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage
from bee_code_interpreter_fs_tpu.services.usage import UsageLedger
from bee_code_interpreter_fs_tpu.utils.metrics import ExecutorMetrics


class FakeClock:
    def __init__(self, start: float = 1_000_000.0) -> None:
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


def make_config(tmp_path, **kwargs):
    kwargs.setdefault("file_storage_path", str(tmp_path / "storage"))
    kwargs.setdefault("executor_pod_queue_target_length", 1)
    return Config(**kwargs)


def make_enforcer(tmp_path, clock=None, **kwargs):
    clock = clock or FakeClock()
    config = make_config(tmp_path, **kwargs)
    ledger = UsageLedger(config, walltime=clock)
    enforcer = QuotaEnforcer(config, usage=ledger, walltime=clock)
    return enforcer, ledger, clock


# ------------------------------------------------------------- window budgets


def test_budget_denial_and_window_refill(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_chip_seconds_per_window=10.0,
        quota_window_seconds=100.0,
    )
    # Within budget: admitted, remaining reported.
    v = enforcer.admit("t-a")
    assert v is not None and v.remaining_chip_seconds == 10.0
    enforcer.release(v)
    ledger.add("t-a", chip_seconds=6.0)
    clock.advance(1.0)
    v = enforcer.admit("t-a")
    assert v.remaining_chip_seconds == pytest.approx(4.0)
    enforcer.release(v)
    # Over budget: denied with the typed reason and a refill-derived
    # Retry-After (the consumption ages out of the window, not a guess).
    ledger.add("t-a", chip_seconds=6.0)
    clock.advance(1.0)
    with pytest.raises(QuotaExceededError) as e:
        enforcer.admit("t-a")
    assert e.value.reason == "chip_seconds"
    assert e.value.remaining_chip_seconds == 0.0
    assert e.value.limit_chip_seconds == 10.0
    assert 0 < e.value.retry_after <= 100.0
    # Waiting out the advertised Retry-After re-admits (the acceptance
    # criterion's "re-admitted after the window refills").
    clock.advance(e.value.retry_after + 0.1)
    v = enforcer.admit("t-a")
    assert v is not None
    enforcer.release(v)


def test_budget_isolation_between_tenants(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_chip_seconds_per_window=5.0,
        quota_window_seconds=60.0,
    )
    ledger.add("t-a", chip_seconds=50.0)
    enforcer.admit("t-a")  # first admit seeds the baseline sample
    ledger.add("t-a", chip_seconds=50.0)
    clock.advance(1.0)
    with pytest.raises(QuotaExceededError):
        enforcer.admit("t-a")
    # t-b is untouched by t-a's exhaustion.
    v = enforcer.admit("t-b")
    assert v is not None
    enforcer.release(v)


def test_zero_caps_enforce_nothing(tmp_path):
    enforcer, ledger, clock = make_enforcer(tmp_path)
    ledger.add("t-a", chip_seconds=1e9)
    for _ in range(50):
        v = enforcer.admit("t-a")
        assert v is not None and v.limit_chip_seconds is None
        enforcer.release(v)


# ------------------------------------------------------------ rate/concurrency


def test_request_rate_cap(tmp_path):
    enforcer, _, clock = make_enforcer(
        tmp_path,
        quota_requests_per_window=3,
        quota_window_seconds=60.0,
    )
    for _ in range(3):
        enforcer.release(enforcer.admit("t-a"))
        clock.advance(1.0)
    with pytest.raises(QuotaExceededError) as e:
        enforcer.admit("t-a")
    assert e.value.reason == "request_rate"
    # The oldest admission ages out of the window -> re-admitted.
    clock.advance(e.value.retry_after + 0.1)
    assert enforcer.admit("t-a") is not None


def test_concurrency_cap_and_idempotent_release(tmp_path):
    enforcer, _, clock = make_enforcer(tmp_path, quota_max_concurrent=2)
    a = enforcer.admit("t-a")
    b = enforcer.admit("t-a")
    with pytest.raises(QuotaExceededError) as e:
        enforcer.admit("t-a")
    assert e.value.reason == "concurrency"
    enforcer.release(a)
    enforcer.release(a)  # double release must not free a second slot
    c = enforcer.admit("t-a")
    assert c is not None
    with pytest.raises(QuotaExceededError):
        enforcer.admit("t-a")
    enforcer.release(b)
    enforcer.release(c)


# ------------------------------------------------------------------ quarantine


def test_violation_storm_quarantines_and_decays(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_violations_per_window=3,
        quota_window_seconds=100.0,
        quota_quarantine_base_seconds=10.0,
        quota_quarantine_max_seconds=1000.0,
        quota_quarantine_decay_seconds=50.0,
    )
    enforcer.release(enforcer.admit("t-bad"))  # baseline sample
    for _ in range(3):
        ledger.add("t-bad", violation="oom", requests=1,
                   outcome="limit_violation")
    clock.advance(1.0)
    # Storm crosses the threshold: quarantined with the base sentence.
    with pytest.raises(QuotaExceededError) as e1:
        enforcer.admit("t-bad")
    assert e1.value.reason == "quarantined"
    assert e1.value.retry_after == pytest.approx(10.0)
    # Still quarantined mid-sentence.
    clock.advance(5.0)
    with pytest.raises(QuotaExceededError) as e2:
        enforcer.admit("t-bad")
    assert e2.value.reason == "quarantined"
    assert e2.value.retry_after == pytest.approx(5.0)
    # Sentence served; the spent violations do NOT re-quarantine (the
    # violation floor) — the tenant decays back in.
    clock.advance(6.0)
    v = enforcer.admit("t-bad")
    assert v is not None
    enforcer.release(v)
    # A SECOND storm doubles the sentence (exponential episode ladder).
    for _ in range(3):
        ledger.add("t-bad", violation="nproc")
    clock.advance(1.0)
    with pytest.raises(QuotaExceededError) as e3:
        enforcer.admit("t-bad")
    assert e3.value.retry_after == pytest.approx(20.0)
    # Long clean stretch decays the ladder: the NEXT storm is back to the
    # base sentence.
    clock.advance(20.0 + 2 * 50.0 + 1.0)
    enforcer.release(enforcer.admit("t-bad"))
    for _ in range(3):
        ledger.add("t-bad", violation="cpu_time")
    clock.advance(1.0)
    with pytest.raises(QuotaExceededError) as e4:
        enforcer.admit("t-bad")
    assert e4.value.retry_after == pytest.approx(10.0)


def test_quarantine_sentence_caps_at_max(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_violations_per_window=1,
        quota_window_seconds=100.0,
        quota_quarantine_base_seconds=10.0,
        quota_quarantine_max_seconds=25.0,
        quota_quarantine_decay_seconds=10000.0,
    )
    enforcer.release(enforcer.admit("t-bad"))
    sentences = []
    for _ in range(4):
        ledger.add("t-bad", violation="oom")
        clock.advance(1.0)
        with pytest.raises(QuotaExceededError) as e:
            enforcer.admit("t-bad")
        sentences.append(e.value.retry_after)
        clock.advance(e.value.retry_after + 0.1)
    assert sentences == [
        pytest.approx(10.0),
        pytest.approx(20.0),
        pytest.approx(25.0),  # capped
        pytest.approx(25.0),
    ]


# ----------------------------------------------------------------- policy file


def test_policy_file_overrides_and_hot_reload(tmp_path):
    policy_path = tmp_path / "policy.json"
    policy_path.write_text(
        json.dumps(
            {
                "default": {"chip_seconds_per_window": 100},
                "tenants": {"vip": {"chip_seconds_per_window": 1000}},
            }
        )
    )
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_window_seconds=60.0,
        quota_policy_file=str(policy_path),
        quota_policy_reload_seconds=1.0,
    )
    assert enforcer.default_policy.chip_seconds_per_window == 100.0
    assert enforcer.policy_for("vip").chip_seconds_per_window == 1000.0
    assert enforcer.policy_for("other").chip_seconds_per_window == 100.0
    # Hot reload: rewrite, bump mtime, advance past the reload throttle.
    policy_path.write_text(
        json.dumps({"default": {"chip_seconds_per_window": 7}})
    )
    os.utime(policy_path, (clock.now + 60, clock.now + 60))
    clock.advance(2.0)
    enforcer.release(enforcer.admit("other"))
    assert enforcer.default_policy.chip_seconds_per_window == 7.0
    assert enforcer.policy_loads == 2


def test_policy_reload_is_idempotent_in_file_content(tmp_path):
    """Every reload layers over the CONFIG baseline, not the previous
    load: a key REMOVED from the file reverts to the config default
    instead of silently keeping its old value on long-running instances
    (which would split a fleet into two policies from one file)."""
    policy_path = tmp_path / "policy.json"
    policy_path.write_text(
        json.dumps(
            {"default": {"max_concurrent": 5, "chip_seconds_per_window": 9}}
        )
    )
    enforcer, _, clock = make_enforcer(
        tmp_path,
        quota_policy_file=str(policy_path),
        quota_policy_reload_seconds=1.0,
    )
    assert enforcer.default_policy.max_concurrent == 5
    # Rewrite WITHOUT max_concurrent: it must revert to the config
    # default (0 = off), exactly what a restarted instance would compute.
    policy_path.write_text(
        json.dumps({"default": {"chip_seconds_per_window": 9}})
    )
    os.utime(policy_path, (clock.now + 60, clock.now + 60))
    clock.advance(2.0)
    enforcer.release(enforcer.admit("t"))
    assert enforcer.default_policy.max_concurrent == 0
    assert enforcer.default_policy.chip_seconds_per_window == 9.0


def test_whitelisted_past_cap_tenant_paces_on_its_own_budget(tmp_path):
    """A tenant whitelisted BY NAME past the ledger cardinality cap is
    admitted under its named override; the post-run pacing refresh must
    use that same budget, not re-resolve the shared `_overflow` label's
    policy (which would report a nearly-full budget as exhausted)."""
    policy_path = tmp_path / "policy.json"
    policy_path.write_text(
        json.dumps({"tenants": {"vip": {"chip_seconds_per_window": 1000}}})
    )
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        usage_max_tenants=1,
        quota_policy_file=str(policy_path),
        quota_window_seconds=60.0,
    )
    ledger.add("squatter", chip_seconds=0.1)  # fills the 1-row cap
    verdict = enforcer.admit("vip")  # lands on _overflow's row...
    assert verdict.tenant == "_overflow"
    assert verdict.limit_chip_seconds == 1000.0  # ...under vip's policy
    ledger.add("vip", chip_seconds=2.0)  # accrues to _overflow
    clock.advance(1.0)
    enforcer.refresh_verdict(verdict)
    # Remaining computed against vip's OWN 1000s budget, minus the shared
    # row's consumption — never the overflow policy's (unlimited -> None
    # -> rendered 0.0, the "budget exhausted" lie this test pins).
    assert verdict.remaining_chip_seconds == pytest.approx(998.0)
    enforcer.release(verdict)


def test_malformed_policy_file_keeps_last_good(tmp_path):
    policy_path = tmp_path / "policy.json"
    policy_path.write_text(
        json.dumps({"default": {"chip_seconds_per_window": 100}})
    )
    enforcer, _, clock = make_enforcer(
        tmp_path,
        quota_policy_file=str(policy_path),
        quota_policy_reload_seconds=1.0,
    )
    assert enforcer.default_policy.chip_seconds_per_window == 100.0
    for bad in ("{not json", json.dumps({"default": {"bogus_key": 1}}),
                json.dumps({"default": {"chip_seconds_per_window": -5}})):
        policy_path.write_text(bad)
        os.utime(policy_path, (clock.now + 60, clock.now + 60))
        clock.advance(2.0)
        enforcer.release(enforcer.admit("t"))
        # Fail closed: the last GOOD policy stands.
        assert enforcer.default_policy.chip_seconds_per_window == 100.0
    assert enforcer.policy_load_errors >= 2  # unparseable + bad key/value


def test_policy_validation_rejects_bad_values():
    with pytest.raises(ValueError):
        from bee_code_interpreter_fs_tpu.services.quotas import (
            _policy_from_mapping,
        )

        _policy_from_mapping(QuotaPolicy(), {"nope": 1}, source="t")


# ----------------------------------------------------------- overflow-cap rule


def test_past_cap_tenants_share_overflow_budget(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        usage_max_tenants=2,
        quota_chip_seconds_per_window=5.0,
        quota_window_seconds=60.0,
    )
    ledger.add("a", chip_seconds=0.1)
    ledger.add("b", chip_seconds=0.1)
    # Past the cap: minted names land on _overflow's row AND its budget.
    enforcer.release(enforcer.admit("minted-1"))
    ledger.add("minted-1", chip_seconds=10.0)  # accrues to _overflow
    clock.advance(1.0)
    with pytest.raises(QuotaExceededError) as e:
        enforcer.admit("minted-2")  # a DIFFERENT minted name
    assert e.value.tenant == "_overflow"
    assert e.value.reason == "chip_seconds"


# -------------------------------------------------------------- journal restore


def test_windows_restore_from_ledger_journal(tmp_path):
    clock = FakeClock()
    config = make_config(
        tmp_path,
        quota_chip_seconds_per_window=5.0,
        quota_window_seconds=1000.0,
    )
    ledger = UsageLedger(config, walltime=clock)
    enforcer = QuotaEnforcer(config, usage=ledger, walltime=clock)
    enforcer.release(enforcer.admit("t-a"))
    ledger.add("t-a", chip_seconds=10.0)
    ledger.flush()  # the journal now holds the timestamped sample
    clock.advance(1.0)
    with pytest.raises(QuotaExceededError):
        enforcer.admit("t-a")
    # "Restart": fresh ledger + enforcer over the same directory. The
    # offender must NOT get a fresh budget (the journal restores the
    # window), even though all in-memory state is gone.
    ledger2 = UsageLedger(config, walltime=clock)
    enforcer2 = QuotaEnforcer(config, usage=ledger2, walltime=clock)
    with pytest.raises(QuotaExceededError) as e:
        enforcer2.admit("t-a")
    assert e.value.reason == "chip_seconds"
    # And the refill point survives too: after the window passes, admitted.
    clock.advance(e.value.retry_after + 0.1)
    assert enforcer2.admit("t-a") is not None


def test_quarantine_ladder_survives_restart(tmp_path):
    """Crashing the control plane must not truncate a standing sentence
    (or reset the escalation ladder): the offender sidecar restores
    level, quarantined_until, and the spent-violation floor."""
    clock = FakeClock()
    config = make_config(
        tmp_path,
        quota_violations_per_window=2,
        quota_window_seconds=1000.0,
        quota_quarantine_base_seconds=100.0,
        quota_quarantine_decay_seconds=10_000.0,
    )
    ledger = UsageLedger(config, walltime=clock)
    enforcer = QuotaEnforcer(config, usage=ledger, walltime=clock)
    enforcer.release(enforcer.admit("t-bad"))
    # Two storms: the second sentence is the escalated 200s one.
    for sentence in (100.0, 200.0):
        for _ in range(2):
            ledger.add("t-bad", violation="oom")
        clock.advance(1.0)
        with pytest.raises(QuotaExceededError) as e:
            enforcer.admit("t-bad")
        assert e.value.retry_after == pytest.approx(sentence, abs=0.01)
        if sentence == 100.0:
            clock.advance(sentence + 0.1)
            enforcer.release(enforcer.admit("t-bad"))
    ledger.flush()
    # "Restart" 50s into the 200s sentence: the fresh enforcer must
    # continue the SAME sentence (150s remaining at level 2), not start a
    # fresh base one — and the spent-violation floor must hold (no
    # re-sentencing for already-punished violations after release).
    clock.advance(50.0)
    ledger2 = UsageLedger(config, walltime=clock)
    enforcer2 = QuotaEnforcer(config, usage=ledger2, walltime=clock)
    with pytest.raises(QuotaExceededError) as e:
        enforcer2.admit("t-bad")
    assert e.value.reason == "quarantined"
    assert e.value.retry_after == pytest.approx(150.0, abs=1.0)
    clock.advance(151.0)
    assert enforcer2.admit("t-bad") is not None


def test_restore_ignores_samples_outside_horizon(tmp_path):
    clock = FakeClock()
    config = make_config(
        tmp_path,
        quota_chip_seconds_per_window=5.0,
        quota_window_seconds=100.0,
    )
    ledger = UsageLedger(config, walltime=clock)
    ledger.add("t-a", chip_seconds=10.0)
    ledger.flush()
    # Far past the window: the old consumption must not deny anything.
    clock.advance(10_000.0)
    ledger2 = UsageLedger(config, walltime=clock)
    enforcer2 = QuotaEnforcer(config, usage=ledger2, walltime=clock)
    assert enforcer2.admit("t-a") is not None


# ------------------------------------------------------------------ kill switch


def test_kill_switch_disables_everything(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quotas_enabled=False,
        quota_chip_seconds_per_window=0.001,
        quota_violations_per_window=1,
    )
    assert not enforcer.enabled
    ledger.add("t-a", chip_seconds=1e9, violation="oom")
    for _ in range(10):
        assert enforcer.admit("t-a") is None  # no verdict object at all
    assert enforcer.snapshot() == {"enabled": False}
    assert enforcer.remaining_gauge_samples() == {}


def test_quotas_inert_without_metering(tmp_path):
    config = make_config(
        tmp_path,
        usage_metering_enabled=False,
        quota_chip_seconds_per_window=0.001,
    )
    ledger = UsageLedger(config)
    enforcer = QuotaEnforcer(config, usage=ledger)
    assert not enforcer.enabled  # reads the ledger; nothing to read


# ------------------------------------------------------- executor integration


def make_executor(tmp_path, **kwargs):
    config = make_config(tmp_path, **kwargs)
    executor = CodeExecutor(
        FakeBackend(), Storage(config.file_storage_path), config
    )

    async def post(client, base, payload, timeout, sandbox):
        return {
            "stdout": "ok\n",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "warm": True,
            "duration_s": 0.5,
            "device_op_seconds": 0.5,
        }

    executor._post_execute = post
    return executor


async def test_executor_denies_before_any_sandbox_is_consumed(tmp_path):
    executor = make_executor(
        tmp_path,
        quota_chip_seconds_per_window=0.4,
        quota_window_seconds=3600.0,
        executor_pod_queue_target_length=0,  # no warm pool: spawns visible
    )
    try:
        # First request admitted (window empty); it bills 0.5 chip-seconds
        # against the 0.4 budget, so everything after is denied.
        result = await executor.execute("print(1)", tenant="t-a")
        assert result.phases["quota"]["limit_chip_seconds"] == 0.4
        spawns_after_first = executor.backend.spawns
        for _ in range(5):
            with pytest.raises(QuotaExceededError) as e:
                await executor.execute("print(1)", tenant="t-a")
            assert e.value.reason == "chip_seconds"
        # ZERO sandboxes (and zero scheduler tickets) consumed by the five
        # denied attempts — the point of admission-side enforcement.
        assert executor.backend.spawns == spawns_after_first
        assert executor.scheduler.queued(0) == 0
        # The denials are visible: metric family + ledger outcome counts.
        samples = dict(
            (tuple(labels.items()), value)
            for labels, value in executor.metrics.quota_denials.samples()
        )
        assert samples[
            (("tenant", "t-a"), ("reason", "chip_seconds"))
        ] == 5.0
        row = executor.usage.snapshot()["tenants"]["t-a"]
        assert row["outcomes"]["rejected"] == 5.0
    finally:
        await executor.close()


async def test_violation_storm_tenant_quarantined_at_door(tmp_path):
    executor = make_executor(
        tmp_path,
        quota_violations_per_window=2,
        quota_window_seconds=3600.0,
        quota_quarantine_base_seconds=60.0,
    )
    try:
        # Two violations land in the ledger (as the limits pipeline would
        # record them).
        await executor.execute("print(1)", tenant="t-bad")
        executor.usage.add("t-bad", violation="oom", requests=1,
                           outcome="limit_violation")
        executor.usage.add("t-bad", violation="oom", requests=1,
                           outcome="limit_violation")
        spawns_before = executor.backend.spawns
        with pytest.raises(QuotaExceededError) as e:
            await executor.execute("print(1)", tenant="t-bad")
        assert e.value.reason == "quarantined"
        assert executor.backend.spawns == spawns_before
        # Another tenant is unaffected.
        result = await executor.execute("print(1)", tenant="t-good")
        assert result.exit_code == 0
    finally:
        await executor.close()


async def test_trusted_runs_bypass_quotas(tmp_path):
    executor = make_executor(
        tmp_path,
        quota_requests_per_window=1,
        quota_window_seconds=3600.0,
    )
    try:
        # Internal (pre-warm) runs are unmetered AND unquota'd: they carry
        # no tenant, so a tight default policy cannot starve the control
        # plane's own warmup work.
        for _ in range(3):
            result = await executor._execute_trusted("print(1)")
            assert result.exit_code == 0
    finally:
        await executor.close()


async def test_quota_kill_switch_end_to_end(tmp_path):
    executor = make_executor(
        tmp_path,
        quotas_enabled=False,
        quota_chip_seconds_per_window=0.0001,
        quota_requests_per_window=1,
    )
    try:
        for _ in range(4):
            result = await executor.execute("print(1)", tenant="t-a")
            assert result.exit_code == 0
            assert "quota" not in result.phases  # byte-for-byte
        registry_text = executor.metrics.registry.render()
        assert "quota_remaining_chip_seconds" not in registry_text
    finally:
        await executor.close()


async def test_concurrency_cap_through_executor(tmp_path):
    executor = make_executor(tmp_path, quota_max_concurrent=1)
    release = asyncio.Event()

    async def slow_post(client, base, payload, timeout, sandbox):
        await release.wait()
        return {
            "stdout": "",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "warm": True,
            "duration_s": 0.1,
            "device_op_seconds": 0.1,
        }

    executor._post_execute = slow_post
    try:
        first = asyncio.create_task(
            executor.execute("print(1)", tenant="t-a")
        )
        await asyncio.sleep(0.05)  # first request is in flight
        with pytest.raises(QuotaExceededError) as e:
            await executor.execute("print(1)", tenant="t-a")
        assert e.value.reason == "concurrency"
        release.set()
        result = await first
        assert result.exit_code == 0
        # Slot released at exit: next request admitted.
        result = await executor.execute("print(1)", tenant="t-a")
        assert result.exit_code == 0
    finally:
        release.set()
        await executor.close()


# ------------------------------------------------------------------- HTTP side


async def http_client_for(executor):
    app = create_http_app(
        executor, CustomToolExecutor(executor), executor.storage
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def test_http_429_with_quota_headers(tmp_path):
    executor = make_executor(
        tmp_path,
        quota_chip_seconds_per_window=0.4,
        quota_window_seconds=3600.0,
    )
    client = await http_client_for(executor)
    try:
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "print(1)", "tenant": "t-a"},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["phases"]["quota"]["limit_chip_seconds"] == 0.4
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "print(1)", "tenant": "t-a"},
        )
        assert resp.status == 429
        assert resp.headers["X-Quota-Reason"] == "chip_seconds"
        assert float(resp.headers["X-Quota-Remaining-Chip-Seconds"]) == 0.0
        assert float(resp.headers["X-Quota-Limit-Chip-Seconds"]) == 0.4
        assert int(resp.headers["Retry-After"]) >= 1
        body = await resp.json()
        assert body["quota"]["reason"] == "chip_seconds"
        # Tenant via header (gateway idiom) hits the same budget row.
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "print(1)"},
            headers={"X-Tenant": "t-a"},
        )
        assert resp.status == 429
    finally:
        await client.close()
        await executor.close()


async def test_http_quotas_surface(tmp_path):
    executor = make_executor(
        tmp_path,
        quota_chip_seconds_per_window=10.0,
        quota_window_seconds=3600.0,
    )
    client = await http_client_for(executor)
    try:
        await executor.execute("print(1)", tenant="t-a")
        resp = await client.get("/quotas")
        assert resp.status == 200
        body = await resp.json()
        assert body["enabled"] is True
        assert body["default_policy"]["chip_seconds_per_window"] == 10.0
        assert "t-a" in body["tenants"]
        assert body["tenants"]["t-a"]["remaining_chip_seconds"] <= 10.0
        resp = await client.get("/quotas/t-a")
        assert resp.status == 200
        one = await resp.json()
        assert one["quota"]["policy"]["chip_seconds_per_window"] == 10.0
        resp = await client.get("/quotas/never-seen")
        assert resp.status == 404
        resp = await client.get("/quotas?format=text")
        assert resp.status == 200
        text = await resp.text()
        assert "t-a" in text and "quota enforcement" in text
        # /statusz carries the quotas section in both formats.
        resp = await client.get("/statusz")
        statusz = await resp.json()
        assert statusz["quotas"]["enabled"] is True
        resp = await client.get("/statusz?format=text")
        assert "quotas:" in await resp.text()
        # The remaining-budget gauge rides /metrics.
        resp = await client.get("/metrics")
        metrics_text = await resp.text()
        assert "code_interpreter_quota_remaining_chip_seconds" in metrics_text
        assert 'tenant="t-a"' in metrics_text
    finally:
        await client.close()
        await executor.close()


async def test_http_quotas_404_when_disabled(tmp_path):
    executor = make_executor(tmp_path, quotas_enabled=False)
    client = await http_client_for(executor)
    try:
        assert (await client.get("/quotas")).status == 404
        assert (await client.get("/quotas/t-a")).status == 404
    finally:
        await client.close()
        await executor.close()


# ------------------------------------------------------------------- gRPC side


class AbortRaised(Exception):
    def __init__(self, code, details):
        super().__init__(details)
        self.code = code
        self.details = details


class FakeContext:
    def __init__(self, metadata=()):
        self.metadata = tuple(metadata)
        self.trailing = ()

    def invocation_metadata(self):
        return self.metadata

    def set_trailing_metadata(self, trailing):
        self.trailing = tuple(trailing)

    async def abort(self, code, details=""):
        raise AbortRaised(code, details)


async def test_grpc_quota_denial_metadata(tmp_path):
    executor = make_executor(
        tmp_path,
        quota_chip_seconds_per_window=0.4,
        quota_window_seconds=3600.0,
    )
    servicer = CodeInterpreterServicer(executor, CustomToolExecutor(executor))
    try:
        context = FakeContext(metadata=[("x-tenant", "t-a")])
        await servicer.Execute(
            pb2.ExecuteRequest(source_code="print(1)"), context
        )
        trailing = dict(context.trailing)
        # Success-path pacing metadata (the satellite): remaining budget.
        assert "x-quota-remaining-chip-seconds" in trailing
        context = FakeContext(metadata=[("x-tenant", "t-a")])
        with pytest.raises(AbortRaised) as e:
            await servicer.Execute(
                pb2.ExecuteRequest(source_code="print(1)"), context
            )
        assert e.value.code == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert "quota denied" in e.value.details
        trailing = dict(context.trailing)
        assert trailing["x-quota-reason"] == "chip_seconds"
        assert float(trailing["x-quota-retry-after"]) > 0
        assert float(trailing["x-quota-remaining-chip-seconds"]) == 0.0
        assert float(trailing["x-quota-limit-chip-seconds"]) == 0.4
    finally:
        await executor.close()


# ----------------------------------------------------------------- invariants


def test_denial_reasons_closed_set(tmp_path):
    # Contract: every reason the enforcer can emit is in DENIAL_REASONS
    # (they label quota_denials_total; an unlisted reason is a new metric
    # series nobody dashboards).
    assert set(DENIAL_REASONS) == {
        "chip_seconds",
        "hbm_byte_seconds",
        "burst_credits",
        "predicted_overrun",
        "request_rate",
        "concurrency",
        "quarantined",
    }


def test_gauge_samples_only_budgeted_tenants(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_chip_seconds_per_window=10.0,
        quota_window_seconds=60.0,
    )
    enforcer.release(enforcer.admit("t-a"))
    ledger.add("t-a", chip_seconds=4.0)
    clock.advance(1.0)
    enforcer.release(enforcer.admit("t-a"))
    samples = enforcer.remaining_gauge_samples()
    assert samples[("t-a",)] == pytest.approx(6.0)


def test_metrics_bind_quotas_registers_once(tmp_path):
    config = make_config(
        tmp_path, quota_chip_seconds_per_window=1.0
    )
    ledger = UsageLedger(config)
    enforcer = QuotaEnforcer(config, usage=ledger)
    metrics = ExecutorMetrics()
    metrics.bind_quotas(enforcer)
    assert metrics.quota_remaining is not None
    # A disabled enforcer must not register the family at all.
    disabled = QuotaEnforcer(
        make_config(tmp_path, quotas_enabled=False), usage=ledger
    )
    metrics2 = ExecutorMetrics()
    metrics2.bind_quotas(disabled)
    assert metrics2.quota_remaining is None


# ---------------------------------------------- admission-time cost prediction


def test_predicted_overrun_denies_before_the_burn(tmp_path):
    """The PR 11 carried follow-up: a request whose DECLARED cost
    (chip_count x timeout) cannot fit the remaining window budget is
    denied at the door with the typed reason and a refill-derived
    Retry-After — zero scheduler state, zero chip-seconds burned."""
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_chip_seconds_per_window=10.0,
        quota_window_seconds=100.0,
    )
    # Fits: 4 chip-seconds declared against a full 10s budget.
    v = enforcer.admit("t-a", predicted_chip_seconds=4.0)
    assert v is not None
    enforcer.release(v)
    ledger.add("t-a", chip_seconds=8.0)
    clock.advance(1.0)
    # Remaining is 2.0; a declared 4.0 cannot fit — typed denial.
    with pytest.raises(QuotaExceededError) as e:
        enforcer.admit("t-a", predicted_chip_seconds=4.0)
    assert e.value.reason == "predicted_overrun"
    assert e.value.retry_after > 0
    assert e.value.remaining_chip_seconds == pytest.approx(2.0)
    # A smaller declaration still fits the same window.
    v = enforcer.admit("t-a", predicted_chip_seconds=1.5)
    assert v is not None
    enforcer.release(v)


def test_predicted_overrun_larger_than_whole_budget_backs_off_a_window(
    tmp_path,
):
    enforcer, _ledger, _clock = make_enforcer(
        tmp_path,
        quota_chip_seconds_per_window=5.0,
        quota_window_seconds=100.0,
    )
    # Even an empty window can never fit this declaration: denied with a
    # full-window back-off (the client must shrink the request).
    with pytest.raises(QuotaExceededError) as e:
        enforcer.admit("t-a", predicted_chip_seconds=50.0)
    assert e.value.reason == "predicted_overrun"
    assert e.value.retry_after >= 99.0


def test_predicted_overrun_kill_switch(tmp_path):
    enforcer, _ledger, _clock = make_enforcer(
        tmp_path,
        quota_chip_seconds_per_window=5.0,
        quota_window_seconds=100.0,
        quota_cost_prediction=False,
    )
    # Prediction off: the declaration is ignored (deny-after-the-burn,
    # the pre-satellite behavior, byte-for-byte).
    v = enforcer.admit("t-a", predicted_chip_seconds=50.0)
    assert v is not None
    enforcer.release(v)


async def test_executor_predicts_from_declared_chip_count_and_timeout(
    tmp_path,
):
    """End to end through the executor: the declared chip_count x clamped
    timeout is the prediction, and the denial happens BEFORE any sandbox
    or scheduler state is touched."""
    executor = make_executor(
        tmp_path,
        quota_cost_prediction=True,
        quota_chip_seconds_per_window=30.0,
        quota_window_seconds=3600.0,
        executor_pod_queue_target_length=0,  # no warm pool: spawns visible
    )
    try:
        # 1 chip x 10s = 10 fits the 30s budget.
        result = await executor.execute(
            "print(1)", tenant="t-a", timeout=10.0
        )
        assert result.exit_code == 0
        spawns_before = executor.backend.spawns
        # 8 chips x 10s = 80 cannot fit — denied at the door, no spawn.
        with pytest.raises(QuotaExceededError) as e:
            await executor.execute(
                "print(1)", tenant="t-a", timeout=10.0, chip_count=8
            )
        assert e.value.reason == "predicted_overrun"
        assert executor.backend.spawns == spawns_before
        assert executor.scheduler.queued(8) == 0
    finally:
        await executor.close()


async def test_http_predicted_overrun_429(tmp_path):
    executor = make_executor(
        tmp_path,
        quota_cost_prediction=True,
        quota_chip_seconds_per_window=5.0,
        quota_window_seconds=3600.0,
    )
    client = await http_client_for(executor)
    try:
        resp = await client.post(
            "/v1/execute",
            json={
                "source_code": "print(1)",
                "tenant": "t-a",
                "timeout": 10.0,
                "chip_count": 4,
            },
        )
        assert resp.status == 429
        assert resp.headers["X-Quota-Reason"] == "predicted_overrun"
        assert int(resp.headers["Retry-After"]) >= 1
        body = await resp.json()
        assert body["quota"]["reason"] == "predicted_overrun"
    finally:
        await client.close()
        await executor.close()


# ------------------------------------------- HBM budget (device memory)


def test_hbm_budget_denies_and_refills(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_hbm_byte_seconds=1000.0,
        quota_window_seconds=60.0,
    )
    enforcer.release(enforcer.admit("t-mem"))
    ledger.add("t-mem", chip_seconds=1.0, hbm_byte_seconds=600.0)
    clock.advance(10.0)
    enforcer.release(enforcer.admit("t-mem"))  # under budget: admitted
    ledger.add("t-mem", chip_seconds=1.0, hbm_byte_seconds=500.0)
    clock.advance(1.0)
    with pytest.raises(QuotaExceededError) as exc:
        enforcer.admit("t-mem")
    assert exc.value.reason == "hbm_byte_seconds"
    assert exc.value.remaining_hbm_byte_seconds == 0.0
    assert exc.value.limit_hbm_byte_seconds == 1000.0
    assert exc.value.retry_after > 0
    # The first burst ages out of the window at its refill point: the
    # Retry-After contract (waiting it out re-admits).
    clock.advance(exc.value.retry_after + 0.1)
    verdict = enforcer.admit("t-mem")
    assert verdict is not None
    enforcer.release(verdict)


def test_hbm_budget_policy_file_override(tmp_path):
    policy_path = tmp_path / "policy.json"
    policy_path.write_text(json.dumps(
        {"tenants": {"vip": {"hbm_byte_seconds_per_window": 5000}}}
    ))
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_hbm_byte_seconds=100.0,
        quota_window_seconds=60.0,
        quota_policy_file=str(policy_path),
    )
    enforcer.release(enforcer.admit("vip"))
    enforcer.release(enforcer.admit("pleb"))
    ledger.add("vip", chip_seconds=1.0, hbm_byte_seconds=200.0)
    ledger.add("pleb", chip_seconds=1.0, hbm_byte_seconds=200.0)
    clock.advance(1.0)
    enforcer.release(enforcer.admit("vip"))  # 200 < 5000: fine
    with pytest.raises(QuotaExceededError) as exc:
        enforcer.admit("pleb")  # 200 >= 100: denied
    assert exc.value.reason == "hbm_byte_seconds"


def test_hbm_surfaces_in_snapshot(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_hbm_byte_seconds=1000.0,
        quota_window_seconds=60.0,
    )
    enforcer.release(enforcer.admit("t-mem"))
    ledger.add("t-mem", chip_seconds=2.0, hbm_byte_seconds=300.0)
    clock.advance(1.0)
    enforcer.release(enforcer.admit("t-mem"))
    row = enforcer.tenant_snapshot("t-mem")
    assert row["used_hbm_byte_seconds_window"] == pytest.approx(300.0)
    assert row["remaining_hbm_byte_seconds"] == pytest.approx(700.0)
    assert row["policy"]["hbm_byte_seconds_per_window"] == 1000.0


# ------------------------------------------- burst-credit smoothing


def test_burst_credits_drain_and_refill(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_burst_credits=10.0,
        quota_refill_per_second=1.0,
        quota_window_seconds=3600.0,
    )
    verdict = enforcer.admit("t-burst")
    assert verdict.burst_credits_remaining == pytest.approx(10.0)
    enforcer.release(verdict)
    # Burn 12 chip-seconds in one go: the bucket overdraws.
    ledger.add("t-burst", chip_seconds=12.0)
    clock.advance(1.0)  # refill is capped at the full bucket (10)
    with pytest.raises(QuotaExceededError) as exc:
        enforcer.admit("t-burst")
    assert exc.value.reason == "burst_credits"
    assert exc.value.burst_credits_remaining == 0.0
    # Deficit is 12 - 10 = 2 credits; at 1/s the Retry-After covers it.
    assert exc.value.retry_after == pytest.approx(2.0, abs=0.2)
    clock.advance(exc.value.retry_after + 1.0)
    verdict = enforcer.admit("t-burst")
    assert verdict is not None
    assert verdict.burst_credits_remaining > 0
    enforcer.release(verdict)


def test_burst_credits_cap_at_bucket_size(tmp_path):
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_burst_credits=5.0,
        quota_refill_per_second=100.0,
        quota_window_seconds=3600.0,
    )
    enforcer.release(enforcer.admit("t"))
    clock.advance(3600.0)  # hours of refill never exceed the bucket
    verdict = enforcer.admit("t")
    assert verdict.burst_credits_remaining == pytest.approx(5.0)
    enforcer.release(verdict)


def test_burst_mode_off_without_both_knobs(tmp_path):
    # Opt-in means BOTH knobs: credits without a refill rate (or vice
    # versa) keeps the bucket out of the verdict entirely.
    for kwargs in (
        dict(quota_burst_credits=10.0),
        dict(quota_refill_per_second=1.0),
    ):
        enforcer, ledger, clock = make_enforcer(tmp_path, **kwargs)
        assert not enforcer.default_policy.burst_mode()
        verdict = enforcer.admit("t")
        assert verdict is None or verdict.burst_credits_remaining is None


def test_burst_beside_hard_window(tmp_path):
    """The bucket smooths WITHIN the window budget: a tenant with both
    configured can be denied by either — the bucket on a fast burst, the
    window on sustained consumption."""
    enforcer, ledger, clock = make_enforcer(
        tmp_path,
        quota_chip_seconds_per_window=20.0,
        quota_burst_credits=50.0,
        quota_refill_per_second=100.0,
        quota_window_seconds=60.0,
    )
    enforcer.release(enforcer.admit("t"))
    ledger.add("t", chip_seconds=21.0)  # bucket fine (50), window blown (20)
    clock.advance(0.1)
    with pytest.raises(QuotaExceededError) as exc:
        enforcer.admit("t")
    assert exc.value.reason == "chip_seconds"


def test_burst_credits_http_headers(tmp_path):
    async def scenario():
        clock = FakeClock()
        config = make_config(
            tmp_path,
            quota_burst_credits=5.0,
            quota_refill_per_second=0.5,
            quota_window_seconds=3600.0,
        )
        ledger = UsageLedger(config, walltime=clock)
        enforcer = QuotaEnforcer(config, usage=ledger, walltime=clock)
        backend = FakeBackend()
        executor = CodeExecutor(
            backend, Storage(config.file_storage_path), config,
            usage=ledger, quotas=enforcer,
        )

        async def fake_post_execute(client, base, payload, timeout, sandbox):
            return {"stdout": "", "stderr": "", "exit_code": 0,
                    "files": [], "warm": True}

        executor._post_execute = fake_post_execute
        app = create_http_app(
            executor, CustomToolExecutor(executor),
            Storage(config.file_storage_path),
        )
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # First request anchors the bucket (history predating it is
            # the window budget's business, not the bucket's)...
            resp = await client.post(
                "/v1/execute",
                json={"source_code": "print(1)", "tenant": "t-h"},
            )
            assert resp.status == 200
            # ...then a 9 chip-second burn overdraws the 5-credit bucket.
            ledger.add("t-h", chip_seconds=9.0)
            clock.advance(0.1)
            resp = await client.post(
                "/v1/execute",
                json={"source_code": "print(1)", "tenant": "t-h"},
            )
            assert resp.status == 429
            assert resp.headers["X-Quota-Reason"] == "burst_credits"
            assert float(resp.headers["X-Quota-Burst-Credits"]) == 0.0
            assert "Retry-After" in resp.headers
            body = await resp.json()
            assert body["quota"]["burst_credits_remaining"] == 0.0
        finally:
            await client.close()
            await executor.close()

    asyncio.run(scenario())
