"""Checkpoint save/restore for model pytrees, including sharded restore."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bee_code_interpreter_fs_tpu.models import (
    LlamaConfig,
    forward,
    init_params,
    param_specs,
)
from bee_code_interpreter_fs_tpu.parallel import (
    best_mesh_shape,
    make_mesh,
    shard_pytree,
)
from bee_code_interpreter_fs_tpu.utils.checkpoint import (
    restore_checkpoint,
    save_checkpoint,
)


def test_roundtrip_params(tmp_path):
    cfg = LlamaConfig.tiny(dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    save_checkpoint(tmp_path / "ckpt", params)
    restored = restore_checkpoint(tmp_path / "ckpt")
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        restored,
    )


def test_moe_checkpoint_restores_onto_ep_mesh(tmp_path):
    """Composition: a MoE checkpoint restores with experts sharded over ep
    and computes identical logits."""
    cfg = LlamaConfig.tiny(dtype="float32", n_experts=4, n_experts_per_token=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, cfg.vocab_size)
    expected = forward(params, tokens, cfg)

    save_checkpoint(tmp_path / "moe", params)
    mesh = make_mesh(best_mesh_shape(8, tp=2, sp=1, ep=2))
    like = shard_pytree(mesh, jax.tree.map(jnp.zeros_like, params), param_specs(cfg))
    restored = restore_checkpoint(tmp_path / "moe", like=like)
    assert restored["layers"]["w_gate"].sharding.spec == P(None, "ep", None, "tp")
    got = jax.jit(lambda p, t: forward(p, t, cfg))(restored, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=5e-3, atol=5e-3
    )


def test_restore_with_shardings_produces_identical_model(tmp_path):
    """A checkpoint saved unsharded restores directly onto a tp/sp mesh with
    the model's shardings — and the sharded model computes the same logits."""
    cfg = LlamaConfig.tiny(dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    expected = forward(params, tokens, cfg)

    save_checkpoint(tmp_path / "ckpt", params)

    mesh = make_mesh(best_mesh_shape(8, tp=2, sp=2))
    like = shard_pytree(mesh, jax.tree.map(jnp.zeros_like, params), param_specs(cfg))
    restored = restore_checkpoint(tmp_path / "ckpt", like=like)
    # leaves landed sharded, not replicated host arrays
    assert restored["layers"]["wq"].sharding.spec == P(None, None, "tp")
    got = jax.jit(lambda p, t: forward(p, t, cfg))(restored, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=5e-3, atol=5e-3
    )


def test_quantized_tree_roundtrips_and_restores_sharded(tmp_path):
    """models/quant.py's claim that the int8 tree 'checkpoints through
    utils/checkpoint.py unchanged': exact int8/scale roundtrip, plus a
    sharded restore via quantized_param_specs that still decodes."""
    from bee_code_interpreter_fs_tpu.models import (
        greedy_generate,
        quantize_params,
        quantized_param_specs,
    )

    cfg = LlamaConfig.tiny(dtype="float32")
    qparams = quantize_params(init_params(jax.random.PRNGKey(0), cfg))
    save_checkpoint(tmp_path / "q", qparams)
    restored = restore_checkpoint(tmp_path / "q")
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        qparams,
        restored,
    )
    assert restored["lm_head"]["q"].dtype == jnp.int8

    mesh = make_mesh(best_mesh_shape(8, tp=2, sp=1))
    like = shard_pytree(
        mesh, jax.tree.map(jnp.zeros_like, qparams), quantized_param_specs(cfg)
    )
    sharded = restore_checkpoint(tmp_path / "q", like=like)
    assert sharded["lm_head"]["q"].sharding.spec == P(None, "tp")
    prompt = jnp.zeros((1, 4), jnp.int32)
    out = greedy_generate(sharded, prompt, cfg, max_new_tokens=3)
    assert out.shape == (1, 7)
