"""Spawn-failure diagnosability and per-chip warm-spawn gating.

Round 1's driver bench died with a bare "sandbox did not become ready" —
the runner's `import jax` traceback went to DEVNULL and the TPU-side cause
was unrecoverable (VERDICT r1 weakness #2), while the pool's refill raced
the in-flight execution for libtpu's exclusive chip access (weakness #1).
These tests pin the round-2 fixes:

- sandbox stderr is captured per-sandbox and its tail rides in every
  SandboxSpawnError;
- warm-JAX spawns serialize on a TPU slot that is released only when the
  previous sandbox's process group is confirmed dead;
- pool lane targets are capped by backend capacity.
"""

import asyncio
from pathlib import Path

import pytest

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.base import SandboxSpawnError
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage

def _config(tmp_path, **kwargs) -> Config:
    return Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        jax_compilation_cache_dir="",
        **kwargs,
    )


async def test_crashed_runner_traceback_in_spawn_error(tmp_path, monkeypatch):
    """A runner that dies during warm-up (the `import jax` wedge class) must
    surface its stderr traceback in the raised SandboxSpawnError."""
    crasher = tmp_path / "crashing_runner.py"
    crasher.write_text(
        "import sys\nraise RuntimeError('FAKE_TPU_INIT_EXPLOSION')\n"
    )
    monkeypatch.setenv("APP_RUNNER_SCRIPT", str(crasher))
    config = _config(tmp_path, executor_warm_ready_timeout=30.0)
    backend = LocalSandboxBackend(config, warm_import_jax=True)
    try:
        with pytest.raises(SandboxSpawnError) as excinfo:
            await backend.spawn()
        message = str(excinfo.value)
        assert "FAKE_TPU_INIT_EXPLOSION" in message
        assert "stderr tail" in message
    finally:
        await backend.close()


async def test_slow_warmup_is_not_a_ready_failure(tmp_path, monkeypatch):
    """A runner slower than executor_pod_ready_timeout must still spawn fine:
    reachability (the 60s class budget) and warmth (the minutes class budget)
    are independent — conflating them was the round-1 bench killer."""
    slow = tmp_path / "slow_runner.py"
    slow.write_text(
        "import json, os, sys, time\n"
        "time.sleep(3)\n"
        "os.write(4, (json.dumps({'ready': True, 'backend': 'fake',"
        " 'device_count': 1}) + '\\n').encode())\n"
        "while True:\n"
        "    line = os.read(3, 65536)\n"
        "    if not line:\n"
        "        os._exit(0)\n"
        "    for piece in line.splitlines():\n"
        "        req = json.loads(piece)\n"
        "        open(req['stdout_path'], 'w').write('slowwarm\\n')\n"
        "        open(req['stderr_path'], 'w').close()\n"
        "        os.write(4, (json.dumps({'exit_code': 0}) + '\\n').encode())\n"
    )
    monkeypatch.setenv("APP_RUNNER_SCRIPT", str(slow))
    config = _config(
        tmp_path,
        executor_pod_ready_timeout=2.0,  # reachability budget < warm-up time
        executor_warm_ready_timeout=60.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=True)
    try:
        sandbox = await backend.spawn()
        assert sandbox.url
    finally:
        await backend.close()


async def test_tpu_slot_serializes_warm_spawns(tmp_path, monkeypatch):
    """With one TPU slot, a second warm spawn must wait until the first
    sandbox is fully dead — never racing it for the chip."""
    config = _config(tmp_path, local_tpu_slots=1)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    monkeypatch.setattr(backend, "_tpu_exclusive", lambda: True)
    try:
        first = await backend.spawn()
        second_task = asyncio.create_task(backend.spawn())
        await asyncio.sleep(1.0)
        assert not second_task.done(), "second spawn should block on the TPU slot"
        await backend.delete(first)
        second = await asyncio.wait_for(second_task, timeout=30.0)
        assert second.url
        await backend.delete(second)
    finally:
        await backend.close()


async def test_cross_lane_eviction_frees_tpu_slot(tmp_path, monkeypatch):
    """An idle warm sandbox pooled in lane 0 holds the only TPU slot; a
    request for lane 4 must evict it and spawn — not hang on the slot."""
    config = _config(
        tmp_path,
        local_tpu_slots=1,
        executor_pod_queue_target_length=1,
        executor_warm_ready_timeout=60.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    monkeypatch.setattr(backend, "_tpu_exclusive", lambda: True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        await executor.fill_pool(0)
        assert len(executor._pools[0]) == 1
        sandbox = await asyncio.wait_for(executor._acquire(4), timeout=60.0)
        assert sandbox.chip_count == 4
        assert len(executor._pools[0]) == 0  # lane-0 idler was evicted
        await backend.delete(sandbox)
    finally:
        await executor.close()


async def test_acquire_waits_for_inflight_refill(tmp_path, monkeypatch):
    """With one TPU slot, a request that finds the pool empty while a refill
    spawn is in flight must wait for the refill to land — not start a
    competing spawn that loses the slot race and starves (the round-2 bench
    run-1 scenario)."""
    config = _config(
        tmp_path,
        local_tpu_slots=1,
        executor_pod_queue_target_length=1,
        executor_warm_ready_timeout=60.0,
        # Single-use mode: with reuse on there is no competing refill at all
        # (the in-use sandbox counts toward the target and comes back via
        # recycle — covered by tests/unit/test_sandbox_reuse.py).
        executor_reuse_sandboxes=False,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    monkeypatch.setattr(backend, "_tpu_exclusive", lambda: True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        await executor.fill_pool(0)
        first = await executor._acquire(0)  # pops; refill blocks on the slot
        acquire2 = asyncio.create_task(executor._acquire(0))
        await asyncio.sleep(0.5)
        assert not acquire2.done(), "second acquire should wait for the refill"
        # Release (non-recyclable) frees the slot -> the refill lands and
        # wakes the waiter.
        await executor._release(first, 0, False)
        second = await asyncio.wait_for(acquire2, timeout=45.0)
        assert second.url
    finally:
        await executor.close()


async def test_pool_lane_target_capped_by_capacity(tmp_path):
    config = _config(tmp_path, executor_pod_queue_target_length=5)

    class OneSlotBackend:
        def pool_capacity(self, chip_count):
            return 1 if chip_count > 0 else None

        async def spawn(self, chip_count=0):  # pragma: no cover - not reached
            raise AssertionError

        async def delete(self, sandbox):  # pragma: no cover
            pass

        async def close(self):
            pass

    executor = CodeExecutor(
        OneSlotBackend(), Storage(config.file_storage_path), config
    )
    assert executor._lane_target(4) == 1
    assert executor._lane_target(0) == 5
    await executor.close()


async def test_local_pool_capacity_reflects_exclusivity(tmp_path, monkeypatch):
    config = _config(tmp_path, local_tpu_slots=1)
    backend = LocalSandboxBackend(config, warm_import_jax=True)
    # Under the test harness JAX_PLATFORMS=cpu → no exclusivity.
    assert backend.pool_capacity(0) is None
    monkeypatch.setattr(backend, "_tpu_exclusive", lambda: True)
    assert backend.pool_capacity(0) == 1
    assert backend.pool_capacity(4) == 1
    await backend.close()


async def test_server_log_written_per_sandbox(tmp_path):
    """The executor server's stderr lands in the sandbox dir's server.log."""
    config = _config(tmp_path)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    try:
        sandbox = await backend.spawn()
        log = Path(backend.root / sandbox.id / "server.log")
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline:
            if log.exists() and b"executor-server listening" in log.read_bytes():
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError("server.log never saw the startup line")
    finally:
        await backend.close()


async def test_missing_binary_triggers_auto_build(tmp_path, monkeypatch):
    """A fresh checkout has no executor binary (`executor/build/` is
    gitignored); the first spawn must attempt `make -C executor` instead of
    failing outright — a re-imaged driver machine runs bench.py without a
    manual build step."""
    from bee_code_interpreter_fs_tpu.services.backends import local as local_mod

    backend = LocalSandboxBackend(_config(tmp_path), warm_import_jax=False)
    fake_default = tmp_path / "build" / "executor-server"
    monkeypatch.setattr(local_mod, "DEFAULT_BINARY", fake_default)
    backend.binary = fake_default

    calls: list[str] = []

    async def fake_build() -> None:
        calls.append("build")

    monkeypatch.setattr(backend, "_build_binary", fake_build)
    # The (failed) build leaves no binary, so the spawn still raises the
    # actionable error — the assertion is that the build hook ran first.
    with pytest.raises(SandboxSpawnError, match="executor binary not found"):
        await backend.spawn()
    assert calls == ["build"]


async def test_custom_binary_path_is_not_auto_built(tmp_path):
    """An operator-specified `executor_binary` that is missing is an
    operator error: no build attempt, just the actionable message."""
    missing = tmp_path / "no-such-binary"
    backend = LocalSandboxBackend(
        _config(tmp_path, executor_binary=str(missing)), warm_import_jax=False
    )
    with pytest.raises(SandboxSpawnError, match="executor binary not found"):
        await backend.spawn()
