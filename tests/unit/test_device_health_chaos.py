"""Seeded attach-hang chaos for the device-health probe (faults.py ->
device_health.py), CHAOS_SEED-parameterized like the other chaos suites:
CI pins the {7, 23, 1337} matrix; a red leg replays exactly with
``CHAOS_SEED=<n> pytest tests/unit/test_device_health_chaos.py``.

The injected fault is a HANG, not an error: the host's HTTP plane answers
every probe, but its synthesized /device-stats reports an attach that has
been pending since the hang began and keeps aging in (injected) real time —
the BENCH_r03-r05 wedge semantics. The probe must walk that host
healthy -> (busy/suspect) -> wedged while untouched hosts stay healthy.
"""

import os
import random
import tempfile

import httpx
import pytest

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    ATTACH_HANG,
    AttachHangTransport,
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.device_health import (
    BUSY,
    HEALTHY,
    SUSPECT,
    WEDGED,
    DeviceHealthProbe,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage

from fakes import FakeBackend

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "7"))


def _healthy_stats() -> dict:
    return {
        "status": "ok",
        "warm": True,
        "warm_state": "ready",
        "backend": "cpu",
        "device_kind": "cpu",
        "device_count": 1,
        "attach_pending_s": 0.0,
        "attach_seconds": 1.0,
        "op_in_flight": False,
        "op_age_s": 0.0,
        "op_timeout_s": 0.0,
        "last_device_op_age_s": 1.0,
        "runner_heartbeat_age_s": 0.1,
        "runner_alive": True,
        "rss_bytes": 1,
        "runner_rss_bytes": 1,
    }


def _inner_transport() -> httpx.MockTransport:
    return httpx.MockTransport(
        lambda request: httpx.Response(200, json=_healthy_stats())
    )


def _transport(
    rate: float,
    lane: int,
    host_lanes: dict[str, int],
    clock,
    seed: int = CHAOS_SEED,
    on_fault=None,
) -> AttachHangTransport:
    return AttachHangTransport(
        rate,
        lane,
        random.Random(f"{seed}:{ATTACH_HANG}"),
        host_lanes,
        on_fault,
        inner=_inner_transport(),
        clock=clock,
    )


def test_per_host_draw_is_seeded_and_stable():
    """The wedged subset is a pure function of (seed, first-probe order):
    two transports with the same seed choose the same hosts; a wedge never
    flickers back on a later probe."""
    hosts = [f"h{i}:80" for i in range(8)]
    lanes = {h: 0 for h in hosts}
    clock = lambda: 100.0  # noqa: E731

    def draws(seed):
        transport = _transport(0.5, -1, lanes, clock, seed=seed)
        out = []
        for host in hosts:
            request = httpx.Request("GET", f"http://{host}/device-stats")
            out.append(transport._hang_started(request) is not None)
        return out

    first = draws(CHAOS_SEED)
    assert first == draws(CHAOS_SEED)
    assert any(first), "rate 0.5 over 8 hosts should wedge at least one"
    assert not all(first), "rate 0.5 over 8 hosts should spare at least one"
    # Re-asking the same transport never changes a host's fate.
    transport = _transport(0.5, -1, lanes, clock)
    request = httpx.Request("GET", "http://h0:80/device-stats")
    assert (
        transport._hang_started(request) is transport._hang_started(request)
        or transport._hang_started(request) == transport._hang_started(request)
    )


def test_lane_restriction_spares_other_lanes():
    lanes = {"a:1": 0, "b:2": 2}
    clock = lambda: 5.0  # noqa: E731
    transport = _transport(1.0, 2, lanes, clock)
    assert (
        transport._hang_started(httpx.Request("GET", "http://a:1/device-stats"))
        is None
    )
    assert (
        transport._hang_started(httpx.Request("GET", "http://b:2/device-stats"))
        is not None
    )


async def test_hang_age_grows_in_real_time():
    now = [10.0]
    lanes = {"w:9": 0}
    transport = _transport(1.0, -1, lanes, lambda: now[0])
    async with httpx.AsyncClient(transport=transport) as client:
        first = (await client.get("http://w:9/device-stats")).json()
        assert first["injected"] == ATTACH_HANG
        assert first["warm_state"] == "pending"
        assert first["attach_pending_s"] == pytest.approx(0.0)
        now[0] += 42.0
        later = (await client.get("http://w:9/device-stats")).json()
        assert later["attach_pending_s"] == pytest.approx(42.0)
        # Matching stale heartbeat: the runner has said nothing since.
        assert later["runner_heartbeat_age_s"] == pytest.approx(42.0)


async def test_probe_escalates_wedge_on_hung_host_spares_healthy_one():
    """End-to-end through the probe: two hosts, the fault wedges exactly
    the attach_hang_lane one; the probe walks it to WEDGED while the other
    stays healthy, and the wedge counter/fault counter fire once."""
    tmp = tempfile.mkdtemp(prefix="dh-chaos-")
    config = Config(
        file_storage_path=tmp,
        executor_fault_spec=(
            f"attach_hang:1.0,attach_hang_lane:2,seed:{CHAOS_SEED}"
        ),
        device_probe_attach_budget=10.0,
        device_probe_wedge_after=10.0,
        # Detection-only posture (the actuation kill switch): this suite
        # asserts the PR 8 classification semantics; the fence/drain/
        # replace loop has its own chaos suite (test_recovery_chaos.py).
        device_fence_enabled=False,
    )
    faults = []
    backend = FaultInjectingBackend(
        FakeBackend(distinct_urls=True),
        FaultSpec.parse(config.executor_fault_spec),
        on_fault=faults.append,
    )
    executor = CodeExecutor(backend, Storage(tmp), config)
    try:
        healthy_box = await backend.spawn(0)
        wedged_box = await backend.spawn(2)
        for lane, box in ((0, healthy_box), (2, wedged_box)):
            executor._live_sandboxes[box.id] = (lane, box)
        # The injected clock drives the synthesized hang age.
        now = [0.0]
        hang = _transport(
            1.0, 2, backend._host_lanes, lambda: now[0], on_fault=faults.append
        )
        client = httpx.AsyncClient(transport=hang)
        executor._http_client = lambda: client
        probe = DeviceHealthProbe(executor)
        states = await probe.probe_once()
        assert states[healthy_box.url] == HEALTHY
        # Hang just started: attaching within budget -> busy.
        assert states[wedged_box.url] == BUSY
        now[0] += 15.0  # past the 10s attach budget, not yet wedge_after
        states = await probe.probe_once()
        assert states[wedged_box.url] == SUSPECT
        assert states[healthy_box.url] == HEALTHY
        now[0] += 30.0  # stall >> wedge_after
        states = await probe.probe_once()
        assert states[wedged_box.url] == WEDGED
        assert states[healthy_box.url] == HEALTHY
        assert wedged_box.meta["device_health"] == WEDGED
        assert "device_health" not in healthy_box.meta or (
            healthy_box.meta["device_health"] == HEALTHY
        )
        text = executor.metrics.registry.render()
        assert 'device_wedge_detected_total{chip_count="2"} 1' in text
        assert 'device_wedge_detected_total{chip_count="0"}' not in text
        assert faults.count(ATTACH_HANG) == 1  # one draw, one fault record
        await client.aclose()
    finally:
        await executor.close()


def test_spec_parses_and_counts_as_active():
    spec = FaultSpec.parse(f"attach_hang:0.5,attach_hang_lane:4,seed:{CHAOS_SEED}")
    assert spec.attach_hang == 0.5
    assert spec.attach_hang_lane == 4
    assert spec.active
    # Lane alone (no rate) injects nothing.
    assert not FaultSpec.parse("attach_hang_lane:4").active
    with pytest.raises(ValueError):
        FaultSpec.parse("attach_hang:1.5")


def test_backend_records_host_lanes_at_spawn():
    spec = FaultSpec.parse(f"attach_hang:1.0,seed:{CHAOS_SEED}")
    backend = FaultInjectingBackend(FakeBackend(distinct_urls=True), spec)

    async def run():
        sandbox = await backend.spawn(4)
        parsed = httpx.URL(sandbox.url)
        assert backend._host_lanes[f"{parsed.host}:{parsed.port}"] == 4
        transport = backend.http_transport()
        assert isinstance(transport, AttachHangTransport)

    import asyncio

    asyncio.run(run())
