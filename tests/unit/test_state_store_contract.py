"""StateStore contract suite, parametrized over ALL THREE impls (in-memory,
SQLite, RESP against the in-repo stub server): the cross-replica components
are written against the interface, so every impl — including a future
fourth — must agree on CAS atomicity under concurrent mutate, TTL lease
expiry, incr monotonicity, and first-write-wins acquire semantics. A new
impl earns the whole control plane by passing this file.
"""

import threading

import pytest

from bee_code_interpreter_fs_tpu.services.resp_stub import RespStubServer
from bee_code_interpreter_fs_tpu.services.state_store import (
    InMemoryStateStore,
    RespStateStore,
    SQLiteStateStore,
)


@pytest.fixture(scope="module")
def resp_stub():
    with RespStubServer() as url:
        yield url


@pytest.fixture(params=["memory", "sqlite", "resp"])
def store(request, tmp_path):
    """One store per impl; `factory` hands concurrency tests an extra
    handle on the SAME backing state (a second replica, in effect)."""
    if request.param == "memory":
        instance = InMemoryStateStore(shared=True)
        yield instance, lambda: instance  # dicts: one object IS the state
    elif request.param == "sqlite":
        path = str(tmp_path / "contract.db")
        instance = SQLiteStateStore(path)
        yield instance, lambda: SQLiteStateStore(path)
        instance.close()
    else:
        url = request.getfixturevalue("resp_stub")
        instance = RespStateStore(url)
        # Module-scoped stub: scrub between tests so cases stay independent.
        instance._cmd("FLUSHALL")
        yield instance, lambda: RespStateStore(url)
        instance.close()


def test_basic_kv_contract(store):
    s, _ = store
    assert s.get("ns", "a") is None
    s.put("ns", "a", {"x": 1})
    s.put("ns", "b", [1, 2])
    s.put("other", "a", "elsewhere")
    assert s.get("ns", "a") == {"x": 1}
    assert s.items("ns") == {"a": {"x": 1}, "b": [1, 2]}
    s.delete("ns", "a")
    assert s.get("ns", "a") is None
    s.delete("ns", "never-existed")  # idempotent
    assert s.get("other", "a") == "elsewhere"


def test_incr_monotonic_and_independent(store):
    s, _ = store
    assert s.incr("gen", "scope") == 1.0
    assert s.incr("gen", "scope") == 2.0
    assert s.incr("gen", "scope", 3) == 5.0
    assert s.incr("gen", "other") == 1.0
    # Monotonic under interleaving with a second handle (two replicas
    # bumping one lease-generation counter must never repeat a value).
    _, factory = store
    peer = factory()
    seen = [s.incr("gen", "scope"), peer.incr("gen", "scope")]
    assert seen == sorted(seen) and len(set(seen)) == 2
    if peer is not s:
        peer.close()


def test_mutate_cas_atomic_under_concurrency(store):
    """The CAS primitive the WFQ tags and lease floors ride: concurrent
    read-modify-writes from many threads (through separate handles, where
    the impl has real connections) must never lose an update."""
    s, factory = store
    per_thread, threads = 25, 4

    def bump(current):
        return (current or 0) + 1, None

    def spin():
        handle = factory()
        for _ in range(per_thread):
            handle.mutate("cas", "counter", bump)
        if handle is not s:
            handle.close()

    workers = [threading.Thread(target=spin) for _ in range(threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    assert s.get("cas", "counter") == per_thread * threads


def test_mutate_none_deletes(store):
    s, _ = store
    s.put("ns", "k", {"n": 1})
    assert s.mutate("ns", "k", lambda cur: (None, cur)) == {"n": 1}
    assert s.get("ns", "k") is None
    assert "k" not in s.items("ns")


def test_ttl_lease_expiry(store):
    """put_ttl/get_live against the injectable wall clock: live inside
    the window, None (and dropped) past it."""
    s, _ = store
    s.put_ttl("hb", "replica-a", {"load": 3}, 10.0, now=1000.0)
    assert s.get_live("hb", "replica-a", now=1005.0) == {"load": 3}
    assert s.get_live("hb", "replica-a", now=1010.0) is None
    # Lazy expiry dropped the record — a later read inside a NEW window
    # does not resurrect it.
    assert s.get_live("hb", "replica-a", now=1001.0) is None


def test_acquire_lease_first_write_wins(store):
    """Two replicas racing one lease key: exactly one wins; re-acquire by
    the holder extends; the loser wins only after expiry."""
    s, factory = store
    peer = factory()
    assert s.acquire_lease("lock", "lane-4", "replica-a", 30.0, now=0.0)
    assert not peer.acquire_lease("lock", "lane-4", "replica-b", 30.0, now=1.0)
    # Holder re-acquires (extends) while the lease is live.
    assert s.acquire_lease("lock", "lane-4", "replica-a", 30.0, now=15.0)
    # Still extended at the original deadline...
    assert not peer.acquire_lease("lock", "lane-4", "replica-b", 30.0, now=31.0)
    # ...and free once the extension lapses.
    assert peer.acquire_lease("lock", "lane-4", "replica-b", 30.0, now=46.0)
    if peer is not s:
        peer.close()


def test_two_handles_share_state(store):
    """The N-replicas-one-store contract: a second handle sees the first
    handle's writes (trivially true in-memory; load-bearing for the
    file/network impls)."""
    s, factory = store
    peer = factory()
    s.put("ns", "k", "from-first")
    assert peer.get("ns", "k") == "from-first"
    peer.put("ns", "k2", "from-second")
    assert s.items("ns") == {"k": "from-first", "k2": "from-second"}
    if peer is not s:
        peer.close()
