import pytest

from bee_code_interpreter_fs_tpu.utils.validation import (
    PathEscapeError,
    confine_path,
    normalize_workspace_path,
    validate_absolute_path,
    validate_object_id,
)


def test_object_id_patterns():
    validate_object_id("a" * 64)
    validate_object_id("legacy-ID_123")
    with pytest.raises(ValueError):
        validate_object_id("")
    with pytest.raises(ValueError):
        validate_object_id("x" * 256)
    with pytest.raises(ValueError):
        validate_object_id("has/slash")
    with pytest.raises(ValueError):
        validate_object_id("../escape")


def test_absolute_path():
    validate_absolute_path("/workspace/foo.txt")
    with pytest.raises(ValueError):
        validate_absolute_path("relative.txt")
    with pytest.raises(ValueError):
        validate_absolute_path("//double")


def test_normalize_workspace_path():
    assert normalize_workspace_path("/workspace/foo.txt") == "workspace/foo.txt"
    assert normalize_workspace_path("foo/bar.txt") == "foo/bar.txt"
    assert normalize_workspace_path("./a/./b") == "a/b"
    assert normalize_workspace_path("a/b/../c") == "a/c"
    with pytest.raises(PathEscapeError):
        normalize_workspace_path("../../etc/passwd")
    with pytest.raises(PathEscapeError):
        normalize_workspace_path("a/../../etc")
    with pytest.raises(PathEscapeError):
        normalize_workspace_path("/")


def test_confine_path(tmp_path):
    base = tmp_path / "ws"
    base.mkdir()
    p = confine_path(base, "/workspace-escape-attempt.txt")
    assert str(p).startswith(str(base))
    # The reference's Rust join() would have replaced the base entirely for
    # absolute inputs (SURVEY.md §0.4); ours must keep it confined.
    p2 = confine_path(base, "/etc/passwd")
    assert str(p2) == str(base / "etc/passwd")
    with pytest.raises(PathEscapeError):
        confine_path(base, "../outside.txt")


def test_confine_path_symlink_escape(tmp_path):
    base = tmp_path / "ws"
    base.mkdir()
    (base / "link").symlink_to("/etc")
    with pytest.raises(PathEscapeError):
        confine_path(base, "link/passwd")
