"""State-store contract tests: both impls must agree on get/put/delete/
items/incr/mutate semantics (the cross-replica components are written
against the interface, not an impl), plus the SQLite impl's cross-thread
and cross-process properties the replica bench and multi-writer story
rest on."""

import json
import sqlite3
import threading

import pytest

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.state_store import (
    InMemoryStateStore,
    RespStateStore,
    ResilientStateStore,
    SQLiteStateStore,
    make_state_store,
    resolve_replica_id,
)


def stores(tmp_path):
    return [
        InMemoryStateStore(shared=True),
        SQLiteStateStore(str(tmp_path / "state.db")),
    ]


def test_get_put_delete_items(tmp_path):
    for store in stores(tmp_path):
        assert store.get("ns", "a") is None
        store.put("ns", "a", {"x": 1})
        store.put("ns", "b", [1, 2])
        store.put("other", "a", "different-namespace")
        assert store.get("ns", "a") == {"x": 1}
        assert store.items("ns") == {"a": {"x": 1}, "b": [1, 2]}
        store.delete("ns", "a")
        assert store.get("ns", "a") is None
        assert store.get("other", "a") == "different-namespace"
        store.delete("ns", "never-existed")  # idempotent


def test_incr_monotonic(tmp_path):
    for store in stores(tmp_path):
        assert store.incr("gen", "scope") == 1.0
        assert store.incr("gen", "scope") == 2.0
        assert store.incr("gen", "scope", 3) == 5.0
        assert store.incr("gen", "other") == 1.0  # keys independent


def test_mutate_read_modify_write(tmp_path):
    for store in stores(tmp_path):
        result = store.mutate(
            "ns", "k", lambda cur: ({"n": (cur or {}).get("n", 0) + 1}, "ret")
        )
        assert result == "ret"
        store.mutate("ns", "k", lambda cur: ({"n": cur["n"] + 1}, None))
        assert store.get("ns", "k") == {"n": 2}
        # Returning None as the new value deletes the key.
        store.mutate("ns", "k", lambda cur: (None, cur))
        assert store.get("ns", "k") is None


def test_sqlite_two_handles_share_state(tmp_path):
    """Two store objects on one path see each other's writes — the
    N-replicas-one-file contract."""
    path = str(tmp_path / "shared.db")
    a = SQLiteStateStore(path)
    b = SQLiteStateStore(path)
    a.put("ns", "k", "from-a")
    assert b.get("ns", "k") == "from-a"
    assert a.incr("gen", "s") == 1.0
    assert b.incr("gen", "s") == 2.0  # one counter, not two


def test_sqlite_incr_atomic_across_threads(tmp_path):
    """Concurrent incr from worker threads never loses an increment
    (BEGIN IMMEDIATE serializes the read-modify-write)."""
    path = str(tmp_path / "atomic.db")
    store = SQLiteStateStore(path)
    per_thread, threads = 50, 4

    def spin():
        local = SQLiteStateStore(path)
        for _ in range(per_thread):
            local.incr("gen", "k")

    workers = [threading.Thread(target=spin) for _ in range(threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    assert store.get("gen", "k") == per_thread * threads


def test_in_memory_private_vs_shared():
    assert InMemoryStateStore().shared is False
    assert InMemoryStateStore(shared=True).shared is True


def test_make_state_store_grammar(tmp_path):
    # The private default is returned BARE: no resilience wrapper, no new
    # layers — byte-for-byte the single-replica path.
    assert isinstance(make_state_store(Config()), InMemoryStateStore)
    assert make_state_store(Config()).shared is False
    assert make_state_store(Config(state_store="memory")).shared is False
    # Shared stores ship inside the degraded-mode wrapper by default...
    path = str(tmp_path / "s.db")
    sq = make_state_store(Config(state_store=path))
    assert isinstance(sq, ResilientStateStore) and sq.shared
    assert isinstance(sq.inner, SQLiteStateStore)
    sq2 = make_state_store(Config(state_store=f"sqlite://{path}"))
    assert isinstance(sq2.inner, SQLiteStateStore)
    # ...and bare when the wrapper is explicitly disabled.
    raw = make_state_store(
        Config(state_store=path, state_store_resilient=False)
    )
    assert isinstance(raw, SQLiteStateStore)
    resp = make_state_store(
        Config(
            state_store="redis://10.0.0.5:6379/2",
            state_store_resilient=False,
        )
    )
    assert isinstance(resp, RespStateStore)
    assert (resp.host, resp.port, resp.db) == ("10.0.0.5", 6379, 2)
    with pytest.raises(ValueError):
        make_state_store(
            Config(state_store=str(tmp_path / "no" / "such" / "dir" / "x.db"))
        )


def test_resolve_replica_id():
    # Single-replica: empty — legacy file names stay byte-for-byte.
    assert resolve_replica_id(Config()) == ""
    assert resolve_replica_id(Config(replica_self="r1")) == ""
    # Replicated (peers or a shared store): explicit id wins, else derived.
    assert (
        resolve_replica_id(Config(replica_peers="r1=h:1,r2=h:2", replica_self="r1"))
        == "r1"
    )
    derived = resolve_replica_id(Config(state_store="/tmp/x.db"))
    assert derived  # POD_NAME or hostname — non-empty either way


def test_sqlite_values_are_json(tmp_path):
    """The on-disk representation is plain JSON — inspectable, and a
    future store impl can migrate it without a binary decoder."""
    path = str(tmp_path / "j.db")
    store = SQLiteStateStore(path)
    store.put("ns", "k", {"a": [1, 2]})
    raw = sqlite3.connect(path).execute(
        "SELECT value FROM kv WHERE ns='ns' AND key='k'"
    ).fetchone()[0]
    assert json.loads(raw) == {"a": [1, 2]}
