"""Traceparent propagation under chaos (ISSUE 4 satellite): across the
pinned fault-seed matrix {7, 23, 1337}, every retry attempt and breaker
rejection must land in ONE connected trace with correct parent ids — the
whole point of tracing is explaining exactly these paths.

Seed-parameterized like the scheduler chaos suite: CI's chaos leg also sets
``CHAOS_SEED``, so a red leg replays exactly with
``CHAOS_SEED=<n> pytest tests/unit/test_tracing_chaos.py``.
"""

import os

import grpc
import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.proto import code_interpreter_pb2 as pb2
from bee_code_interpreter_fs_tpu.services.backends.base import SandboxSpawnError
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.circuit_breaker import BreakerBoard
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CircuitOpenError,
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.grpc_servicers.code_interpreter_servicer import (
    CodeInterpreterServicer,
)
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage
from bee_code_interpreter_fs_tpu.utils import tracing
from bee_code_interpreter_fs_tpu.utils.tracing import (
    TraceRing,
    Tracer,
    format_traceparent,
)

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "7"))
# The pinned matrix from the ISSUE — run ALL of it locally; CI's per-seed
# legs overlap via CHAOS_SEED without changing coverage.
SEED_MATRIX = sorted({7, 23, 1337, CHAOS_SEED})


def fake_sandbox_server(executor: CodeExecutor) -> None:
    async def fake_post_execute(client, base, payload, timeout, sandbox):
        return {
            "stdout": "ok\n",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "warm": True,
        }

    executor._post_execute = fake_post_execute


def make_executor(backend, tmp_path, breakers=None, **config_kwargs):
    config_kwargs.setdefault("executor_pod_queue_target_length", 1)
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        **config_kwargs,
    )
    tracer = Tracer(ring=TraceRing(1024))
    executor = CodeExecutor(
        backend,
        Storage(config.file_storage_path),
        config,
        breakers=breakers,
        tracer=tracer,
    )
    fake_sandbox_server(executor)
    return executor


def assert_connected(spans: list[dict], root) -> None:
    """Every span belongs to the root's trace and parents onto another span
    of the same trace (or the root's upstream parent) — no orphans."""
    assert spans, "trace recorded no spans"
    ids = {s["span_id"] for s in spans}
    for span in spans:
        assert span["trace_id"] == root.trace_id
        if span["parent_id"] is None:
            assert span["span_id"] == root.span_id
        else:
            assert span["parent_id"] in ids | {root.parent_id}


def trace_events(spans: list[dict], name: str) -> list[dict]:
    return [
        event
        for span in spans
        for event in span.get("events", ())
        if event["name"] == name
    ]


# ----------------------------------------------------- retries stay in-trace


@pytest.mark.parametrize("seed", SEED_MATRIX)
async def test_spawn_retries_land_in_one_connected_trace(tmp_path, seed):
    backend = FaultInjectingBackend(
        FakeBackend(), FaultSpec(spawn_fail=0.5, seed=seed)
    )
    # Reuse off: every execute walks the spawn retry ladder (with reuse on,
    # one spawn serves all 8 and the seeded plan may never fire).
    executor = make_executor(
        backend, tmp_path, executor_reuse_sandboxes=False
    )
    tracer = executor.tracer
    try:
        incoming = format_traceparent(f"{seed:032x}", "c" * 16, True)
        completed = failed = 0
        with tracer.start_trace("chaos-root", traceparent=incoming) as root:
            for _ in range(8):
                try:
                    result = await executor.execute("x")
                    assert result.exit_code == 0
                    completed += 1
                except SandboxSpawnError:
                    failed += 1  # retry ladder exhausted — chaos did its job
        spans = tracer.ring.trace(root.trace_id)
        assert_connected(spans, root)
        assert completed + failed == 8
        # The seeded plan at 0.5 must actually have injected spawn faults;
        # each one shows up as a retry event (or an exhausted ladder) in
        # THIS trace — never as orphaned telemetry.
        retries = trace_events(spans, "retry")
        errored = [s for s in spans if s["status"] == "error"]
        assert retries or failed, (
            f"seed {seed} injected no observable spawn faults"
        )
        for event in retries:
            assert event["attributes"]["operation"] == "spawn"
            assert event["attributes"]["attempt"] >= 1
        # Retry events ride the scheduler.queue_wait span (the spawn runs
        # inside the acquisition), whose parent is the root.
        queue_spans = [s for s in spans if s["name"] == "scheduler.queue_wait"]
        assert queue_spans
        assert all(s["parent_id"] == root.span_id for s in queue_spans)
        if failed:
            assert errored  # an exhausted ladder marks its span errored
        # Scheduler decisions are visible too: every execute enqueued and
        # every successful acquisition granted, in the same trace.
        assert len(trace_events(spans, "scheduler.enqueue")) == 8
        assert len(trace_events(spans, "scheduler.grant")) >= completed
    finally:
        await executor.close()


# ---------------------------------------------- breaker rejections in-trace


@pytest.mark.parametrize("seed", SEED_MATRIX)
async def test_breaker_rejection_lands_in_same_trace(tmp_path, seed):
    backend = FaultInjectingBackend(
        FakeBackend(), FaultSpec(spawn_fail=1.0, seed=seed)
    )
    breakers = BreakerBoard(failure_threshold=1, cooldown=300.0)
    executor = make_executor(backend, tmp_path, breakers=breakers)
    tracer = executor.tracer
    try:
        with tracer.start_trace("chaos-root") as root:
            with pytest.raises((SandboxSpawnError, CircuitOpenError)):
                await executor.execute("x")  # opens the lane-0 breaker
            with pytest.raises(CircuitOpenError):
                await executor.execute("x")  # fail-fast rejection
        spans = tracer.ring.trace(root.trace_id)
        assert_connected(spans, root)
        rejects = trace_events(spans, "breaker.reject")
        assert rejects, "breaker rejection did not land in the trace"
        assert rejects[0]["attributes"]["lane"] == "0"
        assert rejects[0]["attributes"]["failures"] >= 1
    finally:
        await executor.close()


# ------------------------------------------- propagation into the executor


async def test_traceparent_propagates_to_sandbox_calls(tmp_path):
    """The header each sandbox host would receive parents onto that host's
    executor.execute span of the live trace."""
    backend = FakeBackend()
    executor = make_executor(backend, tmp_path)
    tracer = executor.tracer
    seen: list[str] = []

    async def capturing_post_execute(client, base, payload, timeout, sandbox):
        headers = executor._trace_headers()
        seen.append(headers["traceparent"] if headers else None)
        return {"stdout": "", "stderr": "", "exit_code": 0, "files": []}

    executor._post_execute = capturing_post_execute
    try:
        with tracer.start_trace("root") as root:
            await executor.execute("x")
        [header] = seen
        trace_id, parent_span, sampled = tracing.parse_traceparent(header)
        assert trace_id == root.trace_id
        assert sampled
        spans = tracer.ring.trace(root.trace_id)
        [host_span] = [s for s in spans if s["name"] == "executor.execute"]
        assert host_span["span_id"] == parent_span
    finally:
        await executor.close()


async def test_sandbox_trace_block_grafts_as_child_spans(tmp_path):
    backend = FakeBackend()
    executor = make_executor(backend, tmp_path)
    tracer = executor.tracer

    async def post_execute_with_trace(client, base, payload, timeout, sandbox):
        headers = executor._trace_headers()
        return {
            "stdout": "",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "trace": {
                "traceparent": headers["traceparent"],
                "spans": [
                    {"name": "install", "start_offset_s": 0.0, "duration_s": 0.01},
                    {"name": "exec", "start_offset_s": 0.01, "duration_s": 0.5},
                    {"name": "collect", "start_offset_s": 0.51, "duration_s": 0.02},
                    {"name": 7, "start_offset_s": 0, "duration_s": 0},  # junk
                ],
            },
        }

    executor._post_execute = post_execute_with_trace
    try:
        with tracer.start_trace("root") as root:
            result = await executor.execute("x")
        spans = tracer.ring.trace(root.trace_id)
        [host_span] = [s for s in spans if s["name"] == "executor.execute"]
        grafted = {
            s["name"]: s for s in spans if s["name"].startswith("sandbox.")
        }
        assert set(grafted) == {"sandbox.install", "sandbox.exec", "sandbox.collect"}
        for span in grafted.values():
            assert span["parent_id"] == host_span["span_id"]
            assert span["start_unix"] >= host_span["start_unix"]
        assert grafted["sandbox.exec"]["duration_s"] == 0.5
        assert result.phases["trace_id"] == root.trace_id
    finally:
        await executor.close()


async def test_untraced_and_disabled_paths_record_nothing(tmp_path):
    backend = FakeBackend()
    executor = make_executor(backend, tmp_path)
    try:
        # No root span: the pipeline's child spans are no-ops and no
        # traceparent is sent to sandboxes.
        result = await executor.execute("x")
        assert "trace_id" not in result.phases
        assert len(executor.tracer.ring) == 0
    finally:
        await executor.close()
    # Disabled subsystem (APP_TRACING_ENABLED=0): even a root span records
    # nothing anywhere.
    executor = make_executor(backend, tmp_path)
    executor.tracer = Tracer(enabled=False, ring=TraceRing(64))
    try:
        with executor.tracer.start_trace("root"):
            result = await executor.execute("x")
        assert "trace_id" not in result.phases
        assert len(executor.tracer.ring) == 0
    finally:
        await executor.close()


# --------------------------------------------------- API-surface correlation


async def test_http_error_bodies_and_headers_carry_ids(tmp_path):
    executor = make_executor(FakeBackend(), tmp_path)
    error = CircuitOpenError("lane-0 spawn circuit is open", lane=0, retry_after=3.0)

    async def raise_error(*args, **kwargs):
        raise error

    executor.execute = raise_error
    tools = CustomToolExecutor(executor)
    from aiohttp.test_utils import TestClient, TestServer

    app = create_http_app(executor, tools, executor.storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        incoming = format_traceparent("d" * 32, "e" * 16, True)
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "x"},
            headers={"traceparent": incoming},
        )
        assert resp.status == 503
        body = await resp.json()
        # The degraded-response body names the trace an operator should
        # pull, and the headers echo both correlation ids.
        assert body["trace_id"] == "d" * 32
        assert resp.headers["X-Trace-Id"] == "d" * 32
        assert resp.headers["X-Request-Id"]
        # The rejection is retrievable as a trace.
        resp = await client.get(f"/traces/{'d' * 32}")
        assert resp.status == 200
        spans = (await resp.json())["spans"]
        assert spans[0]["parent_id"] == "e" * 16
    finally:
        await client.close()
        await executor.close()


async def test_grpc_trailing_metadata_echoes_ids(tmp_path):
    executor = make_executor(FakeBackend(), tmp_path)
    tools = CustomToolExecutor(executor)
    servicer = CodeInterpreterServicer(executor, tools)

    class FakeContext:
        def __init__(self, metadata=()):
            self.metadata = tuple(metadata)
            self.trailing = None

        def invocation_metadata(self):
            return self.metadata

        def set_trailing_metadata(self, metadata):
            self.trailing = dict(metadata)

        async def abort(self, code, details=""):
            raise AssertionError(f"unexpected abort: {code} {details}")

    incoming = format_traceparent("f" * 32, "a" * 16, True)
    context = FakeContext(metadata=(("x-traceparent", incoming),))
    try:
        response = await servicer.Execute(
            pb2.ExecuteRequest(source_code="x"), context
        )
        assert response.exit_code == 0
        assert context.trailing["x-trace-id"] == "f" * 32
        assert context.trailing["x-request-id"]
        spans = executor.tracer.ring.trace("f" * 32)
        assert spans[0]["name"] == "grpc Execute"
        assert spans[0]["parent_id"] == "a" * 16
        # The full pipeline hangs off the gRPC root span.
        assert {s["name"] for s in spans} >= {
            "grpc Execute",
            "scheduler.queue_wait",
            "transfer.upload",
            "executor.execute",
            "transfer.download",
        }
    finally:
        await executor.close()


async def test_grpc_abort_still_carries_request_id(tmp_path):
    """Trailing metadata is set BEFORE the handler can abort, so even an
    INVALID_ARGUMENT response correlates."""
    executor = make_executor(FakeBackend(), tmp_path)
    tools = CustomToolExecutor(executor)
    servicer = CodeInterpreterServicer(executor, tools)

    class AbortRaised(Exception):
        pass

    class FakeContext:
        def __init__(self):
            self.trailing = None

        def invocation_metadata(self):
            return ()

        def set_trailing_metadata(self, metadata):
            self.trailing = dict(metadata)

        async def abort(self, code, details=""):
            assert code == grpc.StatusCode.INVALID_ARGUMENT
            raise AbortRaised(details)

    context = FakeContext()
    try:
        with pytest.raises(AbortRaised):
            await servicer.Execute(pb2.ExecuteRequest(), context)
        assert context.trailing["x-request-id"]
    finally:
        await executor.close()
