"""Beam search: greedy degeneracy, exhaustive-argmax equivalence, EOS."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_fs_tpu.models.beam import beam_generate
from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    forward,
    greedy_generate,
    init_params,
)


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.tiny(n_layers=2, dim=64, hidden_dim=128, n_heads=4,
                           n_kv_heads=2, vocab_size=61, max_seq_len=64,
                           dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


@pytest.fixture(scope="module")
def tiny_vocab_model():
    cfg = LlamaConfig.tiny(n_layers=2, dim=32, hidden_dim=64, n_heads=2,
                           n_kv_heads=2, vocab_size=5, max_seq_len=32,
                           dtype="float32")
    params = init_params(jax.random.PRNGKey(3), cfg)
    return params, cfg


def _seq_logprob(params, cfg, prompt, continuation):
    """Total log-prob of `continuation` after `prompt` under the model."""
    toks = jnp.asarray([list(prompt) + list(continuation)], jnp.int32)
    logits = forward(params, toks[:, :-1], cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    total = 0.0
    for i, t in enumerate(continuation):
        total += float(logp[0, len(prompt) - 1 + i, t])
    return total


def test_beam_one_equals_greedy(model):
    params, cfg = model
    prompt = jnp.asarray([[7, 3, 19], [2, 40, 5]], jnp.int32)
    out_b = beam_generate(params, prompt, cfg, max_new_tokens=9, beam_size=1)
    out_g = greedy_generate(params, prompt, cfg, max_new_tokens=9)
    np.testing.assert_array_equal(np.asarray(out_b), np.asarray(out_g))


def test_beam_one_equals_greedy_with_eos(model):
    params, cfg = model
    prompt = jnp.asarray([[11, 4]], jnp.int32)
    free = np.asarray(greedy_generate(params, prompt, cfg, max_new_tokens=8))
    eos = int(free[0, 2 + 3])  # greedy's 4th generated token as eos
    out_b = beam_generate(params, prompt, cfg, max_new_tokens=8, beam_size=1,
                          eos_id=eos)
    out_g = greedy_generate(params, prompt, cfg, max_new_tokens=8, eos_id=eos)
    np.testing.assert_array_equal(np.asarray(out_b), np.asarray(out_g))


def test_beam_exhaustive_is_argmax(tiny_vocab_model):
    """With beam_size >= vocab**steps the search is exhaustive: the result
    must be the true argmax continuation, verified by brute force over all
    vocab**3 = 125 length-3 continuations."""
    params, cfg = tiny_vocab_model
    prompt = [1, 2]
    out = beam_generate(
        params, jnp.asarray([prompt], jnp.int32), cfg,
        max_new_tokens=3, beam_size=125, length_penalty=0.0,
    )
    got = np.asarray(out)[0, len(prompt):].tolist()
    best, best_lp = None, -1e18
    for a in range(5):
        for c in range(5):
            for d in range(5):
                lp = _seq_logprob(params, cfg, prompt, [a, c, d])
                if lp > best_lp:
                    best, best_lp = [a, c, d], lp
    assert got == best, (got, best, best_lp,
                         _seq_logprob(params, cfg, prompt, got))


def test_wider_beam_not_worse_on_fixture(model):
    """On THIS pinned fixture, wider beams find sequences of
    non-decreasing model log-prob. Beam search does NOT guarantee
    monotonicity in width in general (a wider beam can crowd out the
    narrower beam's eventual winner); this is a seeded regression probe
    that the search machinery improves over greedy here, not an invariant.
    The exhaustive-width test above is the real correctness anchor."""
    params, cfg = model
    prompt = [9, 33, 17, 2]
    lps = []
    for k in (1, 2, 4, 8):
        out = beam_generate(
            params, jnp.asarray([prompt], jnp.int32), cfg,
            max_new_tokens=6, beam_size=k, length_penalty=0.0,
        )
        cont = np.asarray(out)[0, len(prompt):].tolist()
        lps.append(_seq_logprob(params, cfg, prompt, cont))
    assert all(b >= a - 1e-4 for a, b in zip(lps, lps[1:])), lps


def test_eos_finished_beam_padded(model):
    params, cfg = model
    prompt = jnp.asarray([[5, 28]], jnp.int32)
    free = np.asarray(
        beam_generate(params, prompt, cfg, max_new_tokens=10, beam_size=3)
    )
    eos = int(free[0, 2 + 2])  # the winning beam's 3rd token
    out = np.asarray(
        beam_generate(params, prompt, cfg, max_new_tokens=10, beam_size=3,
                      eos_id=eos)
    )
    gen = out[0, 2:]
    hits = np.nonzero(gen == eos)[0]
    assert hits.size, gen
    # everything after the first eos is pinned eos
    assert (gen[hits[0]:] == eos).all()


def test_beam_validation(model):
    params, cfg = model
    prompt = jnp.asarray([[1]], jnp.int32)
    with pytest.raises(ValueError, match="beam_size"):
        beam_generate(params, prompt, cfg, max_new_tokens=2, beam_size=0)
    with pytest.raises(ValueError, match="cache too small"):
        beam_generate(params, prompt, cfg, max_new_tokens=8, beam_size=2,
                      max_len=4)
