"""Result-memo tests: key derivation (every output-determining input moves
the key), the store lifecycle (record/lookup/evict/kill switch/first-write-
wins/self-heal), the CodeExecutor admission flow over an in-memory fake
executor host (miss records, identical repeat serves with ZERO sandbox HTTP
and zero chip-seconds, tenants never share records, the shared scope is
provenance-gated), the executor-echo verification gate (no echo / lying
echo / truncation = nothing recorded), the keep-alive connection-reuse
regression (two sequential dispatches to one real TCP host reuse one
connection), and the seeded-chaos legs (wire drops mid-store never admit
partial results; kill switch = byte-for-byte pre-memo behavior).
"""

import asyncio
import hashlib
import json
import random

import httpx
import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CodeExecutor,
    _trusted_source_var,
)
from bee_code_interpreter_fs_tpu.services.result_memo import (
    MEMO_NS,
    SHARED_SCOPE,
    ResultMemoStore,
    derive_key,
    manifest_sha,
    result_content_sha,
)
from bee_code_interpreter_fs_tpu.services.state_store import InMemoryStateStore
from bee_code_interpreter_fs_tpu.services.storage import Storage

CHAOS_SEEDS = [7, 23, 1337]


def sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------- keying


BASE_KEY = dict(
    scope="tenant-a",
    source_code="print(1)",
    source_file=None,
    files={"in.txt": "a" * 64},
    env={"X": "1"},
    limits={"cpu_seconds": 10},
    lane=1,
    binary_key="bin:abc",
)


def test_derive_key_is_deterministic():
    assert derive_key(**BASE_KEY) == derive_key(**BASE_KEY)


@pytest.mark.parametrize(
    "field,value",
    [
        ("source_code", "print(2)"),
        ("files", {"in.txt": "b" * 64}),
        ("files", {"other.txt": "a" * 64}),
        ("env", {"X": "2"}),
        ("env", None),
        ("limits", {"cpu_seconds": 20}),
        ("lane", 8),
        ("binary_key", "bin:def"),
    ],
)
def test_every_input_component_moves_the_key(field, value):
    moved = derive_key(**{**BASE_KEY, field: value})
    assert moved.digest != derive_key(**BASE_KEY).digest


def test_scope_partitions_but_does_not_move_the_digest():
    a = derive_key(**BASE_KEY)
    b = derive_key(**{**BASE_KEY, "scope": "tenant-b"})
    # Same inputs, same digest — the partition lives in the index key, so
    # a shared-scope record can serve any tenant's identical request.
    assert a.digest == b.digest
    assert a.index_key != b.index_key


def test_source_file_and_source_code_never_collide():
    by_code = derive_key(**{**BASE_KEY, "source_code": "run.py"})
    by_file = derive_key(
        **{**BASE_KEY, "source_code": None, "source_file": "run.py"}
    )
    assert by_code.digest != by_file.digest


def test_manifest_sha_is_order_independent_and_content_sensitive():
    a = manifest_sha({"x": "1" * 64, "y": "2" * 64})
    b = manifest_sha({"y": "2" * 64, "x": "1" * 64})
    assert a == b
    assert manifest_sha({"x": "3" * 64, "y": "2" * 64}) != a


def test_result_content_sha_separates_fields():
    # NUL separation: shifting a byte across a field boundary moves the
    # hash (the classic concatenation-ambiguity check).
    assert result_content_sha("ab", "", 0, []) != result_content_sha(
        "a", "b", 0, []
    )
    # File-sha order never matters (the executor sorts too).
    assert result_content_sha("o", "e", 1, ["b" * 64, "a" * 64]) == (
        result_content_sha("o", "e", 1, ["a" * 64, "b" * 64])
    )


# ----------------------------------------------------------------- store


def make_store(tmp_path, **kwargs) -> ResultMemoStore:
    kwargs.setdefault("max_bytes", 1 << 20)
    kwargs.setdefault("max_entries", 64)
    state = kwargs.pop("state", None) or InMemoryStateStore()
    workspace = kwargs.pop("workspace", None)
    if workspace is None:
        workspace = Storage(tmp_path / "ws")
    return ResultMemoStore(tmp_path / "memo", state, workspace, **kwargs)


def make_record(stdout="hi\n", stderr="", exit_code=0, files=None):
    files = files or {}
    return {
        "stdout": stdout,
        "stderr": stderr,
        "exit_code": exit_code,
        "files": files,
        "stdout_truncated": False,
        "stderr_truncated": False,
        "warm": True,
        "phases": {"execute": 0.5},
        "result_sha": result_content_sha(
            stdout, stderr, exit_code, sorted(files.values())
        ),
    }


def key_for(scope="tenant-a", **overrides):
    return derive_key(**{**BASE_KEY, "scope": scope, "files": None, **overrides})


async def test_store_record_and_lookup_roundtrip(tmp_path):
    store = make_store(tmp_path)
    key = key_for()
    assert await store.lookup(key) is None
    assert await store.record(key, make_record()) == "admitted"
    record = await store.lookup(key)
    assert record["stdout"] == "hi\n"
    assert record["phases"] == {"execute": 0.5}
    assert store.entry_count() == 1
    assert store.total_bytes() > 0


async def test_store_kill_switch_is_inert(tmp_path):
    store = make_store(tmp_path, enabled=False)
    key = key_for()
    assert await store.lookup(key) is None
    assert await store.record(key, make_record()) == "error"
    assert store.entry_count() == 0
    assert store.total_bytes() == 0
    # Disabled store creates nothing on disk.
    assert not (tmp_path / "memo").exists()
    assert store.snapshot() == {"enabled": False}


async def test_store_first_write_wins_on_conflict(tmp_path):
    store = make_store(tmp_path)
    key = key_for()
    assert await store.record(key, make_record(stdout="first\n")) == "admitted"
    # A declared-pure run that produced DIFFERENT bytes under the same key:
    # rejected, counted, first record untouched.
    assert (
        await store.record(key, make_record(stdout="second\n")) == "conflict"
    )
    assert store.conflicts == 1
    record = await store.lookup(key)
    assert record["stdout"] == "first\n"


async def test_store_identical_rerecord_is_exists(tmp_path):
    store = make_store(tmp_path)
    key = key_for()
    await store.record(key, make_record())
    assert await store.record(key, make_record()) == "exists"
    assert store.conflicts == 0
    assert store.entry_count() == 1


async def test_store_lru_eviction_by_last_hit(tmp_path):
    clock = [0.0]
    store = make_store(tmp_path, max_entries=2, clock=lambda: clock[0])
    old, mid, new = key_for(lane=1), key_for(lane=2), key_for(lane=3)
    await store.record(old, make_record(stdout="old\n"))
    clock[0] = 1.0
    await store.record(mid, make_record(stdout="mid\n"))
    clock[0] = 2.0
    assert await store.lookup(old) is not None  # refresh: mid is now LRU
    clock[0] = 3.0
    await store.record(new, make_record(stdout="new\n"))
    assert await store.lookup(mid) is None
    assert (await store.lookup(old))["stdout"] == "old\n"
    assert (await store.lookup(new))["stdout"] == "new\n"
    assert store.entry_count() == 2


async def test_lookup_self_heals_missing_blob(tmp_path):
    store = make_store(tmp_path)
    key = key_for()
    await store.record(key, make_record())
    entry = store.state.get(MEMO_NS, key.index_key)
    await store.storage.delete(entry["record"])
    assert await store.lookup(key) is None
    # The dangling index row was removed, not left to fail every lookup.
    assert store.state.get(MEMO_NS, key.index_key) is None


async def test_lookup_validates_workspace_file_objects(tmp_path):
    workspace = Storage(tmp_path / "ws")
    present = await workspace.write(b"kept-bytes")
    store = make_store(tmp_path, workspace=workspace)
    key = key_for()
    files = {"out.txt": present, "gone.txt": "f" * 64}
    await store.record(key, make_record(files=files))
    # A referenced output object is gone from the workspace store: the hit
    # must demote to a miss (never hand out dangling object ids) and
    # self-heal the index.
    assert await store.lookup(key) is None
    assert store.state.get(MEMO_NS, key.index_key) is None


async def test_shared_scope_lookup_order(tmp_path):
    store = make_store(tmp_path, shared=True)
    assert store.scopes_for("tenant-a") == ["tenant-a", SHARED_SCOPE]
    assert store.scopes_for(SHARED_SCOPE) == [SHARED_SCOPE]
    # A shared-scope record serves any tenant's identical digest...
    shared_key = key_for(scope=SHARED_SCOPE)
    await store.record(shared_key, make_record(stdout="shared\n"))
    hit = await store.lookup(key_for(scope="tenant-b"))
    assert hit is not None and hit["stdout"] == "shared\n"
    # ...but with sharing off, the shared scope is invisible.
    solo = make_store(tmp_path / "solo", shared=False, state=store.state)
    assert solo.scopes_for("tenant-a") == ["tenant-a"]
    assert await solo.lookup(key_for(scope="tenant-b")) is None


# ------------------------------------------------- fake host + executor flow


class FakeMemoHost:
    """In-memory executor host for the memo flow: POST /execute runs a
    canned program (stdout derived from the source so distinct sources give
    distinct outputs), echoing the purity declaration + canonical result
    hash exactly like the C++ server — unless ``legacy`` (no echo, an old
    binary) or ``lie`` (echoes a wrong hash) says otherwise."""

    def __init__(self, legacy: bool = False, lie: bool = False):
        self.legacy = legacy
        self.lie = lie
        self.executes = 0
        self.pure_seen = 0  # /execute payloads that carried the pure flag
        self.requests: list[str] = []
        self.drop_decider = None  # callable() -> bool: drop this /execute
        self.files_out: dict[str, bytes] = {}

    async def handler(self, request: httpx.Request) -> httpx.Response:
        path = request.url.path
        self.requests.append(f"{request.method} {path}")
        if request.method == "POST" and path == "/execute":
            if self.drop_decider is not None and self.drop_decider():
                raise httpx.ReadError("connection dropped mid-execute")
            self.executes += 1
            payload = json.loads(await request.aread())
            if payload.get("pure"):
                self.pure_seen += 1
            source = (
                payload.get("source_code") or payload.get("source_file") or ""
            )
            out = f"ran:{hashlib.sha256(source.encode()).hexdigest()[:8]}\n"
            files = [
                {"path": rel, "sha256": sha(data)}
                for rel, data in sorted(self.files_out.items())
            ]
            body = {
                "stdout": out,
                "stderr": "",
                "exit_code": 0,
                "files": files,
                "deleted": [],
                "warm": True,
                "runner_restarted": False,
            }
            if payload.get("pure") and not self.legacy:
                echo_sha = result_content_sha(
                    out, "", 0, sorted(f["sha256"] for f in files)
                )
                body["pure"] = True
                body["result_sha256"] = (
                    "0" * 64 if self.lie else echo_sha
                )
            return httpx.Response(200, json=body)
        if request.method == "GET" and path.startswith("/workspace/"):
            rel = path[len("/workspace/") :]
            if rel in self.files_out:
                return httpx.Response(200, content=self.files_out[rel])
            return httpx.Response(404, json={"error": "not found"})
        if request.method == "GET" and path == "/workspace-manifest":
            return httpx.Response(200, json={"files": {}})
        if request.method == "POST" and path == "/reset":
            return httpx.Response(200, json={"ok": True})
        return httpx.Response(404, json={"error": "no route"})

    def transport(self) -> httpx.MockTransport:
        return httpx.MockTransport(self.handler)


class MemoBackend(FakeBackend):
    def __init__(self, host: FakeMemoHost, **kwargs):
        super().__init__(**kwargs)
        self.fake_host = host

    def http_transport(self):
        return self.fake_host.transport()


def make_stack(tmp_path, legacy=False, lie=False, **config_kwargs):
    host = FakeMemoHost(legacy=legacy, lie=lie)
    backend = MemoBackend(host)
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
        compile_cache_enabled=False,
        **config_kwargs,
    )
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    return executor, host, backend


def counter_value(counter, **labels) -> float:
    return sum(
        value
        for sample_labels, value in counter.samples()
        if all(sample_labels.get(k) == v for k, v in labels.items())
    )


async def test_pure_miss_records_then_identical_run_hits(tmp_path):
    executor, host, _ = make_stack(tmp_path)
    try:
        first = await executor.execute("print('pure')", pure=True)
        assert first.exit_code == 0
        assert first.phases["memo"] == {"state": "miss", "recorded": "admitted"}
        executes_after_miss = host.executes
        assert executes_after_miss == 1

        second = await executor.execute("print('pure')", pure=True)
        # The acceptance criterion, unit flavor: zero sandbox HTTP, zero
        # chip-seconds, identical bytes.
        assert host.executes == executes_after_miss
        assert second.stdout == first.stdout
        assert second.stderr == first.stderr
        assert second.exit_code == first.exit_code
        assert second.phases["memo"]["state"] == "hit"
        assert second.phases["chip_seconds"] == 0.0
        assert second.phases["device_op_seconds"] == 0.0
        # The recorded run's measured phases ride the memo block, so a
        # client can still see what the live execution cost.
        assert "chip_seconds" in second.phases["memo"]["recorded"]
        assert executor.result_memo.hits == 1
        assert executor.result_memo.misses == 1
        assert counter_value(
            executor.metrics.result_memo_requests, outcome="hit"
        ) == 1.0
        # A hit is a logical request on the executions surface.
        assert counter_value(executor.metrics.executions, outcome="ok") == 2.0
    finally:
        await executor.close()


async def test_different_source_misses(tmp_path):
    executor, host, _ = make_stack(tmp_path)
    try:
        await executor.execute("print('a')", pure=True)
        result = await executor.execute("print('b')", pure=True)
        assert result.phases["memo"]["state"] == "miss"
        assert host.executes == 2
    finally:
        await executor.close()


async def test_undeclared_request_never_touches_memo(tmp_path):
    executor, host, _ = make_stack(tmp_path)
    try:
        first = await executor.execute("print('x')")
        second = await executor.execute("print('x')")
        # No declaration: no memo phases key (pre-memo response shape),
        # every run executes, nothing recorded.
        assert "memo" not in first.phases and "memo" not in second.phases
        assert host.executes == 2
        assert executor.result_memo.entry_count() == 0
        # An undeclared run also never sends the pure flag on the wire.
        assert host.pure_seen == 0
    finally:
        await executor.close()


async def test_tenants_never_share_records(tmp_path):
    executor, host, _ = make_stack(tmp_path)
    try:
        await executor.execute("print('k')", pure=True, tenant="tenant-a")
        result = await executor.execute(
            "print('k')", pure=True, tenant="tenant-b"
        )
        # Identical inputs, different tenant: a MISS — per-tenant keying.
        assert result.phases["memo"]["state"] == "miss"
        assert host.executes == 2
        hit = await executor.execute(
            "print('k')", pure=True, tenant="tenant-a"
        )
        assert hit.phases["memo"]["state"] == "hit"
        assert host.executes == 2
    finally:
        await executor.close()


async def test_shared_scope_records_only_from_trusted_runs(tmp_path):
    executor, host, _ = make_stack(tmp_path, result_memo_shared=True)
    try:
        # A tenant's pure run records into ITS scope even with sharing on:
        # tenant-provenance results never become cross-tenant answers.
        await executor.execute("print('t')", pure=True, tenant="tenant-a")
        miss = await executor.execute(
            "print('t')", pure=True, tenant="tenant-b"
        )
        assert miss.phases["memo"]["state"] == "miss"
        # A control-plane-authored (trusted) run records into the shared
        # scope, and then ANY tenant's identical request hits it.
        token = _trusted_source_var.set(True)
        try:
            trusted = await executor.execute("print('s')", pure=True)
            assert trusted.phases["memo"]["recorded"] == "admitted"
        finally:
            _trusted_source_var.reset(token)
        executes = host.executes
        for tenant in ("tenant-a", "tenant-b"):
            hit = await executor.execute(
                "print('s')", pure=True, tenant=tenant
            )
            assert hit.phases["memo"]["state"] == "hit"
        assert host.executes == executes
    finally:
        await executor.close()


async def test_kill_switch_is_pre_memo_byte_for_byte(tmp_path):
    executor, host, _ = make_stack(tmp_path, result_memo_enabled=False)
    try:
        for _ in range(2):
            result = await executor.execute("print('off')", pure=True)
            assert result.exit_code == 0
            # No phases keys, no record, no memo IO — and the wire payload
            # never carries the pure flag (the executor echo arm is dark).
            assert "memo" not in result.phases
        assert host.executes == 2
        assert host.pure_seen == 0
        assert executor.result_memo.entry_count() == 0
        assert not (tmp_path / "storage" / ".result-memo").exists()
    finally:
        await executor.close()


async def test_output_files_ride_the_hit(tmp_path):
    executor, host, _ = make_stack(tmp_path)
    try:
        host.files_out = {"out.bin": b"artifact-bytes"}
        first = await executor.execute("make_artifact()", pure=True)
        assert first.phases["memo"]["recorded"] == "admitted"
        second = await executor.execute("make_artifact()", pure=True)
        assert second.phases["memo"]["state"] == "hit"
        assert second.files == first.files
        # The hit's object ids are real: the bytes are readable.
        object_id = second.files["/workspace/out.bin"]
        assert await executor.storage.read(object_id) == b"artifact-bytes"
    finally:
        await executor.close()


async def test_legacy_executor_without_echo_records_nothing(tmp_path):
    executor, host, _ = make_stack(tmp_path, legacy=True)
    try:
        result = await executor.execute("print('old')", pure=True)
        assert result.phases["memo"] == {
            "state": "miss",
            "recorded": "skipped_echo",
        }
        # Nothing recorded -> the repeat executes again.
        repeat = await executor.execute("print('old')", pure=True)
        assert repeat.phases["memo"]["state"] == "miss"
        assert host.executes == 2
    finally:
        await executor.close()


async def test_lying_echo_records_nothing(tmp_path):
    executor, host, _ = make_stack(tmp_path, lie=True)
    try:
        result = await executor.execute("print('liar')", pure=True)
        # The echoed hash does not re-derive from the wire fields the
        # Result is built from: record nothing.
        assert result.phases["memo"]["recorded"] == "skipped_echo"
        assert executor.result_memo.entry_count() == 0
    finally:
        await executor.close()


async def test_profile_and_session_requests_bypass(tmp_path):
    executor, host, _ = make_stack(tmp_path)
    try:
        result = await executor.execute(
            "print('p')", pure=True, profile=True
        )
        assert result.phases["memo"] == {"state": "bypass"}
        assert executor.result_memo.entry_count() == 0
        # The admission classifier (sessions bypass the same way; the env
        # spelling of profiling too).
        key, state = executor._memo_admission(
            True,
            executor_id="sess-1",
            profile=False,
            source_code="x",
            source_file=None,
            files=None,
            env=None,
            chip_count=None,
            tenant=None,
            limits=None,
        )
        assert (key, state) == (None, "bypass")
        key, state = executor._memo_admission(
            True,
            executor_id=None,
            profile=False,
            source_code="x",
            source_file=None,
            files=None,
            env={"APP_JAX_PROFILE": "1"},
            chip_count=None,
            tenant=None,
            limits=None,
        )
        assert (key, state) == (None, "bypass")
    finally:
        await executor.close()


async def test_truncated_output_never_recorded(tmp_path):
    executor, host, _ = make_stack(tmp_path)
    try:
        real_handler = host.handler

        async def truncating_handler(request):
            resp = await real_handler(request)
            if request.url.path == "/execute":
                body = json.loads(resp.content)
                body["stdout_truncated"] = True
                return httpx.Response(200, json=body)
            return resp

        host_transport = httpx.MockTransport(truncating_handler)
        executor.backend.http_transport = lambda: host_transport
        result = await executor.execute("print('big')", pure=True)
        assert result.phases["memo"]["recorded"] == "skipped_truncated"
        assert executor.result_memo.entry_count() == 0
    finally:
        await executor.close()


# -------------------------------------------------- connection-reuse proof


async def test_sequential_dispatches_reuse_one_tcp_connection(tmp_path):
    """Satellite regression: the tuned httpx.Limits keep-alive pool means
    two sequential requests to one host share ONE TCP connection — proven
    against a real socket (MockTransport has no network stream), with the
    reuse observable on executor_connections_reused_total."""
    connections = []

    async def handle(reader, writer):
        connections.append(writer)
        try:
            while True:
                data = await reader.readuntil(b"\r\n\r\n")
                if not data:
                    break
                body = b"{}"
                writer.write(
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                    b"\r\n" + body
                )
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    executor, _, backend = make_stack(tmp_path)
    # Real wire: shadow the fake transport on THIS instance so _http_client
    # builds the tuned keep-alive pool over actual TCP.
    backend.http_transport = lambda: None
    try:
        client = executor._http_client()
        base = f"http://127.0.0.1:{port}"
        for _ in range(3):
            resp = await client.get(f"{base}/workspace-manifest")
            assert resp.status_code == 200
        assert len(connections) == 1, "keep-alive pool re-handshook"
        assert (
            counter_value(executor.metrics.executor_connections_reused) >= 2
        )
    finally:
        await executor.close()
        server.close()
        await server.wait_closed()


# ------------------------------------------------------------------- chaos


@pytest.mark.parametrize("seed", CHAOS_SEEDS)
async def test_chaos_drops_mid_store_never_admit_partial_results(
    tmp_path, seed
):
    """Seeded wire drops on /execute plus seeded record-blob write faults:
    whatever subset of runs survives, every index entry's blob is complete
    valid JSON whose result_sha re-derives from its own fields, and every
    later hit serves bytes identical to a live run's."""
    rng = random.Random(seed)
    executor, host, _ = make_stack(tmp_path)
    host.drop_decider = lambda: rng.random() < 0.4
    store = executor.result_memo
    real_write = store.storage.write

    async def flaky_write(data):
        if rng.random() < 0.3:
            raise OSError("disk fault injected mid-store")
        return await real_write(data)

    store.storage.write = flaky_write
    try:
        outcomes = {}
        for i in range(12):
            source = f"print({i % 4})"
            try:
                result = await executor.execute(source, pure=True)
            except Exception:
                continue  # wire drop surfaced as an infra error: fine
            outcomes.setdefault(source, result)
        # Invariant 1: every index entry deserializes completely and its
        # recorded hash re-derives from its own recorded fields.
        for index_key, entry in store.state.items(MEMO_NS).items():
            blob = await store.storage.read(entry["record"])
            record = json.loads(blob)
            assert record["result_sha"] == result_content_sha(
                record["stdout"],
                record["stderr"],
                record["exit_code"],
                sorted(record["files"].values()),
            ), f"partial/corrupt record admitted at {index_key}"
        # Invariant 2: with faults off, a hit serves exactly the bytes the
        # live run produced.
        host.drop_decider = None
        store.storage.write = real_write
        for source, live in outcomes.items():
            replay = await executor.execute(source, pure=True)
            assert replay.stdout == live.stdout
            assert replay.exit_code == live.exit_code
    finally:
        store.storage.write = real_write
        await executor.close()


@pytest.mark.parametrize("seed", CHAOS_SEEDS)
async def test_chaos_kill_switch_is_pre_memo_exact(tmp_path, seed):
    """The same fault plan with the memo disabled: byte-for-byte pre-memo
    behavior — no memo phases, no memo dirs, no record IO, regardless of
    where the faults land."""
    rng = random.Random(seed)
    executor, host, _ = make_stack(tmp_path, result_memo_enabled=False)
    host.drop_decider = lambda: rng.random() < 0.4
    try:
        for i in range(8):
            try:
                result = await executor.execute(f"print({i})", pure=True)
            except Exception:
                continue
            assert "memo" not in result.phases
        assert executor.result_memo.entry_count() == 0
        assert not (tmp_path / "storage" / ".result-memo").exists()
    finally:
        await executor.close()
