"""Kubernetes backend + kubectl adapter against a fake kubectl binary.

The fake records every invocation (argv + stdin) into a directory and plays
back canned responses, so manifest shape, TPU scheduling fields, wait/delete
flows, and error paths are all testable without a cluster — the gap the
reference left open (SURVEY.md §4: no unit layer, no fake backends).
"""

import json
import os
import stat
from pathlib import Path

import pytest

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.base import SandboxSpawnError
from bee_code_interpreter_fs_tpu.services.backends.kubernetes import (
    KubernetesSandboxBackend,
    deep_merge,
)
from bee_code_interpreter_fs_tpu.services.kubectl import Kubectl, KubectlError

FAKE_KUBECTL = r"""#!/usr/bin/env python3
import json, os, sys
state = os.environ["FAKE_KUBECTL_DIR"]
stdin = sys.stdin.read() if not sys.stdin.isatty() else ""
with open(os.path.join(state, "calls.jsonl"), "a") as f:
    f.write(json.dumps({"argv": sys.argv[1:], "stdin": stdin}) + "\n")
args = sys.argv[1:]
verb = args[0] if args else ""
if os.path.exists(os.path.join(state, "fail_" + verb)):
    sys.stderr.write(verb + " exploded\n")
    sys.exit(1)
if verb == "create":
    manifest = json.loads(stdin)
    with open(os.path.join(state, manifest["metadata"]["name"] + ".json"), "w") as f:
        json.dump(manifest, f)
    print(json.dumps(manifest))
elif verb == "get":
    name = args[2] if len(args) > 2 and not args[2].startswith("-") else None
    path = os.path.join(state, (name or "none") + ".json")
    if name and os.path.exists(path):
        manifest = json.load(open(path))
        manifest.setdefault("status", {})["podIP"] = "10.0.0.7"
        status_path = os.path.join(state, "status.json")
        if os.path.exists(status_path):
            manifest["status"].update(json.load(open(status_path)))
        manifest["metadata"]["uid"] = "uid-" + name
        print(json.dumps(manifest))
    else:
        sys.stderr.write("NotFound\n")
        sys.exit(1)
elif verb == "wait":
    print("pod condition met")
elif verb == "delete":
    print("pod deleted")
elif verb == "logs":
    logs_path = os.path.join(state, "logs.txt")
    if os.path.exists(logs_path):
        print(open(logs_path).read())
    else:
        sys.stderr.write("no logs\n")
        sys.exit(1)
else:
    sys.exit(2)
"""


@pytest.fixture
def fake_kubectl(tmp_path, monkeypatch):
    state = tmp_path / "state"
    state.mkdir()
    binary = tmp_path / "kubectl"
    binary.write_text(FAKE_KUBECTL)
    binary.chmod(binary.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("FAKE_KUBECTL_DIR", str(state))
    monkeypatch.delenv("HOSTNAME", raising=False)

    def calls():
        path = state / "calls.jsonl"
        if not path.exists():
            return []
        return [json.loads(line) for line in path.read_text().splitlines()]

    return Kubectl(binary=str(binary)), state, calls


async def _await_calls(calls, predicate, *, timeout=10.0, settle=0.2):
    """Deadline-poll the fake-kubectl call log until `predicate(calls())` is
    truthy, then hold one settle interval so a spurious LATE extra call
    (e.g. a double-delete regression) still fails the caller's exact
    asserts. Replaces the fixed 0.2s sleeps that flaked whenever a loaded
    host ran the fire-and-forget delete subprocesses slowly (the recurring
    F's documented in CHANGES.md)."""
    import asyncio

    deadline = asyncio.get_running_loop().time() + timeout
    while not predicate(calls()) and asyncio.get_running_loop().time() < deadline:
        await asyncio.sleep(0.05)
    await asyncio.sleep(settle)
    return calls()


def _backend(kubectl, **config_kwargs) -> KubernetesSandboxBackend:
    config = Config(
        tpu_node_selector={
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "2x2",
        },
        **config_kwargs,
    )
    return KubernetesSandboxBackend(config, kubectl=kubectl)


async def test_spawn_cpu_pod(fake_kubectl):
    kubectl, state, calls = fake_kubectl
    backend = _backend(kubectl)
    sandbox = await backend.spawn(chip_count=0)
    assert sandbox.url == "http://10.0.0.7:8000"
    manifest = json.loads((state / (sandbox.id + ".json")).read_text())
    container = manifest["spec"]["containers"][0]
    assert manifest["metadata"]["labels"]["app"] == "code-executor"
    assert "nodeSelector" not in manifest["spec"]
    assert "google.com/tpu" not in json.dumps(container["resources"])
    verbs = [c["argv"][0] for c in calls()]
    assert verbs == ["create", "wait", "get"]


async def test_spawn_tpu_pod_gets_chips_and_selector(fake_kubectl):
    kubectl, state, _ = fake_kubectl
    backend = _backend(kubectl)
    sandbox = await backend.spawn(chip_count=4)
    manifest = json.loads((state / (sandbox.id + ".json")).read_text())
    container = manifest["spec"]["containers"][0]
    assert container["resources"]["limits"]["google.com/tpu"] == "4"
    assert container["resources"]["requests"]["google.com/tpu"] == "4"
    assert (
        manifest["spec"]["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2"
    )
    env = {e["name"]: e["value"] for e in container["env"]}
    assert env["APP_CHIP_COUNT"] == "4"
    assert env["APP_NUMPY_DISPATCH"] == "1"


async def test_pod_spec_extra_merges(fake_kubectl):
    kubectl, state, _ = fake_kubectl
    backend = _backend(
        kubectl,
        executor_pod_spec_extra={
            "tolerations": [{"key": "google.com/tpu", "operator": "Exists"}],
            "containers": [],  # list merge keeps the executor container
        },
        executor_container_resources={"limits": {"memory": "2Gi"}},
    )
    sandbox = await backend.spawn(chip_count=4)
    manifest = json.loads((state / (sandbox.id + ".json")).read_text())
    assert manifest["spec"]["tolerations"][0]["key"] == "google.com/tpu"
    limits = manifest["spec"]["containers"][0]["resources"]["limits"]
    assert limits == {"memory": "2Gi", "google.com/tpu": "4"}


def test_compile_cache_volume_mounted(fake_kubectl):
    """The cache dir is a real volume (emptyDir by default), not an env var
    pointing at the container overlay: the pod-side path is guaranteed
    writable and survives container restarts within the pod."""
    kubectl, _, _ = fake_kubectl
    backend = _backend(kubectl)
    manifest = backend.pod_manifest("p", 0, None)
    cache_dir = backend.config.jax_compilation_cache_dir
    assert manifest["spec"]["volumes"] == [
        {"name": "jax-compile-cache", "emptyDir": {}}
    ]
    container = manifest["spec"]["containers"][0]
    assert container["volumeMounts"] == [
        {"name": "jax-compile-cache", "mountPath": cache_dir}
    ]
    env = {e["name"]: e["value"] for e in container["env"]}
    assert env["JAX_COMPILATION_CACHE_DIR"] == cache_dir
    assert env["APP_COMPILE_CACHE"] == "1"
    # emptyDir is pod-private: per-sandbox taint vouches for it, so the
    # executor's harvest gate sees a private dir.
    assert backend.compile_cache_dir_scope == "private"


def test_compile_cache_volume_source_knob(fake_kubectl):
    kubectl, _, _ = fake_kubectl
    backend = _backend(
        kubectl,
        compile_cache_volume_source={
            "persistentVolumeClaim": {"claimName": "fleet-jax-cache"}
        },
    )
    manifest = backend.pod_manifest("p", 0, None)
    assert manifest["spec"]["volumes"][0]["persistentVolumeClaim"] == {
        "claimName": "fleet-jax-cache"
    }
    # A shared PVC is writable by other pods' tenants — parties this
    # control plane never sees — so the harvest gate must see "external"
    # (structurally never harvested).
    assert backend.compile_cache_dir_scope == "external"


def test_compile_cache_kill_switch_reaches_pod_env(fake_kubectl):
    kubectl, _, _ = fake_kubectl
    backend = _backend(kubectl, compile_cache_enabled=False)
    manifest = backend.pod_manifest("p", 0, None)
    container = manifest["spec"]["containers"][0]
    env = {e["name"]: e["value"] for e in container["env"]}
    # The per-pod cache dir still works host-locally; only the fleet
    # endpoints are off.
    assert env["APP_COMPILE_CACHE"] == "0"
    # No volume at the cache dir when the cache is disabled: the executor's
    # reset preserve is off, so a mounted-but-unpreserved cache dir under
    # /var/tmp would survive each wipe as an empty mount point (the wipe
    # forgives the mount's EBUSY) — skipping the mount restores the exact
    # pre-cache pod spec and turnover instead.
    assert "volumes" not in manifest["spec"]
    assert "volumeMounts" not in container
    # /var/tmp stays on the wipe list: with no mount the cache dir is
    # ordinary residue, removed at turnover — exact pre-cache behavior.
    assert "/var/tmp" in env["APP_RESET_EXTRA_WIPE_DIRS"]


def test_no_cache_dir_means_no_volume(fake_kubectl):
    kubectl, _, _ = fake_kubectl
    backend = _backend(kubectl, jax_compilation_cache_dir="")
    manifest = backend.pod_manifest("p", 0, None)
    assert "volumes" not in manifest["spec"]
    container = manifest["spec"]["containers"][0]
    assert "volumeMounts" not in container
    env_names = {e["name"] for e in container["env"]}
    assert "JAX_COMPILATION_CACHE_DIR" not in env_names


async def test_spawn_failure_deletes_pod(fake_kubectl):
    kubectl, state, calls = fake_kubectl
    (state / "fail_wait").touch()
    backend = _backend(kubectl)
    with pytest.raises(SandboxSpawnError):
        await backend.spawn(chip_count=0)
    # Fire-and-forget delete: poll with a deadline instead of a fixed sleep.
    seen = await _await_calls(
        calls, lambda cs: any(c["argv"][0] == "delete" for c in cs)
    )
    assert "delete" in [c["argv"][0] for c in seen]


async def test_spawn_failure_includes_pod_diagnostics(fake_kubectl):
    """A failed spawn must carry WHY: pod phase/conditions/container state
    plus the kubectl-logs tail — the k8s analogue of the local backend's
    stderr tail (VERDICT r2 #7)."""
    kubectl, state, calls = fake_kubectl
    (state / "fail_wait").touch()
    (state / "status.json").write_text(
        json.dumps(
            {
                "phase": "Pending",
                "conditions": [
                    {
                        "type": "Ready",
                        "status": "False",
                        "reason": "ContainersNotReady",
                        "message": "containers with unready status: [executor]",
                    }
                ],
                "containerStatuses": [
                    {
                        "name": "executor",
                        "state": {
                            "waiting": {
                                "reason": "CrashLoopBackOff",
                                "message": "back-off 40s restarting failed container",
                            }
                        },
                    }
                ],
            }
        )
    )
    (state / "logs.txt").write_text(
        "RuntimeError: TPU initialization failed: device busy\n"
    )
    backend = _backend(kubectl)
    with pytest.raises(SandboxSpawnError) as exc_info:
        await backend.spawn(chip_count=0)
    message = str(exc_info.value)
    assert "did not become ready" in message
    assert "phase=Pending" in message
    assert "CrashLoopBackOff" in message
    assert "TPU initialization failed: device busy" in message
    await backend.close()  # drain the fire-and-tracked failure-path delete


async def test_spawn_failure_diagnostics_degrade_gracefully(fake_kubectl):
    """Logs/status fetch failures must not mask the original error."""
    kubectl, state, calls = fake_kubectl
    (state / "fail_wait").touch()
    (state / "fail_get").touch()  # no logs.txt either -> logs verb fails
    backend = _backend(kubectl)
    with pytest.raises(SandboxSpawnError) as exc_info:
        await backend.spawn(chip_count=0)
    message = str(exc_info.value)
    assert "did not become ready" in message
    assert "pod status unavailable" in message
    assert "pod logs unavailable" in message
    await backend.close()  # drain the fire-and-tracked failure-path delete


async def test_delete_and_close(fake_kubectl):
    kubectl, state, calls = fake_kubectl
    backend = _backend(kubectl)
    s1 = await backend.spawn()
    s2 = await backend.spawn()
    await backend.delete(s1)
    await backend.close()
    deletes = [c["argv"] for c in calls() if c["argv"][0] == "delete"]
    deleted = {argv[2] for argv in deletes}
    assert deleted == {s1.id, s2.id}
    assert any("--ignore-not-found" in argv for argv in deletes[0:1])


async def test_owner_reference_attached_in_cluster(fake_kubectl, monkeypatch):
    kubectl, state, _ = fake_kubectl
    # Pretend we run as pod "control-plane-0".
    (state / "control-plane-0.json").write_text(
        json.dumps({"metadata": {"name": "control-plane-0"}})
    )
    monkeypatch.setenv("HOSTNAME", "control-plane-0")
    backend = _backend(kubectl)
    sandbox = await backend.spawn()
    manifest = json.loads((state / (sandbox.id + ".json")).read_text())
    owner = manifest["metadata"]["ownerReferences"][0]
    assert owner["name"] == "control-plane-0"
    assert owner["uid"] == "uid-control-plane-0"


async def test_kubectl_error_surface(fake_kubectl):
    kubectl, state, _ = fake_kubectl
    (state / "fail_create").touch()
    backend = _backend(kubectl)
    with pytest.raises(SandboxSpawnError, match="create failed"):
        await backend.spawn()


async def test_kubectl_flags_and_json(fake_kubectl):
    kubectl, state, calls = fake_kubectl
    ns = Kubectl(binary=kubectl.binary, namespace="bee")
    await ns.wait("pod", "p1", **{"for": "condition=Ready"}, timeout="60s")
    argv = calls()[-1]["argv"]
    assert argv[:2] == ["wait", "pod/p1"]
    assert "--namespace=bee" in argv
    assert "--for=condition=Ready" in argv
    assert "--timeout=60s" in argv


def test_deep_merge():
    base = {"a": {"x": 1}, "list": [1], "keep": True}
    extra = {"a": {"y": 2}, "list": [2], "new": "v"}
    assert deep_merge(base, extra) == {
        "a": {"x": 1, "y": 2},
        "list": [1, 2],
        "keep": True,
        "new": "v",
    }


# ----------------------------------------------------------- multi-host slices


async def test_spawn_multihost_group(fake_kubectl):
    """chip_count > chips-per-host → one pod per host, coordinator bootstrap
    (SURVEY.md §7.6): pod 0 is created first, peers get its IP as the
    jax.distributed coordinator address, every pod requests only its own
    host's chips, and the Sandbox aggregates all host URLs."""
    kubectl, state, calls = fake_kubectl
    backend = _backend(kubectl, tpu_chips_per_host=4, coordinator_port=8476)
    sandbox = await backend.spawn(chip_count=8)

    assert sandbox.chip_count == 8
    assert sandbox.num_hosts == 2
    assert sandbox.host_urls == ["http://10.0.0.7:8000", "http://10.0.0.7:8000"]
    assert sandbox.url == sandbox.host_urls[0]
    assert sandbox.meta["pods"] == [f"{sandbox.id}-h0", f"{sandbox.id}-h1"]

    manifests = [
        json.loads((state / f"{sandbox.id}-h{i}.json").read_text()) for i in range(2)
    ]
    for i, manifest in enumerate(manifests):
        container = manifest["spec"]["containers"][0]
        # each host requests its own 4 chips, not the slice's 8
        assert container["resources"]["limits"]["google.com/tpu"] == "4"
        env = {e["name"]: e["value"] for e in container["env"]}
        assert env["APP_NUM_HOSTS"] == "2"
        assert env["APP_HOST_ID"] == str(i)
        assert manifest["metadata"]["labels"]["code-executor/slice-group"] == sandbox.id
        # libtpu single-slice multi-host worker identity + stable DNS names
        assert env["TPU_WORKER_ID"] == str(i)
        assert env["TPU_WORKER_HOSTNAMES"] == (
            f"{sandbox.id}-h0.{sandbox.id},{sandbox.id}-h1.{sandbox.id}"
        )
        assert manifest["spec"]["hostname"] == f"{sandbox.id}-h{i}"
        assert manifest["spec"]["subdomain"] == sandbox.id
    env0 = {e["name"]: e["value"] for e in manifests[0]["spec"]["containers"][0]["env"]}
    env1 = {e["name"]: e["value"] for e in manifests[1]["spec"]["containers"][0]["env"]}
    assert env0["APP_COORDINATOR_ADDR"] == "0.0.0.0:8476"  # host 0 binds
    assert env1["APP_COORDINATOR_ADDR"] == "10.0.0.7:8476"  # peers dial host 0

    # the headless service gives not-yet-Ready pods resolvable names
    service = json.loads((state / f"{sandbox.id}.json").read_text())
    assert service["kind"] == "Service"
    assert service["spec"]["clusterIP"] == "None"
    assert service["spec"]["publishNotReadyAddresses"] is True
    assert service["spec"]["selector"] == {
        "code-executor/slice-group": sandbox.id
    }

    # service → pod 0 created → IP polled → peer created → both waited on
    verbs = [c["argv"][0] for c in calls()]
    assert verbs[0] == "create"  # the service
    assert verbs[1] == "create"  # pod 0
    assert "get" in verbs[2:verbs.index("create", 2)]  # IP poll before peer create
    assert verbs.count("create") == 3
    assert verbs.count("wait") == 2


async def test_multihost_topology_selector_by_slice_size(fake_kubectl):
    """ADVICE r1 #1: the slice's TOTAL chip count picks the node topology —
    a static single-host selector would scatter group pods across unrelated
    slices where the ICI mesh cannot form."""
    kubectl, state, _ = fake_kubectl
    backend = _backend(
        kubectl,
        tpu_chips_per_host=4,
        tpu_node_selector_by_chip_count={
            "8": {
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                "cloud.google.com/gke-tpu-topology": "2x4",
            }
        },
    )
    sandbox = await backend.spawn(chip_count=8)
    for i in range(2):
        manifest = json.loads((state / f"{sandbox.id}-h{i}.json").read_text())
        assert (
            manifest["spec"]["nodeSelector"]["cloud.google.com/gke-tpu-topology"]
            == "2x4"
        )
    # single-host spawns keep the static selector
    single = await backend.spawn(chip_count=4)
    manifest = json.loads((state / f"{single.id}.json").read_text())
    assert (
        manifest["spec"]["nodeSelector"]["cloud.google.com/gke-tpu-topology"]
        == "2x2"
    )


async def test_multihost_delete_removes_all_pods(fake_kubectl):
    kubectl, state, calls = fake_kubectl
    backend = _backend(kubectl, tpu_chips_per_host=4)
    sandbox = await backend.spawn(chip_count=16)
    assert sandbox.num_hosts == 4
    await backend.delete(sandbox)
    # The headless-service delete is fire-and-tracked: poll for the full
    # expected set (4 pods + the service) instead of a fixed sleep.
    expected = {f"{sandbox.id}-h{i}" for i in range(4)} | {sandbox.id}
    seen = await _await_calls(
        calls,
        lambda cs: {c["argv"][2] for c in cs if c["argv"][0] == "delete"}
        >= expected,
    )
    deleted = {c["argv"][2] for c in seen if c["argv"][0] == "delete"}
    assert deleted == expected


async def test_multihost_spawn_failure_cleans_whole_group(fake_kubectl):
    kubectl, state, calls = fake_kubectl
    (state / "fail_wait").touch()
    backend = _backend(kubectl, tpu_chips_per_host=4)
    with pytest.raises(SandboxSpawnError):
        await backend.spawn(chip_count=8)
    seen = await _await_calls(
        calls,
        lambda cs: len({c["argv"][2] for c in cs if c["argv"][0] == "delete"})
        >= 3,
    )
    deleted = {c["argv"][2] for c in seen if c["argv"][0] == "delete"}
    # both pods AND the group's headless service: no partial slices left
    assert len(deleted) == 3


def test_num_hosts_for_tiling():
    from bee_code_interpreter_fs_tpu.services.backends.base import num_hosts_for

    assert num_hosts_for(0, 4) == 1      # CPU lane
    assert num_hosts_for(1, 4) == 1      # sub-host slice (v5e-1)
    assert num_hosts_for(4, 4) == 1      # full host
    assert num_hosts_for(8, 4) == 2
    assert num_hosts_for(16, 4) == 4
    with pytest.raises(ValueError, match="does not tile"):
        num_hosts_for(6, 4)              # would silently reserve 8 chips
    with pytest.raises(ValueError, match="does not tile"):
        num_hosts_for(9, 4)


async def test_non_tiling_chip_count_rejected_before_spawn(fake_kubectl, tmp_path):
    from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
    from bee_code_interpreter_fs_tpu.services.storage import Storage

    kubectl, state, calls = fake_kubectl
    backend = _backend(kubectl, tpu_chips_per_host=4)
    executor = CodeExecutor(backend, Storage(tmp_path / "storage"), backend.config)
    with pytest.raises(ValueError, match="does not tile"):
        await executor.execute("print(1)", chip_count=6)
    assert calls() == []  # rejected before any kubectl traffic
    await executor.close()


# ------------------------------------------- pod-watch breaker integration


async def test_group_watch_failures_feed_lane_breaker(fake_kubectl):
    """Satellite (ISSUE 2): multi-host pod-watch failures record one lane
    strike PER failed host watch, the moment the watch fails — not one
    aggregate strike when the whole group spawn surfaces."""
    from bee_code_interpreter_fs_tpu.services.circuit_breaker import BreakerBoard

    kubectl, state, _ = fake_kubectl
    (state / "fail_wait").touch()  # every readiness watch fails
    backend = _backend(kubectl, tpu_chips_per_host=4)
    board = BreakerBoard(failure_threshold=100, cooldown=60.0)
    backend.bind_breakers(board)
    with pytest.raises(SandboxSpawnError):
        await backend.spawn(chip_count=8)  # 2 hosts -> 2 failed watches
    assert board.lane(8)._failures == 2


async def test_single_host_watch_failure_leaves_strike_to_executor(fake_kubectl):
    """Single-host spawns surface ONE SandboxSpawnError that the executor's
    spawn ladder counts; the backend must not also record it (double
    strike)."""
    from bee_code_interpreter_fs_tpu.services.circuit_breaker import BreakerBoard

    kubectl, state, _ = fake_kubectl
    (state / "fail_wait").touch()
    backend = _backend(kubectl)
    board = BreakerBoard(failure_threshold=100, cooldown=60.0)
    backend.bind_breakers(board)
    with pytest.raises(SandboxSpawnError):
        await backend.spawn(chip_count=0)
    assert board.lane(0)._failures == 0


async def test_pod_ip_watch_aborts_when_lane_opens(fake_kubectl):
    """The coordinator pod-IP poll is breaker-aware: once the lane opens
    (e.g. a sibling's failures crossed the threshold), the watch aborts
    immediately instead of polling blind until its own timeout."""
    from bee_code_interpreter_fs_tpu.services.circuit_breaker import BreakerBoard

    kubectl, state, _ = fake_kubectl
    backend = _backend(kubectl, executor_pod_ready_timeout=30.0)
    board = BreakerBoard(failure_threshold=1, cooldown=60.0)
    backend.bind_breakers(board)
    board.lane(8).record_failure()  # opens at threshold 1
    with pytest.raises(SandboxSpawnError, match="circuit opened"):
        await backend._wait_pod_ip("nonexistent-pod", 8)


async def test_fault_wrapper_passes_breakers_through(fake_kubectl):
    from bee_code_interpreter_fs_tpu.services.backends.faults import (
        FaultInjectingBackend,
        FaultSpec,
    )
    from bee_code_interpreter_fs_tpu.services.circuit_breaker import BreakerBoard

    kubectl, _, _ = fake_kubectl
    inner = _backend(kubectl)
    wrapped = FaultInjectingBackend(inner, FaultSpec.parse("seed:1"))
    board = BreakerBoard()
    wrapped.bind_breakers(board)
    assert inner._breakers is board


async def test_pool_capacity_per_lane_overrides(fake_kubectl):
    """tpu_warm_pool_capacity_by_chip_count: the physical ceiling the
    autoscaler's dynamic targets are clamped under, declared per lane — a
    cluster with three 4-chip slices can pool three warm 4-chip pods while
    bigger lanes keep the flat default."""
    kubectl, _, _ = fake_kubectl
    backend = _backend(
        kubectl,
        tpu_warm_pool_capacity=1,
        tpu_warm_pool_capacity_by_chip_count={"4": 3},
    )
    assert backend.pool_capacity(0) is None  # CPU lanes stay unconstrained
    assert backend.pool_capacity(4) == 3
    assert backend.pool_capacity(8) == 1  # flat default
