"""Executor-level batched-dispatch tests: eligible small jobs coalesce into
ONE fused sandbox round-trip, per-job results demux back to each caller, and
every batch-level fault falls back to the serial path — the ISSUE's demux
edge cases (a typed violation 422s ITS job while batchmates stay clean; a
batch-partner crash reruns everyone serially; the kill switch restores the
serial path byte-for-byte).
"""

import asyncio

import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.errors import LimitExceededError
from bee_code_interpreter_fs_tpu.services.storage import Storage

LANE = 4  # a multi-chip, single-host lane (tpu_chips_per_host default 4)


def job_entry(i, **extra):
    return {
        "workdir": f".batch-1/job-{i}",
        "stdout": f"job {i} ok\n",
        "stderr": "",
        "exit_code": 0,
        "files": [],
        "duration_s": 0.01,
        "start_offset_s": 0.001 * i,
        **extra,
    }


def batch_body(n, **extra):
    return {
        "results": [job_entry(i) for i in range(n)],
        "warm": True,
        "runner_restarted": False,
        **extra,
    }


class Harness:
    """CodeExecutor over FakeBackend with both wire hops faked: records
    every serial /execute and every fused /execute-batch the orchestrator
    attempts, so tests can assert exactly which path served a request."""

    def __init__(self, executor: CodeExecutor):
        self.serial_calls = []
        self.batch_calls = []
        self.batch_response = None  # dict, Exception, or callable(payload)

        async def fake_post_execute(client, base, payload, timeout, sandbox):
            self.serial_calls.append(payload)
            return {
                "stdout": "serial ok\n",
                "stderr": "",
                "exit_code": 0,
                "files": [],
                "warm": True,
            }

        async def fake_post_batch(client, base, payload, timeout, sandbox):
            self.batch_calls.append(payload)
            response = self.batch_response
            if callable(response):
                response = response(payload)
            if isinstance(response, Exception):
                raise response
            if response is None:
                response = batch_body(len(payload["jobs"]))
            return response

        executor._post_execute = fake_post_execute
        executor._post_execute_batch = fake_post_batch


def make_executor(tmp_path, **config_kwargs):
    config_kwargs.setdefault("batch_window_ms", 20.0)
    config_kwargs.setdefault("batch_max_jobs", 4)
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
        **config_kwargs,
    )
    backend = FakeBackend()
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    harness = Harness(executor)
    return executor, harness


async def drain(executor: CodeExecutor) -> None:
    for _ in range(200):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


async def test_full_batch_one_dispatch_demuxed_results(tmp_path):
    executor, harness = make_executor(tmp_path)
    try:
        results = await asyncio.gather(
            *(
                executor.execute(f"print({i})", chip_count=LANE)
                for i in range(4)
            )
        )
        # ONE fused round-trip served all four requests...
        assert len(harness.batch_calls) == 1
        assert len(harness.serial_calls) == 0
        payload = harness.batch_calls[0]
        assert [j["source_code"] for j in payload["jobs"]] == [
            f"print({i})" for i in range(4)
        ]
        # ...with the device-axis placement hint per job...
        assert [j["device_index"] for j in payload["jobs"]] == [0, 1, 2, 3]
        # ...and each caller got ITS job's demuxed result.
        for i, result in enumerate(results):
            assert result.stdout == f"job {i} ok\n"
            assert result.exit_code == 0
            assert result.phases["batch_index"] == float(i)
            assert result.phases["batch_jobs"] == 4.0
        # Occupancy fed the scheduler (full batch = 1.0).
        assert executor.scheduler.batch_occupancies()[LANE] == 1.0
        # The batch demux coordinates ride in phases but are NOT latencies:
        # they must never pollute the phase_seconds histogram (found live —
        # batch_jobs=8.0 read as an 8-second sample).
        rendered = executor.metrics.registry.render()
        assert 'phase="batch_jobs"' not in rendered
        assert 'phase="batch_index"' not in rendered
    finally:
        await executor.close()


async def test_job_violation_422s_its_caller_batchmates_stay_clean(tmp_path):
    """One job in the batch hits a typed in-process limit violation: ITS
    caller gets the 422-mapped LimitExceededError, every batchmate gets a
    clean result — a violation inside a batch never corrupts a partner."""
    executor, harness = make_executor(tmp_path)

    def response(payload):
        body = batch_body(len(payload["jobs"]))
        body["results"][1].update(
            {"exit_code": 1, "violation": "oom", "stderr": "MemoryError"}
        )
        return body

    harness.batch_response = response
    try:
        outcomes = await asyncio.gather(
            *(
                executor.execute(f"print({i})", chip_count=LANE)
                for i in range(4)
            ),
            return_exceptions=True,
        )
        assert isinstance(outcomes[1], LimitExceededError)
        assert outcomes[1].kind == "oom"
        assert outcomes[1].continuable  # runner survived: recycle, no strike
        for i in (0, 2, 3):
            assert outcomes[i].stdout == f"job {i} ok\n"
            assert outcomes[i].exit_code == 0
        # The violation was counted on the lane like any serial violation.
        assert (
            executor.metrics.limit_violations._values[(str(LANE), "oom")]
            == 1.0
        )
    finally:
        await executor.close()


async def test_batch_partner_crash_falls_back_to_serial(tmp_path):
    """The warm runner died mid-batch (one partner took the process down):
    every job transparently reruns on the serial path and succeeds — no
    request fails BECAUSE it was batched."""
    executor, harness = make_executor(tmp_path)
    harness.batch_response = batch_body(
        4, runner_restarted=True, timed_out=True
    )
    try:
        results = await asyncio.gather(
            *(
                executor.execute(f"print({i})", chip_count=LANE)
                for i in range(4)
            )
        )
        assert len(harness.batch_calls) == 1
        assert len(harness.serial_calls) == 4  # everyone re-ran serially
        assert all(r.stdout == "serial ok\n" for r in results)
        assert all(r.exit_code == 0 for r in results)
    finally:
        await executor.close()


async def test_batch_level_violation_falls_back_for_individual_verdicts(
    tmp_path,
):
    """A watchdog-attributed BATCH-level violation (one address space —
    unattributable to a job here): the fused dispatch aborts and the serial
    rerun owns each job's individual verdict."""
    executor, harness = make_executor(tmp_path)
    harness.batch_response = batch_body(4, violation="cpu_time")
    try:
        results = await asyncio.gather(
            *(
                executor.execute(f"print({i})", chip_count=LANE)
                for i in range(4)
            )
        )
        assert len(harness.serial_calls) == 4
        assert all(r.exit_code == 0 for r in results)
    finally:
        await executor.close()


async def test_tenants_never_share_a_dispatch(tmp_path):
    executor, harness = make_executor(tmp_path, batch_max_jobs=2)
    try:
        await asyncio.gather(
            executor.execute("print(0)", chip_count=LANE, tenant="alice"),
            executor.execute("print(1)", chip_count=LANE, tenant="alice"),
            executor.execute("print(0)", chip_count=LANE, tenant="bob"),
            executor.execute("print(1)", chip_count=LANE, tenant="bob"),
        )
        assert len(harness.batch_calls) == 2  # one dispatch PER tenant
        assert all(len(p["jobs"]) == 2 for p in harness.batch_calls)
    finally:
        await executor.close()


async def test_kill_switch_restores_serial_path(tmp_path):
    executor, harness = make_executor(tmp_path, batching_enabled=False)
    try:
        results = await asyncio.gather(
            *(
                executor.execute(f"print({i})", chip_count=LANE)
                for i in range(4)
            )
        )
        assert executor.batcher is None
        assert len(harness.batch_calls) == 0
        assert len(harness.serial_calls) == 4
        assert all(r.stdout == "serial ok\n" for r in results)
    finally:
        await executor.close()


async def test_ineligible_requests_take_the_serial_path(tmp_path):
    """Single-chip lanes, file-carrying requests, deadlines, and sessions
    never enter the batching window."""
    executor, harness = make_executor(tmp_path)
    try:
        # Lane 0 (default / single-chip): serial.
        await executor.execute("print('cpu')")
        assert len(harness.batch_calls) == 0
        assert len(harness.serial_calls) == 1
        # A deadline-carrying request: serial (its start-time promise is
        # per-request, not per-batch).
        await executor.execute("print('d')", chip_count=LANE, deadline=60.0)
        assert len(harness.batch_calls) == 0
        assert len(harness.serial_calls) == 2
    finally:
        await executor.close()


async def test_partial_window_still_batches(tmp_path):
    """Two jobs against a max of four: the window expires and they ride one
    under-filled dispatch (occupancy 0.5), not two serial round-trips."""
    executor, harness = make_executor(tmp_path, batch_window_ms=30.0)
    try:
        results = await asyncio.gather(
            executor.execute("print(0)", chip_count=LANE),
            executor.execute("print(1)", chip_count=LANE),
        )
        assert len(harness.batch_calls) == 1
        assert len(harness.batch_calls[0]["jobs"]) == 2
        assert all(r.exit_code == 0 for r in results)
        assert executor.scheduler.batch_occupancies()[LANE] == 0.5
    finally:
        await executor.close()


async def test_single_job_window_takes_serial_path(tmp_path):
    """A lone job whose window expires with no partner: serial semantics,
    exactly as if batching did not exist."""
    executor, harness = make_executor(tmp_path, batch_window_ms=5.0)
    try:
        result = await executor.execute("print('solo')", chip_count=LANE)
        assert len(harness.batch_calls) == 0
        assert len(harness.serial_calls) == 1
        assert result.stdout == "serial ok\n"
    finally:
        await executor.close()


async def test_batch_files_demux_via_hash_negotiation(tmp_path):
    """A batched job's changed files map back to the caller at the paths
    its code wrote (workdir prefix stripped), hash-negotiated against
    storage like any download."""
    executor, harness = make_executor(tmp_path, batch_max_jobs=2)
    async with executor.storage.writer() as writer:
        await writer.write(b"job output bytes")
    sha = writer.hash

    def response(payload):
        body = batch_body(len(payload["jobs"]))
        body["results"][0]["files"] = [{"path": "out/data.bin", "sha256": sha}]
        return body

    harness.batch_response = response
    try:
        results = await asyncio.gather(
            executor.execute("w", chip_count=LANE),
            executor.execute("x", chip_count=LANE),
        )
        assert results[0].files == {"/workspace/out/data.bin": sha}
        assert results[1].files == {}
    finally:
        await executor.close()


async def test_healthz_surfaces_lane_detail_and_batch_occupancy(tmp_path):
    """GET /healthz detail closes the loop on the PR 3 queue-wait EWMA and
    the new batch-occupancy ratio: after a half-filled batched dispatch the
    operator can read, per lane, whether requests queue and whether batches
    run under-filled — without a Prometheus round-trip."""
    pytest.importorskip("aiohttp", reason="optional dependency not installed")
    from aiohttp.test_utils import TestClient, TestServer

    from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )
    from bee_code_interpreter_fs_tpu.services.http_server import create_http_app

    executor, harness = make_executor(tmp_path, batch_max_jobs=4)
    client = TestClient(
        TestServer(create_http_app(executor, CustomToolExecutor(executor), executor.storage))
    )
    await client.start_server()
    try:
        await asyncio.gather(
            *(
                executor.execute(f"print({i})", chip_count=LANE)
                for i in range(2)
            )
        )
        assert len(harness.batch_calls) == 1  # a 2/4 under-filled dispatch
        resp = await client.get("/healthz")
        assert resp.status == 200
        body = await resp.json()
        assert body["status"] == "ok"
        lane = body["lanes"][str(LANE)]
        assert lane["queued"] == 0.0
        assert lane["queue_wait_ewma_s"] >= 0.0
        assert lane["batch_occupancy"] == pytest.approx(0.5)
        assert body["batching"] == {
            "enabled": True,
            "window_ms": 20.0,
            "max_jobs": 4,
        }
    finally:
        await client.close()
        await executor.close()


async def test_different_timeouts_never_share_a_dispatch(tmp_path):
    """The fused run has ONE deadline, so timeout is part of the
    compatibility key: a 5s job must never ride a partner's 300s window
    (found in review — max(timeouts) previously gated the whole batch)."""
    executor, harness = make_executor(tmp_path, batch_window_ms=10.0)
    try:
        results = await asyncio.gather(
            executor.execute("a", chip_count=LANE, timeout=5.0),
            executor.execute("b", chip_count=LANE, timeout=300.0),
            executor.execute("c", chip_count=LANE, timeout=5.0),
            executor.execute("d", chip_count=LANE, timeout=300.0),
        )
        assert len(harness.batch_calls) == 2
        assert sorted(p["timeout"] for p in harness.batch_calls) == [5.0, 300.0]
        for p in harness.batch_calls:
            assert len(p["jobs"]) == 2
        assert all(r.exit_code == 0 for r in results)
    finally:
        await executor.close()


async def test_malformed_batch_entry_is_a_batch_fault_not_one_callers(tmp_path):
    """One corrupt per-job entry reruns EVERYONE serially (with the serial
    path's retries) instead of failing that one caller with a hard infra
    error no serial request would ever see."""
    executor, harness = make_executor(tmp_path, batch_max_jobs=2)

    def response(payload):
        body = batch_body(len(payload["jobs"]))
        body["results"][1] = "not a dict"
        return body

    harness.batch_response = response
    try:
        results = await asyncio.gather(
            executor.execute("a", chip_count=LANE),
            executor.execute("b", chip_count=LANE),
        )
        assert len(harness.serial_calls) == 2
        assert all(r.stdout == "serial ok\n" for r in results)
    finally:
        await executor.close()


async def test_batch_level_stdout_refuses_demux_and_reruns_serially(tmp_path):
    """fd-level stdout (subprocess / C extension) lands batch-level and
    cannot be attributed to a job — the batch reruns serially so no output
    the serial path returns is ever silently dropped."""
    executor, harness = make_executor(tmp_path, batch_max_jobs=2)

    def response(payload):
        body = batch_body(len(payload["jobs"]))
        body["batch_stdout"] = "fd-level write\n"
        return body

    harness.batch_response = response
    try:
        results = await asyncio.gather(
            executor.execute("a", chip_count=LANE),
            executor.execute("b", chip_count=LANE),
        )
        assert len(harness.serial_calls) == 2
        assert all(r.stdout == "serial ok\n" for r in results)
    finally:
        await executor.close()
