"""Auto-install resolution hit rate against a realistic corpus (VERDICT r4
#9): ~130 imports an LLM agent's generated snippets actually use — the
reference sandbox's own stack, the classic divergent import→distribution
names, and namespace packages — resolved by executor/deps.py with the
installed-package check disabled (so the MAPPING is what's measured, not
what this rig happens to have installed).

The bar: the reference ships replit upm's full pypi_map.sqlite
(/root/reference/executor/Dockerfile:122-124); deps.py replaces it with a
stdlib filter + curated TSV + identity fallback. This test pins that the
curated table actually covers agent traffic: hit rate >= 95%, and every
miss is listed so a regression names itself.
"""

import importlib.util
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "executor"))
import deps  # noqa: E402


# (import statement's module, expected pip distribution(s) — a tuple lists
# acceptable alternatives, None means "must not be pip-installed").
CORPUS: list[tuple[str, object]] = [
    # --- the reference sandbox's own stack (SURVEY §2.16) ---------------
    ("numpy", "numpy"),
    ("pandas", "pandas"),
    ("scipy", "scipy"),
    ("matplotlib", "matplotlib"),
    ("mpl_toolkits.mplot3d", "matplotlib"),
    ("sympy", "sympy"),
    ("cv2", ("opencv-python-headless", "opencv-python")),
    ("moviepy", "moviepy"),
    ("PIL", "pillow"),
    ("xarray", "xarray"),
    ("cowsay", "cowsay"),
    ("pydantic", "pydantic"),
    ("fitz", "pymupdf"),
    ("pdf2image", "pdf2image"),
    ("pikepdf", "pikepdf"),
    ("pypandoc", "pypandoc"),
    ("yt_dlp", "yt-dlp"),
    ("weasyprint", "weasyprint"),
    # --- classic divergent import names ---------------------------------
    ("sklearn", "scikit-learn"),
    ("skimage", "scikit-image"),
    ("bs4", "beautifulsoup4"),
    ("yaml", "pyyaml"),
    ("Crypto", "pycryptodome"),
    ("dateutil", "python-dateutil"),
    ("dotenv", "python-dotenv"),
    ("jwt", ("pyjwt", "PyJWT")),
    ("github", ("pygithub", "PyGithub")),
    ("gitlab", "python-gitlab"),
    ("OpenSSL", ("pyopenssl", "pyOpenSSL")),
    ("magic", "python-magic"),
    ("serial", "pyserial"),
    ("usb", "pyusb"),
    ("attr", "attrs"),
    ("telegram", "python-telegram-bot"),
    ("discord", ("discord.py", "discord-py")),
    ("googleapiclient", "google-api-python-client"),
    ("OpenGL", ("pyopengl", "PyOpenGL")),
    ("Bio", "biopython"),
    ("nacl", "pynacl"),
    ("websocket", "websocket-client"),
    ("websockets", "websockets"),
    ("socks", ("pysocks", "PySocks")),
    ("docx", "python-docx"),
    ("pptx", "python-pptx"),
    ("speech_recognition", ("SpeechRecognition", "speechrecognition")),
    ("tabula", "tabula-py"),
    ("slugify", "python-slugify"),
    ("chess", ("chess", "python-chess")),  # renamed upstream; both valid
    ("barcode", "python-barcode"),
    ("memcache", "python-memcached"),
    ("jose", "python-jose"),
    ("ldap", "python-ldap"),
    ("MySQLdb", "mysqlclient"),
    ("mysql", "mysql-connector-python"),
    ("psycopg2", ("psycopg2-binary", "psycopg2")),
    ("zmq", "pyzmq"),
    ("dns", "dnspython"),
    ("whois", "python-whois"),
    ("nmap", "python-nmap"),
    ("grpc", "grpcio"),
    ("kafka", "kafka-python"),
    ("faiss", ("faiss-cpu", "faiss")),
    ("sentence_transformers", "sentence-transformers"),
    ("flask_cors", "flask-cors"),
    ("flask_sqlalchemy", "flask-sqlalchemy"),
    ("pkg_resources", "setuptools"),
    ("gridfs", "pymongo"),
    ("Levenshtein", ("levenshtein", "python-levenshtein", "Levenshtein")),
    ("fuzzywuzzy", "fuzzywuzzy"),
    ("charset_normalizer", "charset-normalizer"),
    ("email_validator", "email-validator"),
    ("unidecode", ("unidecode", "Unidecode")),
    ("xlsxwriter", ("xlsxwriter", "XlsxWriter")),
    ("odf", "odfpy"),
    ("pyzbar", "pyzbar"),
    ("wx", ("wxpython", "wxPython")),
    ("cairo", "pycairo"),
    ("igraph", ("igraph", "python-igraph")),
    # --- namespace packages (per-subpackage distributions) ---------------
    ("google.cloud.storage", "google-cloud-storage"),
    ("google.cloud.bigquery", "google-cloud-bigquery"),
    ("google.protobuf", "protobuf"),
    ("google.generativeai", "google-generativeai"),
    ("azure.storage.blob", "azure-storage-blob"),
    ("azure.identity", "azure-identity"),
    ("ruamel.yaml", "ruamel.yaml"),
    # --- identity names agents commonly pull -----------------------------
    ("requests", "requests"),
    ("httpx", "httpx"),
    ("aiohttp", "aiohttp"),
    ("urllib3", "urllib3"),
    ("flask", "flask"),
    ("django", "django"),
    ("fastapi", "fastapi"),
    ("uvicorn", "uvicorn"),
    ("starlette", "starlette"),
    ("jinja2", "jinja2"),
    ("sqlalchemy", "sqlalchemy"),
    ("redis", "redis"),
    ("pymongo", "pymongo"),
    ("elasticsearch", "elasticsearch"),
    ("boto3", "boto3"),
    ("openai", "openai"),
    ("anthropic", "anthropic"),
    ("tiktoken", "tiktoken"),
    ("transformers", "transformers"),
    ("datasets", "datasets"),
    ("huggingface_hub", "huggingface-hub"),
    ("torch", "torch"),
    ("torchvision", "torchvision"),
    ("tensorflow", "tensorflow"),
    ("keras", "keras"),
    ("jax", "jax"),
    ("einops", "einops"),
    ("seaborn", "seaborn"),
    ("plotly", "plotly"),
    ("bokeh", "bokeh"),
    ("altair", "altair"),
    ("networkx", "networkx"),
    ("statsmodels", "statsmodels"),
    ("geopandas", "geopandas"),
    ("shapely", "shapely"),
    ("folium", "folium"),
    ("geopy", "geopy"),
    ("pytz", "pytz"),
    ("arrow", "arrow"),
    ("pendulum", "pendulum"),
    ("dateparser", "dateparser"),
    ("humanize", "humanize"),
    ("phonenumbers", "phonenumbers"),
    ("pycountry", "pycountry"),
    ("faker", "faker"),
    ("nltk", "nltk"),
    ("spacy", "spacy"),
    ("gensim", "gensim"),
    ("textblob", "textblob"),
    ("wordcloud", "wordcloud"),
    ("emoji", "emoji"),
    ("psutil", "psutil"),
    ("paramiko", "paramiko"),
    ("pexpect", "pexpect"),
    ("py7zr", "py7zr"),
    ("rarfile", "rarfile"),
    ("pydub", "pydub"),
    ("librosa", "librosa"),
    ("soundfile", "soundfile"),
    ("mido", "mido"),
    ("music21", "music21"),
    ("pygame", "pygame"),
    ("qrcode", "qrcode"),
    ("tqdm", "tqdm"),
    ("rich", "rich"),
    ("click", "click"),
    ("typer", "typer"),
    ("fire", "fire"),
    ("colorama", "colorama"),
    ("tabulate", "tabulate"),
    ("openpyxl", "openpyxl"),
    ("xlrd", "xlrd"),
    ("h5py", "h5py"),
    ("pyarrow", "pyarrow"),
    ("numba", "numba"),
    ("regex", "regex"),
    ("ujson", "ujson"),
    ("orjson", "orjson"),
    ("msgpack", "msgpack"),
    ("lxml", "lxml"),
    ("html5lib", "html5lib"),
    ("markdown", "markdown"),
    ("bleach", "bleach"),
    ("pytesseract", "pytesseract"),
    # --- must NEVER pip-install (stdlib / system-only) --------------------
    ("os", None),
    ("json", None),
    ("asyncio", None),
    ("sqlite3", None),
    ("tkinter", None),
    ("gi", None),
]


def _resolve(module: str, monkeypatch) -> str | None:
    """What deps.py would pip-install for `import <module>`, with the
    installed-check neutralized so the mapping itself is measured."""
    monkeypatch.setattr(deps, "_find_spec_safe", lambda name: None)
    out = deps.missing_packages(f"import {module}\n")
    assert len(out) <= 1
    return out[0] if out else None


def test_corpus_hit_rate(monkeypatch):
    monkeypatch.setattr(deps, "_find_spec_safe", lambda name: None)
    misses = []
    for module, expected in CORPUS:
        got = deps.missing_packages(f"import {module}\n")
        got = got[0] if got else None
        ok_values = (
            expected if isinstance(expected, tuple) else (expected,)
        )
        normalized = {
            (v.lower() if isinstance(v, str) else v) for v in ok_values
        }
        got_n = got.lower() if isinstance(got, str) else got
        if got_n not in normalized:
            misses.append((module, got, expected))
    hit_rate = 1 - len(misses) / len(CORPUS)
    assert hit_rate >= 0.95, (
        f"hit rate {hit_rate:.1%} over {len(CORPUS)} imports; "
        f"misses: {misses}"
    )
    # Record the measured rate where the round artifacts can see it.
    print(f"\nAUTO_INSTALL_HIT_RATE={hit_rate:.3f} corpus={len(CORPUS)} "
          f"misses={len(misses)}")
    if misses:
        print(f"missed: {misses}")


def test_stdlib_never_installs(monkeypatch):
    monkeypatch.setattr(deps, "_find_spec_safe", lambda name: None)
    src = "import os, json, re, sys, math, pathlib, subprocess\n"
    assert deps.missing_packages(src) == []


def test_from_import_namespace(monkeypatch):
    """`from google.cloud import bigquery` must resolve the SUBpackage
    distribution, not a bogus top-level 'google'."""
    monkeypatch.setattr(deps, "_find_spec_safe", lambda name: None)
    out = deps.missing_packages("from google.cloud import bigquery\n")
    assert out == ["google-cloud-bigquery"]
