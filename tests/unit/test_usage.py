"""Per-tenant usage metering: ledger semantics, attribution record points
(serial / batched / violations / faults), the phase-histogram allowlist
(the structural fix for the bug class PRs 6-8 each re-fixed once), the
tenant_usage_* metric families, and the kill switch's byte-for-byte
restoration of pre-metering behavior.
"""

import asyncio
import json
import os

import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.code_executor import (
    LATENCY_PHASES,
    CodeExecutor,
    Result,
)
from bee_code_interpreter_fs_tpu.services.errors import (
    ExecutorError,
    LimitExceededError,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage
from bee_code_interpreter_fs_tpu.services.usage import (
    OVERFLOW_TENANT,
    UsageLedger,
)

BATCH_LANE = 4  # multi-chip, single-host (tpu_chips_per_host default 4)


def make_config(tmp_path, **kwargs):
    kwargs.setdefault("file_storage_path", str(tmp_path / "storage"))
    kwargs.setdefault("executor_pod_queue_target_length", 1)
    return Config(**kwargs)


def make_executor(tmp_path, **kwargs):
    config = make_config(tmp_path, **kwargs)
    return CodeExecutor(FakeBackend(), Storage(config.file_storage_path), config)


def serial_body(device_op=0.25, **extra):
    return {
        "stdout": "ok\n",
        "stderr": "",
        "exit_code": 0,
        "files": [],
        "warm": True,
        "duration_s": device_op,
        "device_op_seconds": device_op,
        **extra,
    }


def fake_serial(executor, bodies):
    """Patch the serial wire hop: pops dicts (responses) or raises
    exceptions from `bodies` in order; the last entry repeats."""
    queue = list(bodies)

    async def post(client, base, payload, timeout, sandbox):
        item = queue.pop(0) if len(queue) > 1 else queue[0]
        if isinstance(item, Exception):
            raise item
        return dict(item)

    executor._post_execute = post


def batch_entry(i, device_op=0.1, **extra):
    return {
        "workdir": f".batch-1/job-{i}",
        "stdout": f"job {i}\n",
        "stderr": "",
        "exit_code": 0,
        "files": [],
        "duration_s": device_op,
        "device_op_seconds": device_op,
        "start_offset_s": 0.0,
        **extra,
    }


async def drain(executor):
    for _ in range(200):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


def tenant_row(executor, tenant):
    return executor.usage.snapshot()["tenants"][tenant]


# ---------------------------------------------------------------- ledger unit


def test_ledger_accumulates_and_counts(tmp_path):
    ledger = UsageLedger(make_config(tmp_path))
    ledger.add("a", chip_seconds=1.5, requests=1, outcome="ok")
    ledger.add("a", chip_seconds=0.5, queue_wait_seconds=0.2, requests=1,
               outcome="limit_violation", violation="oom")
    row = ledger.snapshot()["tenants"]["a"]
    assert row["chip_seconds"] == 2.0
    assert row["queue_wait_seconds"] == 0.2
    assert row["requests"] == 2
    assert row["outcomes"] == {"limit_violation": 1.0, "ok": 1.0}
    assert row["violations"] == {"oom": 1.0}


def test_ledger_overflow_tenant_cap(tmp_path):
    ledger = UsageLedger(make_config(tmp_path, usage_max_tenants=2))
    ledger.add("a", requests=1)
    ledger.add("b", requests=1)
    ledger.add("c", chip_seconds=1.0, requests=1)
    ledger.add("d", chip_seconds=2.0, requests=1)
    tenants = ledger.snapshot()["tenants"]
    assert set(tenants) == {"a", "b", OVERFLOW_TENANT}
    # Usage past the cap still accrues — billing never drops consumption.
    assert tenants[OVERFLOW_TENANT]["chip_seconds"] == 3.0
    assert tenants[OVERFLOW_TENANT]["requests"] == 2


def test_ledger_journal_restores_counters(tmp_path):
    config = make_config(tmp_path)
    ledger = UsageLedger(config)
    ledger.add("a", chip_seconds=3.25, upload_bytes=100, requests=1,
               outcome="ok")
    ledger.add("b", chip_seconds=1.0, requests=1, violation="cpu_time",
               outcome="limit_violation")
    assert ledger.flush() == 2
    restored = UsageLedger(config)
    assert restored.snapshot()["tenants"] == ledger.snapshot()["tenants"]


def test_ledger_compaction_snapshot_and_truncate(tmp_path):
    config = make_config(tmp_path, usage_journal_max_bytes=4096)
    ledger = UsageLedger(config)
    # Enough flushes to outgrow the 4 KiB bound (min-clamped) repeatedly.
    for i in range(60):
        ledger.add("tenant-x", chip_seconds=1.0, requests=1, outcome="ok")
        ledger.flush()
    assert ledger.compactions > 0
    assert os.path.getsize(ledger.journal_path) < 4096
    with open(ledger.snapshot_path, encoding="utf-8") as f:
        snap = json.load(f)
    assert snap["tenants"]["tenant-x"]["chip_seconds"] > 0
    restored = UsageLedger(config)
    assert (
        restored.snapshot()["tenants"]["tenant-x"]["chip_seconds"] == 60.0
    )
    assert restored.snapshot()["tenants"]["tenant-x"]["requests"] == 60


def test_ledger_torn_tail_line_skipped(tmp_path):
    config = make_config(tmp_path)
    ledger = UsageLedger(config)
    ledger.add("a", chip_seconds=2.0, requests=1, outcome="ok")
    ledger.flush()
    # A SIGKILL mid-write leaves a torn (non-JSON) tail: replay must keep
    # everything before it and not crash.
    with open(ledger.journal_path, "a", encoding="utf-8") as f:
        f.write('{"tenant": "a", "usage": {"chip_sec')
    restored = UsageLedger(config)
    assert restored.load_errors == 1
    assert restored.snapshot()["tenants"]["a"]["chip_seconds"] == 2.0


def test_ledger_replay_is_idempotent_latest_wins(tmp_path):
    """Cumulative journal lines + max-merge: replaying an OLD line after a
    newer one (crash between snapshot write and journal truncate) can
    never roll counters back."""
    config = make_config(tmp_path)
    ledger = UsageLedger(config)
    ledger.add("a", chip_seconds=1.0, requests=1, outcome="ok")
    ledger.flush()
    ledger.add("a", chip_seconds=1.0, requests=1, outcome="ok")
    ledger.flush()
    with open(ledger.journal_path, encoding="utf-8") as f:
        first_line = f.readline()
    # Re-append the STALE first line after the newer one.
    with open(ledger.journal_path, "a", encoding="utf-8") as f:
        f.write(first_line)
    restored = UsageLedger(config)
    assert restored.snapshot()["tenants"]["a"]["chip_seconds"] == 2.0
    assert restored.snapshot()["tenants"]["a"]["requests"] == 2


def test_disabled_ledger_is_inert(tmp_path):
    config = make_config(tmp_path, usage_metering_enabled=False)
    ledger = UsageLedger(config)
    ledger.add("a", chip_seconds=1.0, requests=1, outcome="ok")
    assert ledger.flush() == 0
    assert ledger.snapshot()["tenants"] == {}
    assert ledger.journal_path is None
    # No .usage dir ever materializes.
    assert not (tmp_path / "storage" / ".usage").exists()


# ----------------------------------------------------- serial attribution


async def test_serial_execute_bills_executor_reported_device_op(tmp_path):
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(executor, [serial_body(device_op=0.25)])
    try:
        result = await executor.execute("print(1)", tenant="acme")
        assert result.phases["device_op_seconds"] == 0.25
        assert result.phases["chip_seconds"] == 0.25  # CPU lane: chips=1
        row = tenant_row(executor, "acme")
        assert row["chip_seconds"] == pytest.approx(0.25)
        assert row["device_op_seconds"] == pytest.approx(0.25)
        assert row["requests"] == 1
        assert row["outcomes"] == {"ok": 1.0}
        # Queue wait attributed by the scheduler at grant time.
        assert row["queue_wait_seconds"] >= 0.0
    finally:
        await executor.close()


async def test_chip_seconds_multiply_by_lane_chip_count(tmp_path):
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(executor, [serial_body(device_op=0.5)])
    try:
        result = await executor.execute(
            "print(1)", chip_count=BATCH_LANE, tenant="acme"
        )
        assert result.phases["chip_seconds"] == pytest.approx(
            0.5 * BATCH_LANE
        )
        assert tenant_row(executor, "acme")["chip_seconds"] == pytest.approx(
            0.5 * BATCH_LANE
        )
    finally:
        await executor.close()


async def test_violating_request_billed_and_counted(tmp_path):
    """The acceptance criterion's violation clause: a request killed for a
    typed limit breach still bills the device time it consumed AND counts
    under its violation kind."""
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(
        executor,
        [serial_body(device_op=0.4, violation="cpu_time", exit_code=-1)],
    )
    try:
        with pytest.raises(LimitExceededError):
            await executor.execute("while True: pass", tenant="acme")
        row = tenant_row(executor, "acme")
        assert row["chip_seconds"] == pytest.approx(0.4)
        assert row["violations"] == {"cpu_time": 1.0}
        assert row["outcomes"] == {"limit_violation": 1.0}
        assert row["requests"] == 1
    finally:
        await executor.close()


async def test_faulted_request_still_billed(tmp_path):
    """A wire fault mid-exec consumed real device time: each retry
    attempt bills its measured exec wall; the logical request counts once
    as infra_error."""
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(executor, [ExecutorError("connection dropped")])
    try:
        with pytest.raises(ExecutorError):
            await executor.execute("print(1)", tenant="acme")
        row = tenant_row(executor, "acme")
        assert row["chip_seconds"] > 0.0  # billed despite the fault
        assert row["requests"] == 1  # counted once despite 3 attempts
        assert row["outcomes"] == {"infra_error": 1.0}
    finally:
        await executor.close()


async def test_session_requests_attributed(tmp_path):
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(executor, [serial_body(device_op=0.2)])
    try:
        for _ in range(2):
            result = await executor.execute(
                "print(1)", executor_id="sess-1", tenant="acme"
            )
            assert result.phases["chip_seconds"] == pytest.approx(0.2)
        row = tenant_row(executor, "acme")
        assert row["chip_seconds"] == pytest.approx(0.4)
        assert row["requests"] == 2
        await executor.close_session("sess-1")
    finally:
        await executor.close()


async def test_transfer_bytes_billed_moved_not_skipped(tmp_path):
    executor = make_executor(tmp_path, batching_enabled=False)
    storage = executor.storage
    async with storage.writer() as writer:
        await writer.write(b"x" * 1000)
    object_id = writer.hash

    async def post(client, base, payload, timeout, sandbox):
        return serial_body(device_op=0.1)

    uploaded = []

    async def fake_upload(client, base, rel, object_id, manifest):
        uploaded.append(rel)
        manifest.record_upload(rel, object_id)

    executor._post_execute = post
    executor._upload_file = fake_upload
    try:
        # First run moves the bytes; the manifest-skipped rerun must not
        # re-bill them (moved, not skipped — the PR 3 distinction).
        await executor.execute(
            "print(1)",
            files={"/workspace/in.bin": object_id},
            executor_id="sess-t",
            tenant="acme",
        )
        await executor.execute(
            "print(1)",
            files={"/workspace/in.bin": object_id},
            executor_id="sess-t",
            tenant="acme",
        )
        row = tenant_row(executor, "acme")
        assert uploaded == ["in.bin"]  # second turn was manifest-skipped
        assert row["upload_bytes"] == 1000  # billed exactly once
        await executor.close_session("sess-t")
    finally:
        await executor.close()


# ------------------------------------------------------ batched attribution


def fake_batch(executor, response):
    calls = []

    async def post(client, base, payload, timeout, sandbox):
        calls.append(payload)
        item = response(payload) if callable(response) else response
        if isinstance(item, Exception):
            raise item
        return item

    executor._post_execute_batch = post
    return calls


async def test_batch_apportions_fused_chip_seconds_exactly(tmp_path):
    """The no-double-billing/no-loss invariant: per-job shares (weighted
    by per-job exec spans) sum EXACTLY to the fused dispatch's
    chip-seconds, and the ledger bills the total once."""
    executor = make_executor(
        tmp_path, batch_window_ms=20.0, batch_max_jobs=4
    )
    fused_device_op = 0.5
    fake_batch(
        executor,
        lambda payload: {
            "results": [
                batch_entry(i, device_op=0.1 * (i + 1))
                for i in range(len(payload["jobs"]))
            ],
            "warm": True,
            "runner_restarted": False,
            "device_op_seconds": fused_device_op,
        },
    )
    try:
        results = await asyncio.gather(
            *(
                executor.execute(
                    f"print({i})", chip_count=BATCH_LANE, tenant="acme"
                )
                for i in range(4)
            )
        )
        assert all(r.phases["batch_jobs"] == 4.0 for r in results)
        total = fused_device_op * BATCH_LANE
        shares = [r.phases["chip_seconds"] for r in results]
        assert sum(shares) == pytest.approx(total)
        # Weighted by the per-job spans: 0.1/0.2/0.3/0.4 of the total.
        assert sorted(shares) == pytest.approx(
            [total * w / 1.0 for w in (0.1, 0.2, 0.3, 0.4)]
        )
        row = tenant_row(executor, "acme")
        assert row["chip_seconds"] == pytest.approx(total)  # billed ONCE
        assert row["batch_jobs"] == 4
        assert row["requests"] == 4
        # The fused path reports each job's real pre-exec wait.
        assert all("queue_wait" in r.phases for r in results)
    finally:
        await executor.close()


async def test_batch_equal_split_when_spans_absent(tmp_path):
    executor = make_executor(
        tmp_path, batch_window_ms=20.0, batch_max_jobs=4
    )
    fake_batch(
        executor,
        lambda payload: {
            "results": [
                {
                    k: v
                    for k, v in batch_entry(i).items()
                    if k not in ("duration_s", "device_op_seconds")
                }
                for i in range(len(payload["jobs"]))
            ],
            "warm": True,
            "runner_restarted": False,
            "device_op_seconds": 0.8,
        },
    )
    try:
        results = await asyncio.gather(
            *(
                executor.execute(
                    f"print({i})", chip_count=BATCH_LANE, tenant="acme"
                )
                for i in range(4)
            )
        )
        total = 0.8 * BATCH_LANE
        shares = [r.phases["chip_seconds"] for r in results]
        assert shares == pytest.approx([total / 4] * 4)
        assert sum(shares) == pytest.approx(total)
    finally:
        await executor.close()


async def test_bill_identical_fused_vs_serial_path(tmp_path):
    """The tentpole's equality clause: with identical executor-reported
    device-op times, a tenant's chip-second bill is the same whether its
    jobs rode the fused dispatch or the serial path."""

    async def run(batching: bool) -> float:
        executor = make_executor(
            tmp_path / ("batched" if batching else "serial"),
            batching_enabled=batching,
            batch_window_ms=20.0,
            batch_max_jobs=4,
        )
        # Fused: 4 jobs x 0.1s spans inside one 0.4s dispatch. Serial:
        # each job is its own 0.1s op. Same device seconds either way.
        fake_batch(
            executor,
            lambda payload: {
                "results": [
                    batch_entry(i, device_op=0.1)
                    for i in range(len(payload["jobs"]))
                ],
                "warm": True,
                "runner_restarted": False,
                "device_op_seconds": 0.4,
            },
        )
        fake_serial(executor, [serial_body(device_op=0.1)])
        try:
            await asyncio.gather(
                *(
                    executor.execute(
                        f"print({i})", chip_count=BATCH_LANE, tenant="acme"
                    )
                    for i in range(4)
                )
            )
            return tenant_row(executor, "acme")["chip_seconds"]
        finally:
            await executor.close()

    assert await run(True) == pytest.approx(await run(False))


async def test_batch_wire_fault_bills_then_serial_rerun_bills_its_own(
    tmp_path,
):
    executor = make_executor(
        tmp_path, batch_window_ms=20.0, batch_max_jobs=2
    )
    fake_batch(executor, ExecutorError("batch wire dropped"))
    fake_serial(executor, [serial_body(device_op=0.1)])
    try:
        results = await asyncio.gather(
            *(
                executor.execute(
                    f"print({i})", chip_count=BATCH_LANE, tenant="acme"
                )
                for i in range(2)
            )
        )
        assert all(r.exit_code == 0 for r in results)
        row = tenant_row(executor, "acme")
        # The failed fused attempt billed its (tiny, wall-measured)
        # consumption AND the serial reruns billed theirs: >= the serial
        # total alone, requests still counted once each.
        assert row["chip_seconds"] >= 0.1 * BATCH_LANE * 2
        assert row["requests"] == 2
        assert row["outcomes"] == {"ok": 2.0}
    finally:
        await executor.close()


async def test_batch_job_violation_billed_and_counted(tmp_path):
    executor = make_executor(
        tmp_path, batch_window_ms=20.0, batch_max_jobs=2
    )
    fake_batch(
        executor,
        lambda payload: {
            "results": [
                batch_entry(0, device_op=0.1),
                batch_entry(
                    1, device_op=0.1, violation="oom", exit_code=-1
                ),
            ],
            "warm": True,
            "runner_restarted": False,
            "device_op_seconds": 0.2,
        },
    )
    try:
        outcomes = await asyncio.gather(
            *(
                executor.execute(
                    f"print({i})", chip_count=BATCH_LANE, tenant="acme"
                )
                for i in range(2)
            ),
            return_exceptions=True,
        )
        violations = [
            o for o in outcomes if isinstance(o, LimitExceededError)
        ]
        assert len(violations) == 1 and violations[0].kind == "oom"
        row = tenant_row(executor, "acme")
        assert row["chip_seconds"] == pytest.approx(0.2 * BATCH_LANE)
        assert row["violations"] == {"oom": 1.0}
        assert row["outcomes"] == {"limit_violation": 1.0, "ok": 1.0}
    finally:
        await executor.close()


# -------------------------------------------------- kill switch + histogram


async def test_kill_switch_restores_pre_metering_behavior(tmp_path):
    executor = make_executor(
        tmp_path, batching_enabled=False, usage_metering_enabled=False
    )
    fake_serial(executor, [serial_body(device_op=0.25)])
    try:
        result = await executor.execute("print(1)", tenant="acme")
        # No attribution fields in phases — the response is byte-for-byte
        # what a pre-metering control plane produced.
        assert "chip_seconds" not in result.phases
        assert "device_op_seconds" not in result.phases
        assert executor.usage.snapshot()["tenants"] == {}
        assert executor.scheduler.usage is None
        # No tenant_usage_* samples on the metrics surface.
        render = executor.metrics.registry.render()
        assert 'tenant_usage_seconds_total{' not in render
        assert not (tmp_path / "storage" / ".usage").exists()
    finally:
        await executor.close()


def test_phase_histogram_allowlist_blocks_non_latency_keys(tmp_path):
    """THE regression test the satellite asks for: a NEW non-latency
    phases key must never reach the latency histogram — the bug class
    PRs 6, 7, and 8 each re-fixed one key at a time (compile_cache_*,
    batch_jobs, batch_index). The usage attribution fields must pass on
    day one."""
    executor = make_executor(tmp_path)
    result = Result(
        stdout="",
        stderr="",
        exit_code=0,
        files={},
        phases={
            # The real latency phases...
            "queue_wait": 0.1,
            "upload": 0.01,
            "exec": 1.0,
            "download": 0.02,
            "restore": 0.03,
            # ...the new usage attribution fields (day-one requirement)...
            "chip_seconds": 8.0,
            "device_op_seconds": 2.0,
            # ...every historical offender class...
            "compile_cache_hits": 3.0,
            "compile_cache_new_bytes": 4096.0,
            "batch_jobs": 8.0,
            "batch_index": 7.0,
            "upload_bytes": 123.0,
            "trace_id": "a" * 32,
            # ...and a key invented AFTER this test was written: the
            # allowlist must exclude it BY DEFAULT.
            "frobnicate_total": 42.0,
        },
    )
    executor._count_execution(result, session=False)
    observed = {
        labels["phase"]
        for labels, _counts, _sum, _total in executor.metrics.phase_seconds.samples()
    }
    assert observed == set(LATENCY_PHASES)
    # And the histogram's sum is sane: had frobnicate_total/chip_seconds
    # leaked in, the sum would jump by tens of fake "seconds".
    total_sum = sum(
        s for _labels, _counts, s, _total in executor.metrics.phase_seconds.samples()
    )
    assert total_sum == pytest.approx(0.1 + 0.01 + 1.0 + 0.02 + 0.03)


# ----------------------------------------------------------- metric families


async def test_tenant_usage_metric_families_move(tmp_path):
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(
        executor,
        [
            serial_body(
                device_op=0.5,
                compile_cache={"hits": 1, "misses": 2, "new_entries": 2,
                               "new_bytes": 4096},
            )
        ],
    )
    try:
        await executor.execute("print(1)", tenant="acme")
        render = executor.metrics.registry.render()
        assert (
            'code_interpreter_tenant_usage_seconds_total{resource="chip",tenant="acme"}'
            in render
        )
        assert (
            'code_interpreter_tenant_usage_requests_total{outcome="ok",tenant="acme"}'
            in render
        )
        assert (
            'code_interpreter_tenant_usage_compile_recompiles_total{tenant="acme"} 2'
            in render
        )
        assert (
            'code_interpreter_tenant_usage_bytes_total{kind="compile_cache_new",tenant="acme"} 4096'
            in render
        )
        row = tenant_row(executor, "acme")
        assert row["compile_cache_recompiles"] == 2
        assert row["compile_cache_new_bytes"] == 4096
    finally:
        await executor.close()


async def test_statusz_carries_usage_section(tmp_path):
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(executor, [serial_body()])
    try:
        await executor.execute("print(1)", tenant="acme")
        body = executor.statusz()
        assert body["usage"]["enabled"] is True
        assert "acme" in body["usage"]["tenants"]
    finally:
        await executor.close()


# ------------------------------------------------------ queue-wait attribution


async def test_queue_wait_attributed_per_request_for_batch_tickets(tmp_path):
    """A multi-job batch ticket's wait bills once per request it served
    (mirroring how grants count requests, not tickets)."""
    executor = make_executor(tmp_path, batch_window_ms=20.0, batch_max_jobs=4)
    fake_batch(
        executor,
        lambda payload: {
            "results": [
                batch_entry(i) for i in range(len(payload["jobs"]))
            ],
            "warm": True,
            "runner_restarted": False,
            "device_op_seconds": 0.1,
        },
    )
    recorded = []
    real_add = executor.usage.add

    def spy_add(tenant, **kwargs):
        if kwargs.get("queue_wait_seconds"):
            recorded.append(kwargs["queue_wait_seconds"])
        return real_add(tenant, **kwargs)

    executor.usage.add = spy_add
    try:
        await asyncio.gather(
            *(
                executor.execute(
                    f"print({i})", chip_count=BATCH_LANE, tenant="acme"
                )
                for i in range(4)
            )
        )
        # One multi-job grant -> ONE queue-wait record covering 4 requests
        # (wait x jobs); its value is 4x the ticket's wait by construction.
        assert len(recorded) == 1
    finally:
        await executor.close()


# ------------------------------------------------- review-hardening fixes


async def test_session_retry_iteration_still_bills(tmp_path):
    """The closed-while-waiting `continue` must not spend the draft: when
    the first session fetch yields a just-closed session, the retry
    iteration's real consumption still reaches the ledger (the commit
    lives at request exit, not per loop iteration)."""
    from bee_code_interpreter_fs_tpu.services.code_executor import _Session

    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(executor, [serial_body(device_op=0.3)])
    real_get_session = executor._get_session
    handed_closed = False

    async def get_session_with_stale_first(executor_id, lane, **kwargs):
        nonlocal handed_closed
        if not handed_closed:
            handed_closed = True
            stale = _Session(lane=lane)
            stale.closed = True  # forces the loop's `continue` path
            return stale
        return await real_get_session(executor_id, lane, **kwargs)

    executor._get_session = get_session_with_stale_first
    try:
        result = await executor.execute(
            "print(1)", executor_id="sess-r", tenant="acme"
        )
        assert result.exit_code == 0
        assert handed_closed  # the stale iteration really happened
        row = tenant_row(executor, "acme")
        assert row["chip_seconds"] == pytest.approx(0.3)
        await executor.close_session("sess-r")
    finally:
        await executor.close()


def test_restart_restores_full_table_past_the_cap(tmp_path):
    """Persisted rows restore VERBATIM: the live table legitimately holds
    max_tenants real rows plus `_overflow`; replaying it through the cap
    would max-merge the last real tenant into `_overflow` and destroy its
    bill on every restart."""
    config = make_config(tmp_path, usage_max_tenants=2)
    ledger = UsageLedger(config)
    ledger.add("a", chip_seconds=1.0, requests=1)
    ledger.add("b", chip_seconds=2.0, requests=1)
    ledger.add("c", chip_seconds=4.0, requests=1)  # -> _overflow
    ledger.flush()
    restored = UsageLedger(config)
    assert restored.snapshot()["tenants"] == ledger.snapshot()["tenants"]
    # Specifically: "b" (the cap-th row) kept its own bill, and the
    # overflow row holds exactly the overflowed usage.
    tenants = restored.snapshot()["tenants"]
    assert tenants["b"]["chip_seconds"] == 2.0
    assert tenants[OVERFLOW_TENANT]["chip_seconds"] == 4.0


async def test_trusted_prewarm_runs_bill_nobody(tmp_path):
    """Control-plane-authored runs (the compile-cache pre-warm) are
    internal warmup work: no draft, no request count, no queue-wait
    attribution — the default tenant's row must reflect only genuine
    client requests."""
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(executor, [serial_body(device_op=0.5)])
    try:
        result = await executor._execute_trusted("print('prewarm')")
        assert result.exit_code == 0
        assert executor.usage.snapshot()["tenants"] == {}
        assert "chip_seconds" not in result.phases
        # A genuine shared-tenant request afterwards bills normally.
        await executor.execute("print(1)")
        tenants = executor.usage.snapshot()["tenants"]
        assert set(tenants) == {executor.scheduler.default_tenant}
        assert tenants[executor.scheduler.default_tenant][
            "chip_seconds"
        ] == pytest.approx(0.5)
    finally:
        await executor.close()


async def test_batch_refusal_bills_no_phantom_chip_seconds(tmp_path):
    """A clean refusal (404 old binary / 409 no warm runner) answered
    WITHOUT running anything: the tenant must be billed only for the
    serial reruns' real consumption — wall x chips for the refused hop
    would systematically overbill every batch during a rolling upgrade."""
    executor = make_executor(
        tmp_path, batch_window_ms=20.0, batch_max_jobs=2
    )

    async def refusing_batch(client, base, payload, timeout, sandbox):
        error = ExecutorError(
            f"sandbox {sandbox.id} /execute-batch -> 404: no route"
        )
        error.device_may_have_run = False  # as _post_execute_batch tags it
        raise error

    executor._post_execute_batch = refusing_batch
    fake_serial(executor, [serial_body(device_op=0.1)])
    try:
        results = await asyncio.gather(
            *(
                executor.execute(
                    f"print({i})", chip_count=BATCH_LANE, tenant="acme"
                )
                for i in range(2)
            )
        )
        assert all(r.exit_code == 0 for r in results)
        row = tenant_row(executor, "acme")
        # EXACTLY the serial reruns' reported ops — no refusal surcharge.
        assert row["chip_seconds"] == pytest.approx(0.1 * BATCH_LANE * 2)
        assert row["device_op_seconds"] == pytest.approx(0.1 * 2)
    finally:
        await executor.close()


async def test_serial_refusal_not_billed_as_device_time(tmp_path):
    """Same rule on the serial path: a non-200 /execute refusal never ran
    user code — retries then a real run bill only the real run."""
    refusal = ExecutorError("sandbox x /execute -> 409: busy")
    refusal.device_may_have_run = False
    executor = make_executor(tmp_path, batching_enabled=False)
    fake_serial(executor, [refusal, serial_body(device_op=0.2)])
    try:
        result = await executor.execute("print(1)", tenant="acme")
        assert result.exit_code == 0
        row = tenant_row(executor, "acme")
        assert row["chip_seconds"] == pytest.approx(0.2)  # real run only
    finally:
        await executor.close()


async def test_stop_waits_out_inflight_thread_flush(tmp_path):
    """stop() must await an in-flight worker-thread write before the
    final synchronous flush: a late thread compaction would otherwise
    truncate the journal with a pre-final-flush snapshot, erasing the
    drain window's attribution from disk."""
    import time as _time

    ledger = UsageLedger(make_config(tmp_path, usage_flush_interval=0.2))
    real_write = ledger._write_flush
    in_write = asyncio.Event()
    release = False

    def slow_write(payload):
        asyncio.get_event_loop_policy()  # no-op; runs in the worker thread
        in_write.set()
        while not release:
            _time.sleep(0.01)
        return real_write(payload)

    ledger._write_flush = slow_write
    ledger.add("a", chip_seconds=1.0, requests=1, outcome="ok")
    ledger.start()
    await asyncio.wait_for(in_write.wait(), timeout=5.0)
    # The daemon's write is parked in the worker thread; the drain
    # window's last attribution lands now.
    ledger.add("a", chip_seconds=1.0, requests=1, outcome="ok")
    stop_task = asyncio.create_task(ledger.stop())
    await asyncio.sleep(0.1)
    assert not stop_task.done()  # stop is WAITING on the thread
    release = True
    await asyncio.wait_for(stop_task, timeout=5.0)
    # Both attributions are durable: the thread's line AND the final
    # flush's line made it, in order.
    restored = UsageLedger(ledger.config)
    assert restored.snapshot()["tenants"]["a"]["chip_seconds"] == 2.0
    assert restored.snapshot()["tenants"]["a"]["requests"] == 2
