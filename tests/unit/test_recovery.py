"""Wedge-recovery actuation unit tests: probe-driven fence_host (lease
revocation, lane drain, dispose-and-replace), the actuation budget and
breaker integration, the recovering-scope quarantine and gated
re-admission, stale-lease refusals on the dispatch paths, session fencing,
and the /healthz / /statusz surfaces.

Stack: CodeExecutor over FakeBackend with a controllable /device-stats
wire (the test_device_health pattern) and the fencing actuation ON — the
posture the detection-only suites deliberately switch off.
"""

import asyncio
import tempfile

import httpx
import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CodeExecutor,
    StaleLeaseError,
)
from bee_code_interpreter_fs_tpu.services.device_health import (
    DRAINING,
    HEALTHY,
    RECOVERING,
    WEDGED,
    DeviceHealthProbe,
)
from bee_code_interpreter_fs_tpu.services.leases import Lease
from bee_code_interpreter_fs_tpu.services.storage import Storage


def _stats(**overrides) -> dict:
    base = {
        "status": "ok",
        "warm": True,
        "warm_state": "ready",
        "backend": "cpu",
        "device_kind": "cpu",
        "device_count": 1,
        "attach_pending_s": 0.0,
        "attach_seconds": 1.5,
        "op_in_flight": False,
        "op_age_s": 0.0,
        "op_timeout_s": 0.0,
        "last_device_op_age_s": 3.0,
        "runner_heartbeat_age_s": 0.5,
        "runner_alive": True,
        "rss_bytes": 1 << 20,
        "runner_rss_bytes": 2 << 20,
    }
    base.update(overrides)
    return base


WEDGE_STATS = dict(
    warm_state="pending", attach_pending_s=100.0, runner_alive=False
)


class _Stack:
    """Executor + probe with the fencing actuation ON and a controllable
    /device-stats wire: `self.responses[url]` is a stats dict (default
    healthy)."""

    def __init__(self, **config_overrides):
        self.tmp = tempfile.mkdtemp(prefix="recovery-test-")
        defaults = dict(
            file_storage_path=self.tmp,
            executor_pod_queue_target_length=1,
            compile_cache_enabled=False,
            device_probe_interval=10.0,
            device_probe_timeout=1.0,
            device_probe_attach_budget=10.0,
            device_probe_op_grace=5.0,
            device_probe_wedge_after=10.0,
            device_probe_readmit_streak=2,
        )
        defaults.update(config_overrides)
        self.config = Config(**defaults)
        self.backend = FakeBackend(distinct_urls=True)
        self.executor = CodeExecutor(
            self.backend, Storage(self.tmp), self.config
        )
        self.responses: dict[str, object] = {}

        def handler(request: httpx.Request) -> httpx.Response:
            key = f"http://{request.url.host}"
            if request.url.path == "/lease":
                return httpx.Response(200, json={"ok": True})
            value = self.responses.get(key)
            if isinstance(value, dict):
                return httpx.Response(200, json=value)
            return httpx.Response(200, json=_stats())

        self._client = httpx.AsyncClient(
            transport=httpx.MockTransport(handler)
        )
        self.executor._http_client = lambda: self._client
        self.probe = DeviceHealthProbe(self.executor)
        self.executor.device_health = self.probe

        async def post(client, base, payload, timeout, sandbox):
            return {
                "stdout": "ok\n",
                "stderr": "",
                "exit_code": 0,
                "files": [],
                "warm": True,
                "duration_s": 0.01,
            }

        self.executor._post_execute = post

    async def spawn_pooled(self, lane: int = 0):
        """A properly leased sandbox parked in the lane's pool."""
        sandbox = await self.executor._spawn_with_retry(lane)
        self.executor._pool(lane).append(sandbox)
        return sandbox

    async def settle(self):
        for _ in range(50):
            pending = list(self.executor._dispose_tasks) + list(
                self.executor._fill_tasks
            )
            if not pending:
                return
            await asyncio.gather(*pending, return_exceptions=True)

    def fences(self) -> dict:
        return {
            (labels["lane"], labels["outcome"]): value
            for labels, value in self.executor.metrics.device_fences.samples()
        }

    async def close(self):
        await self._client.aclose()
        await self.executor.close()


@pytest.fixture
async def stack():
    s = _Stack()
    yield s
    await s.close()


# ------------------------------------------------------------------ fencing


async def test_spawn_mints_monotonic_lease(stack):
    a = await stack.executor._spawn_with_retry(0)
    b = await stack.executor._spawn_with_retry(0)
    la, lb = a.meta["lease"], b.meta["lease"]
    assert isinstance(la, Lease) and isinstance(lb, Lease)
    assert la.scope == lb.scope == "lane-0"
    assert lb.generation == la.generation + 1


async def test_fence_host_drains_disposes_replaces(stack):
    sandbox = await stack.spawn_pooled(0)
    lease = sandbox.meta["lease"]
    deletes = stack.backend.deletes
    outcome = await stack.executor.fence_host(sandbox.id, reason="wedged")
    assert outcome == "fenced"
    # Lease revoked, scope recovering, host drained from the pool and
    # disposed.
    assert lease.revoked
    assert stack.executor.leases.recovering("lane-0")
    assert sandbox not in stack.executor._pool(0)
    assert stack.backend.deletes == deletes + 1
    assert stack.executor.live_sandbox(sandbox.id) is None
    assert stack.fences()[("0", "fenced")] == 1
    # The refill replaced it; the replacement holds a NEWER generation and
    # starts quarantined (recovering) until the clean-probe streak.
    await stack.settle()
    pool = stack.executor._pool(0)
    assert len(pool) == 1
    replacement = pool[0]
    assert replacement.meta["lease"].generation > lease.generation
    assert replacement.meta["device_health"] == "recovering"
    # Quarantined supply: standby, not servable.
    assert stack.executor._pool_supply(0) == 0
    assert stack.executor._pool_standby(0) == 1
    # Re-fencing the disposed host is a no-op.
    assert await stack.executor.fence_host(sandbox.id) == "gone"


async def test_fence_budget_caps_actuations(stack):
    stack.executor.config.device_fence_max_per_window = 1
    a = await stack.spawn_pooled(0)
    b = await stack.spawn_pooled(0)
    assert await stack.executor.fence_host(a.id) == "fenced"
    assert await stack.executor.fence_host(b.id) == "budget_exhausted"
    # The deferred host is untouched: still live, lease intact.
    assert stack.executor.live_sandbox(b.id) is not None
    assert not b.meta["lease"].revoked
    assert stack.fences()[("0", "budget_exhausted")] == 1


async def test_fence_skipped_while_breaker_open(stack):
    sandbox = await stack.spawn_pooled(0)
    stack.executor.breakers.lane(0).trip("test")
    assert await stack.executor.fence_host(sandbox.id) == "breaker_open"
    assert stack.executor.live_sandbox(sandbox.id) is not None
    assert stack.fences()[("0", "breaker_open")] == 1


async def test_fence_kill_switch_restores_detection_only(stack):
    stack.executor.config.device_fence_enabled = False
    sandbox = await stack.spawn_pooled(0)
    assert await stack.executor.fence_host(sandbox.id) == "disabled"
    stack.executor.on_host_wedged(sandbox.id)
    await stack.settle()
    assert stack.executor.live_sandbox(sandbox.id) is not None
    assert not stack.executor.leases.recovering("lane-0")


async def test_probe_wedge_verdict_triggers_fence(stack):
    sandbox = await stack.spawn_pooled(0)
    stack.responses[sandbox.url] = _stats(**WEDGE_STATS)
    states = await stack.probe.probe_once()
    assert states[sandbox.url] == WEDGED
    await stack.settle()
    assert stack.executor.live_sandbox(sandbox.id) is None
    assert stack.fences()[("0", "fenced")] == 1
    # The wedged host left the table on the next cycle (disposed) and the
    # replacement shows up recovering.
    states = await stack.probe.probe_once()
    assert sandbox.url not in states
    assert RECOVERING in states.values()


async def test_draining_overlay_until_disposed(stack):
    """A fenced-but-not-yet-pruned host reads DRAINING, not whatever its
    stats would classify."""
    sandbox = await stack.spawn_pooled(0)
    sandbox.meta["lease_fenced"] = True
    states = await stack.probe.probe_once()
    assert states[sandbox.url] == DRAINING


# ------------------------------------------------------------- re-admission


async def test_recovering_scope_readmits_after_streak(stack):
    sandbox = await stack.spawn_pooled(0)
    await stack.executor.fence_host(sandbox.id)
    await stack.settle()
    replacement = stack.executor._pool(0)[0]
    # Cycle 1: clean, still recovering (streak 1/2).
    states = await stack.probe.probe_once()
    assert states[replacement.url] == RECOVERING
    assert stack.executor._pool_supply(0) == 0
    # Cycle 2: the streak completes — re-admitted, serving supply again.
    states = await stack.probe.probe_once()
    assert states[replacement.url] == HEALTHY
    assert replacement.meta["device_health"] == "healthy"
    assert stack.executor._pool_supply(0) == 1
    assert not stack.executor.leases.recovering("lane-0")
    readmits = {
        labels["lane"]: value
        for labels, value in stack.executor.metrics.host_readmitted.samples()
    }
    assert readmits["0"] == 1


async def test_suspect_relapse_resets_the_streak(stack):
    sandbox = await stack.spawn_pooled(0)
    await stack.executor.fence_host(sandbox.id)
    await stack.settle()
    replacement = stack.executor._pool(0)[0]
    await stack.probe.probe_once()  # clean: streak 1/2
    # Relapse: the replacement goes suspect mid-streak. The streak resets
    # AND the quarantine holds — the host keeps reading RECOVERING (a raw
    # suspect would count as servable supply and be poppable, the escape
    # the gate exists to prevent).
    stack.responses[replacement.url] = _stats(
        warm_state="pending", attach_pending_s=15.0
    )
    states = await stack.probe.probe_once()
    assert states[replacement.url] == RECOVERING
    assert replacement.meta["device_health"] == "recovering"
    assert stack.executor._pool_supply(0) == 0
    assert stack.executor._pop_pool_sandbox(stack.executor._pool(0)) is None
    assert stack.executor.leases.recovery_progress("lane-0") == (0, 2)
    # Two consecutive clean cycles are needed all over again.
    stack.responses[replacement.url] = _stats()
    await stack.probe.probe_once()
    assert stack.executor.leases.recovering("lane-0")
    await stack.probe.probe_once()
    assert not stack.executor.leases.recovering("lane-0")


async def test_pop_pool_never_hands_out_recovering_hosts(stack):
    sandbox = await stack.spawn_pooled(0)
    sandbox.meta["device_health"] = "recovering"
    pool = stack.executor._pool(0)
    assert stack.executor._pop_pool_sandbox(pool) is None
    assert len(pool) == 1  # still parked
    # A healthy host beside it is popped, quarantined one stays.
    healthy = await stack.spawn_pooled(0)
    popped = stack.executor._pop_pool_sandbox(pool)
    assert popped is healthy
    assert pool[0] is sandbox


# ------------------------------------------------------------- stale leases


async def test_check_lease_refuses_revoked(stack):
    sandbox = await stack.executor._spawn_with_retry(0)
    stack.executor.leases.fence(sandbox.meta["lease"])
    with pytest.raises(StaleLeaseError):
        stack.executor._check_lease(sandbox)


async def test_execute_retries_off_a_fenced_host(stack):
    """A pooled sandbox whose lease was revoked (fence raced the pop): the
    dispatch refuses cleanly, the host is disposed, and the retry ladder
    lands the request on a FRESH sandbox — never the fenced one."""
    sandbox = await stack.spawn_pooled(0)
    sandbox.meta["lease"].revoked = True
    deletes = stack.backend.deletes
    result = await stack.executor.execute("print('ok')")
    assert result.exit_code == 0
    await stack.settle()
    assert stack.backend.deletes >= deletes + 1
    assert stack.executor.live_sandbox(sandbox.id) is None


async def test_stale_lease_409_parsing(stack):
    sandbox = await stack.executor._spawn_with_retry(0)
    typed = httpx.Response(
        409, json={"error": "stale_lease", "held": "lane-0:2",
                   "offered": "lane-0:1"}
    )
    with pytest.raises(StaleLeaseError):
        stack.executor._raise_if_stale_lease(typed, sandbox)
    # A 409 that is NOT the typed refusal (e.g. /reset's "runner not
    # warm", /execute-batch's "no warm runner") passes through.
    stack.executor._raise_if_stale_lease(
        httpx.Response(409, json={"ok": False, "reason": "runner not warm"}),
        sandbox,
    )
    stack.executor._raise_if_stale_lease(
        httpx.Response(200, json={}), sandbox
    )


async def test_wire_headers_carry_lease_token(stack):
    sandbox = await stack.executor._spawn_with_retry(0)
    headers = stack.executor._wire_headers(sandbox)
    assert headers["x-lease-token"] == sandbox.meta["lease"].wire_token


# ----------------------------------------------------------------- sessions


async def test_fence_closes_parked_session(stack):
    result = await stack.executor.execute("print(1)", executor_id="sess-1")
    assert result.session_seq == 1
    session = stack.executor._sessions["sess-1"]
    sandbox = session.sandbox
    await stack.executor.fence_host(sandbox.id, reason="wedged")
    await stack.settle()
    # The session died AT FENCE TIME — not at idle expiry, not at the
    # client's timeout.
    assert session.closed
    assert "sess-1" not in stack.executor._sessions
    # The client's reconnect lands on a fresh, healthy host; seq == 1
    # reports the state loss.
    result = await stack.executor.execute("print(2)", executor_id="sess-1")
    assert result.session_seq == 1
    assert stack.executor._sessions["sess-1"].sandbox is not sandbox


# ----------------------------------------------------------------- surfaces


async def test_lane_supply_carries_census_and_quarantine_counts(stack):
    # Mid-drain (fenced, dispose not yet landed): the lane row shows it.
    draining = await stack.spawn_pooled(0)
    draining.meta["lease_fenced"] = True
    await stack.probe.probe_once()
    rows = stack.executor.lane_supply()
    assert rows["0"]["draining"] >= 1
    assert rows["0"]["device_health"].get("draining", 0) >= 1
    # Full cycle: wedge -> fence -> replacement in recovering quarantine.
    stack.executor._pool(0).remove(draining)
    await stack.executor._dispose(draining)
    sandbox = await stack.spawn_pooled(0)
    stack.responses[sandbox.url] = _stats(**WEDGE_STATS)
    await stack.probe.probe_once()
    await stack.settle()
    await stack.probe.probe_once()
    rows = stack.executor.lane_supply()
    assert rows["0"].get("recovering", 0) == 1
    assert rows["0"]["device_health"].get("recovering", 0) == 1
    assert rows["0"]["pooled"] == 0


async def test_statusz_recovery_section(stack):
    sandbox = await stack.spawn_pooled(0)
    await stack.executor.fence_host(sandbox.id)
    body = stack.executor.statusz()
    recovery = body["recovery"]
    assert recovery["fencing_enabled"] is True
    assert recovery["fences_total"] == 1
    assert "lane-0" in recovery["recovering"]
    assert recovery["fence_budget"]["max_per_window"] == 4


# ------------------------------------- direct-spawn quarantine gate (ISSUE 14)


async def test_direct_spawn_never_hands_out_recovering_host():
    """THE carried quarantine hole (PR 13 follow-up): on an UNCONSTRAINED
    lane a direct-spawn waiter could be handed a recovering-scope
    replacement mid-quarantine (constrained lanes parked via the standby
    capacity count; unconstrained lanes counted nothing). Now the waiter
    parks behind the standby host and surfaces the bounded retryable
    timeout instead — the recovering host is NEVER handed out."""
    from bee_code_interpreter_fs_tpu.services.code_executor import (
        CapacityTimeoutError,
    )

    s = _Stack(executor_acquire_timeout=0.5)
    try:
        assert s.backend.capacity is None  # unconstrained: the hole's shape
        sandbox = await s.spawn_pooled(0)
        await s.executor.fence_host(sandbox.id, reason="wedged")
        await s.settle()
        assert s.executor.leases.recovering("lane-0")
        # The refill machinery parked the replacement as quarantined
        # standby supply.
        pool = s.executor._pool(0)
        assert pool and all(
            sb.meta.get("device_health") == "recovering" for sb in pool
        )
        spawns_before = s.backend.spawns
        with pytest.raises(CapacityTimeoutError):
            await s.executor.execute("print(1)")
        # The waiter parked: no direct spawn raced the standby host for
        # the scope, and nothing recovering was handed out.
        assert s.backend.spawns == spawns_before
        assert all(
            sb.meta.get("device_health") == "recovering"
            for sb in s.executor._pool(0)
        )
        # Re-admission (the probe's settle shape): streak satisfied, host
        # flipped healthy, lanes kicked — the next request serves.
        registry = s.executor.leases
        registry.note_probe("lane-0", clean=True)
        assert registry.note_probe("lane-0", clean=True)
        for sb in s.executor._pool(0):
            sb.meta["device_health"] = "healthy"
        s.executor._notify_all_lanes()
        result = await s.executor.execute("print(2)")
        assert result.exit_code == 0
    finally:
        await s.close()


async def test_direct_spawn_onto_recovering_scope_parks_its_result():
    """No standby anywhere (the replacement refill hasn't landed): the
    direct spawn still runs — something must exist for the probe to
    re-admit — but its recovering-marked result parks as the scope's
    standby instead of serving, and the next loop's standby gate stops a
    spawn stampede behind it."""
    from bee_code_interpreter_fs_tpu.services.code_executor import (
        CapacityTimeoutError,
    )

    s = _Stack(executor_acquire_timeout=0.5)
    try:
        sandbox = await s.spawn_pooled(0)
        await s.executor.fence_host(sandbox.id, reason="wedged")
        await s.settle()
        # Clear the refilled standby so the scope is recovering with NO
        # live replacement.
        for sb in list(s.executor._pool(0)):
            s.executor._pool(0).remove(sb)
            await s.executor._dispose(sb)
        assert s.executor.leases.recovering("lane-0")
        spawns_before = s.backend.spawns
        with pytest.raises(CapacityTimeoutError):
            await s.executor.execute("print(1)")
        # Exactly ONE spawn happened, and it was parked quarantined, not
        # handed out.
        assert s.backend.spawns == spawns_before + 1
        parked = list(s.executor._pool(0))
        assert len(parked) == 1
        assert parked[0].meta.get("device_health") == "recovering"
    finally:
        await s.close()
