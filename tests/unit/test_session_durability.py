"""Session-durability plane tests at the orchestrator level: hibernate
releases the chip, restore continues the session byte-identically
(session_seq continuous), a fence migrates instead of destroying state,
the restore-in-flight interleave gets the typed refusal, and the kill
switch restores pin-forever semantics byte-for-byte.

The sandbox wire is faked at the same seams the session tests use
(`_post_execute`) plus the two durability seams (`_post_snapshot_op`,
`_capture_workspace`) — everything between them (store, sweep, fence,
session table, capacity accounting) is real.
"""

import asyncio

import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CodeExecutor,
    ExecutorError,
    SessionRestoringError,
)
from bee_code_interpreter_fs_tpu.services.session_store import SESSION_NS
from bee_code_interpreter_fs_tpu.services.storage import Storage


class FakeSandboxServer:
    def __init__(self, executor: CodeExecutor):
        self.served_by: list[str] = []

        async def fake_post_execute(client, base, payload, timeout, sandbox):
            self.served_by.append(sandbox.id)
            return {
                "stdout": "ok\n",
                "stderr": "",
                "exit_code": 0,
                "files": [],
                "warm": True,
            }

        executor._post_execute = fake_post_execute


class FakeSnapshotPlane:
    """Fakes the runner's snapshot/restore ops and the workspace capture.
    Knobs: `restore_gate` parks restores until set (interleave tests),
    `restore_reply` forces one clean refusal, `restore_error` /
    `snapshot_error` force one wire failure."""

    STATE = {
        "version": 1,
        "env_set": {"SESSION_VAR": "42"},
        "env_del": [],
        "cwd": "",
        "modules": [],
        "packages": [],
        "skipped": [],
    }

    def __init__(self, executor: CodeExecutor):
        self.snapshots = 0
        self.restored: list[dict] = []
        self.restore_gate: asyncio.Event | None = None
        self.restore_reply: dict | None = None
        self.restore_error: Exception | None = None
        self.snapshot_error: Exception | None = None

        async def fake_post_snapshot_op(client, base, op, payload, sandbox):
            if op == "snapshot":
                if self.snapshot_error is not None:
                    err, self.snapshot_error = self.snapshot_error, None
                    raise err
                self.snapshots += 1
                return {"ok": True, "state": dict(self.STATE)}
            if self.restore_gate is not None:
                await self.restore_gate.wait()
            if self.restore_error is not None:
                err, self.restore_error = self.restore_error, None
                raise err
            if self.restore_reply is not None:
                reply, self.restore_reply = self.restore_reply, None
                return reply
            self.restored.append(payload["state"])
            return {"ok": True, "skipped": []}

        async def fake_capture_workspace(sandbox):
            return {}

        executor._post_snapshot_op = fake_post_snapshot_op
        executor._capture_workspace = fake_capture_workspace


def make_executor(backend, tmp_path, **config_kwargs):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
        **config_kwargs,
    )
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    return executor, FakeSandboxServer(executor), FakeSnapshotPlane(executor)


async def settle(executor):
    for _ in range(3):
        await asyncio.sleep(0)
    tasks = list(executor._dispose_tasks) + list(executor._fill_tasks)
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)


def age_session(executor, executor_id, seconds):
    session = executor._sessions[executor_id]
    session.last_used -= seconds
    session.idle_accounted = 0.0


def counter(executor, name, **labels):
    fam = getattr(executor.metrics, name)
    for sample_labels, value in fam.samples():
        if all(sample_labels.get(k) == v for k, v in labels.items()):
            return value
    return 0.0


async def test_hibernate_releases_chip_then_restore_continues_seq(tmp_path):
    backend = FakeBackend(capacity=1)
    executor, server, plane = make_executor(backend, tmp_path)
    try:
        first = await executor.execute("x", executor_id="sess-d")
        assert first.session_seq == 1
        assert executor._session_held.get(0) == 1

        # Idle past the hibernate threshold but NOT past the hard idle
        # timeout: the durability leg must fire first.
        age_session(
            executor,
            "sess-d",
            executor.config.session_hibernate_idle_seconds + 1.0,
        )
        assert await executor.sweep_sessions() == 1
        await settle(executor)
        # The chip is back: session_held drained, the session is a record.
        assert executor._session_held.get(0) == 0
        assert plane.snapshots == 1
        assert executor.session_store.entry_count() == 1
        assert counter(executor, "session_hibernates", outcome="hibernate") == 1
        snap = executor.statusz()["session_durability"]
        assert snap["enabled"] is True and snap["hibernated"] == 1

        # Next turn restores lazily: interpreter state shipped back,
        # session_seq CONTINUOUS (2, not a reset to 1), restore phase
        # reported.
        second = await executor.execute("x", executor_id="sess-d")
        assert second.session_seq == 2
        assert second.session_ended is False
        assert plane.restored == [dict(plane.STATE)]
        assert "restore" in second.phases
        assert counter(executor, "session_restores", outcome="restored") == 1
        # The record stays until close/expiry (it is superseded on the
        # next hibernate via first-write-wins on a newer seq).
        assert await executor.close_session("sess-d") is True
        assert executor.session_store.entry_count() == 0
    finally:
        await executor.close()


async def test_restore_in_flight_turn_gets_typed_refusal(tmp_path):
    """THE concurrent-turn interleave regression (satellite 2): a second
    turn arriving mid-restore is refused typed-and-retryable, the restore
    finishes unharmed, and the retry rides the restored session."""
    backend = FakeBackend()
    executor, server, plane = make_executor(backend, tmp_path)
    try:
        await executor.execute("x", executor_id="sess-r")
        age_session(
            executor,
            "sess-r",
            executor.config.session_hibernate_idle_seconds + 1.0,
        )
        assert await executor.sweep_sessions() == 1
        await settle(executor)

        plane.restore_gate = asyncio.Event()
        turn_a = asyncio.ensure_future(
            executor.execute("x", executor_id="sess-r")
        )
        for _ in range(200):
            await asyncio.sleep(0)
            session = executor._sessions.get("sess-r")
            if session is not None and session.restoring:
                break
        assert executor._sessions["sess-r"].restoring is True

        with pytest.raises(SessionRestoringError) as exc_info:
            await executor.execute("x", executor_id="sess-r")
        assert exc_info.value.retry_after > 0
        # The loser did NOT end the session or disturb the restore.
        assert executor._sessions.get("sess-r") is session
        plane.restore_gate.set()
        result = await turn_a
        assert result.session_seq == 2
        # The retry (post-restore) is an ordinary session turn.
        retry = await executor.execute("x", executor_id="sess-r")
        assert retry.session_seq == 3
    finally:
        await executor.close()


async def test_fence_migrates_parked_session_with_state(tmp_path):
    backend = FakeBackend(distinct_urls=True)
    executor, server, plane = make_executor(backend, tmp_path)
    try:
        await executor.execute("x", executor_id="sess-m")
        await executor.execute("x", executor_id="sess-m")
        sandbox = executor._sessions["sess-m"].sandbox
        assert await executor.fence_host(sandbox.id, reason="wedged") == "fenced"
        await settle(executor)
        # Migrated, not destroyed: checkpoint admitted with the session's
        # seq, session table entry gone, chip released.
        assert counter(executor, "session_migrations", outcome="saved") == 1
        assert counter(executor, "session_hibernates", outcome="migrate") == 1
        assert executor.session_store.entry_count() == 1
        assert "sess-m" not in executor._sessions
        assert executor._session_held.get(0) == 0

        # Next turn restores on a HEALTHY host with zero state loss:
        # session_seq continues at 3.
        result = await executor.execute("x", executor_id="sess-m")
        assert result.session_seq == 3
        assert plane.restored == [dict(plane.STATE)]
        assert server.served_by[-1] != sandbox.id
    finally:
        await executor.close()


async def test_fence_falls_back_to_force_close_when_snapshot_fails(tmp_path):
    backend = FakeBackend(distinct_urls=True)
    executor, server, plane = make_executor(backend, tmp_path)
    try:
        await executor.execute("x", executor_id="sess-f")
        plane.snapshot_error = ExecutorError("device wedged mid-snapshot")
        sandbox = executor._sessions["sess-f"].sandbox
        assert await executor.fence_host(sandbox.id, reason="wedged") == "fenced"
        await settle(executor)
        # Pre-durability semantics: force-closed, no record, next turn is
        # an honest fresh session.
        assert counter(executor, "session_migrations", outcome="forced") == 1
        assert executor.session_store.entry_count() == 0
        result = await executor.execute("x", executor_id="sess-f")
        assert result.session_seq == 1
    finally:
        await executor.close()


async def test_clean_refusal_recreates_fresh_with_honest_seq(tmp_path):
    backend = FakeBackend()
    executor, server, plane = make_executor(backend, tmp_path)
    try:
        await executor.execute("x", executor_id="sess-c")
        age_session(
            executor,
            "sess-c",
            executor.config.session_hibernate_idle_seconds + 1.0,
        )
        await executor.sweep_sessions()
        await settle(executor)
        plane.restore_reply = {"ok": False, "reason": "corrupt_state"}
        # The turn still SUCCEEDS — on a genuinely fresh session whose
        # seq=1 reports the state loss honestly; the bad record is gone.
        result = await executor.execute("x", executor_id="sess-c")
        assert result.session_seq == 1
        assert executor.session_store.entry_count() == 0
        assert counter(executor, "session_restores", outcome="fresh") == 1
    finally:
        await executor.close()


async def test_wire_failure_mid_restore_keeps_record_for_retry(tmp_path):
    backend = FakeBackend()
    executor, server, plane = make_executor(backend, tmp_path)
    try:
        await executor.execute("x", executor_id="sess-w")
        age_session(
            executor,
            "sess-w",
            executor.config.session_hibernate_idle_seconds + 1.0,
        )
        await executor.sweep_sessions()
        await settle(executor)
        plane.restore_error = ExecutorError("connection dropped mid-restore")
        with pytest.raises(ExecutorError):
            await executor.execute("x", executor_id="sess-w")
        await settle(executor)
        # The record SURVIVES a wire drop (blob intact) — never a
        # half-restored session: the failed sandbox was closed, and the
        # retry restores byte-exact with seq continuity.
        assert executor.session_store.entry_count() == 1
        result = await executor.execute("x", executor_id="sess-w")
        assert result.session_seq == 2
    finally:
        await executor.close()


async def test_kill_switch_restores_pin_forever_semantics(tmp_path):
    backend = FakeBackend()
    executor, server, plane = make_executor(
        backend, tmp_path, session_durability_enabled=False
    )
    try:
        await executor.execute("x", executor_id="sess-k")
        # Idle far past the hibernate threshold, short of the hard
        # timeout: pre-durability behavior is "stay parked".
        age_session(
            executor,
            "sess-k",
            executor.config.session_hibernate_idle_seconds + 1.0,
        )
        assert await executor.sweep_sessions() == 0
        assert executor._session_held.get(0) == 1
        assert plane.snapshots == 0
        assert executor.session_store.entry_count() == 0
        assert executor.statusz()["session_durability"] == {
            "enabled": False,
            "idle_chip_seconds_total": executor.statusz()[
                "session_durability"
            ]["idle_chip_seconds_total"],
        }
        # No store directory was ever created (no-IO posture).
        assert not (
            tmp_path / "storage" / ".session-store"
        ).exists()
        # A fence force-closes, exactly as before the plane existed.
        sandbox = executor._sessions["sess-k"].sandbox
        await executor.fence_host(sandbox.id, reason="wedged")
        await settle(executor)
        assert executor.session_store.entry_count() == 0
        result = await executor.execute("x", executor_id="sess-k")
        assert result.session_seq == 1
    finally:
        await executor.close()


async def test_idle_chip_seconds_accounting(tmp_path):
    backend = FakeBackend()
    executor, server, plane = make_executor(backend, tmp_path)
    try:
        await executor.execute("x", executor_id="sess-i", chip_count=4)
        age_session(executor, "sess-i", 10.0)
        # Under the hibernate threshold: the sweep only accounts idle.
        assert await executor.sweep_sessions() == 0
        total = executor.statusz()["session_durability"][
            "idle_chip_seconds_total"
        ]
        # ~10 idle seconds x 4 chips.
        assert 35.0 <= total <= 60.0
        assert counter(executor, "session_idle_chip_seconds") == pytest.approx(
            total, abs=0.01
        )
    finally:
        await executor.close()


async def test_close_session_evicts_hibernated_record(tmp_path):
    backend = FakeBackend()
    executor, server, plane = make_executor(backend, tmp_path)
    try:
        await executor.execute("x", executor_id="sess-x")
        age_session(
            executor,
            "sess-x",
            executor.config.session_hibernate_idle_seconds + 1.0,
        )
        await executor.sweep_sessions()
        await settle(executor)
        assert executor.session_store.entry_count() == 1
        # No LIVE session — but DELETE must still kill the checkpoint, or
        # the id resurrects with stale state on reuse.
        assert await executor.close_session("sess-x") is True
        assert executor.session_store.entry_count() == 0
        assert await executor.close_session("sess-x") is False
        fresh = await executor.execute("x", executor_id="sess-x")
        assert fresh.session_seq == 1
    finally:
        await executor.close()


async def test_hibernated_record_is_replica_coherent(tmp_path):
    """A session hibernated by replica A restores behind replica B: the
    record index rides the shared StateStore, the interp blob rides the
    store path both replicas mount."""
    backend_a, backend_b = FakeBackend(), FakeBackend()
    exec_a, _, plane_a = make_executor(backend_a, tmp_path)
    exec_b, _, plane_b = make_executor(backend_b, tmp_path)
    # Splice B onto A's index (the InMemory default is per-process; a
    # shared SQLite store does this for real deployments).
    exec_b.session_store.state = exec_a.session_store.state
    try:
        await exec_a.execute("x", executor_id="sess-ab")
        age_session(
            exec_a, "sess-ab", exec_a.config.session_hibernate_idle_seconds + 1
        )
        await exec_a.sweep_sessions()
        await settle(exec_a)
        assert exec_a.session_store.entry_count() == 1
        result = await exec_b.execute("x", executor_id="sess-ab")
        assert result.session_seq == 2
        assert plane_b.restored == [dict(plane_b.STATE)]
    finally:
        await exec_a.close()
        await exec_b.close()
