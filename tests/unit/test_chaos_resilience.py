"""Chaos suite: CodeExecutor driven against the fault-injecting backend.

Acceptance criteria pinned here (ISSUE 1):
- with ``spawn_fail:0.5,seed:*`` the pool still reaches its fill target and
  executes succeed (the retry engine + refill loop absorb a 50% spawn
  failure rate);
- the breaker cycles closed→open→half-open→closed deterministically, fails
  fast while open, and re-opens on a failed half-open probe;
- ``close()`` leaks no sandboxes and no background tasks while faults
  (spawn failures, refused resets, hanging deletes) are being injected;
- gRPC health flips NOT_SERVING while the lane-0 breaker is open and
  recovers after the half-open probe succeeds.
"""

import asyncio

import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.proto import health_pb2
from bee_code_interpreter_fs_tpu.services.backends.base import SandboxSpawnError
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.circuit_breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerBoard,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CircuitOpenError,
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.grpc_server import HealthServicer
from bee_code_interpreter_fs_tpu.services.storage import Storage
from bee_code_interpreter_fs_tpu.utils.retrying import RetryPolicy


class ScriptedBackend(FakeBackend):
    """FakeBackend whose spawn failures are flipped on/off by the test —
    the deterministic control the breaker-transition assertions need."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.down = False
        self.attempts = 0

    async def spawn(self, chip_count: int = 0):
        self.attempts += 1
        if self.down:
            raise SandboxSpawnError("scripted: backend down")
        return await super().spawn(chip_count)


class FakeClock:
    def __init__(self) -> None:
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


def patch_sandbox_http(executor: CodeExecutor) -> None:
    async def fake_post_execute(client, base, payload, timeout, sandbox):
        return {
            "stdout": "ok\n",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "warm": True,
        }

    executor._post_execute = fake_post_execute


FAST_SPAWN_RETRIES = RetryPolicy(
    attempts=3, base_delay=0.001, max_delay=0.002, retry_on=(SandboxSpawnError,)
)


def make_executor(backend, tmp_path, *, breakers=None, **config_kwargs):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=3,
        **config_kwargs,
    )
    executor = CodeExecutor(
        backend, Storage(config.file_storage_path), config, breakers=breakers
    )
    executor._spawn_retry_policy = FAST_SPAWN_RETRIES
    patch_sandbox_http(executor)
    return executor


async def settle(executor: CodeExecutor) -> None:
    for _ in range(200):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


# ------------------------------------------------------- pool under faults


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
async def test_pool_reaches_fill_target_under_spawn_faults(tmp_path, seed):
    inner = FakeBackend()
    backend = FaultInjectingBackend(
        inner, FaultSpec(spawn_fail=0.5, seed=seed)
    )
    # Threshold far above what a 50% fault rate can string together, so the
    # breaker stays out of this test's way (it has its own tests below).
    executor = make_executor(
        backend, tmp_path, breaker_failure_threshold=1000
    )
    try:
        target = executor.config.executor_pod_queue_target_length
        for _ in range(40):
            await executor.fill_pool()
            if len(executor._pool(0)) >= target:
                break
        assert len(executor._pool(0)) == target, (
            f"pool never reached target under seed={seed}"
        )
        for _ in range(3):
            result = await executor.execute("print('hi')")
            assert result.exit_code == 0
        await settle(executor)
    finally:
        await executor.close()
    assert not inner.live, "close() must dispose every sandbox"


@pytest.mark.parametrize("seed", [3, 11])
async def test_close_leaks_nothing_mid_fault(tmp_path, seed):
    inner = FakeBackend()
    backend = FaultInjectingBackend(
        inner,
        FaultSpec(spawn_fail=0.4, reset_fail=0.5, delete_hang=0.002, seed=seed),
    )
    executor = make_executor(
        backend, tmp_path, breaker_failure_threshold=1000
    )
    try:
        for _ in range(8):
            try:
                await executor.execute("print('hi')")
            except SandboxSpawnError:
                pass  # infra failure surfaced; the pool must still clean up
    finally:
        await executor.close()
    assert not inner.live, "no sandbox may outlive close() under faults"
    assert not executor._dispose_tasks and not executor._fill_tasks


# -------------------------------------------------- breaker state machine


async def test_breaker_cycle_is_deterministic(tmp_path):
    clock = FakeClock()
    board = BreakerBoard(failure_threshold=3, cooldown=30.0, clock=clock)
    backend = ScriptedBackend()
    executor = make_executor(backend, tmp_path, breakers=board)
    lane = board.lane(0)
    try:
        # -- closed → open: one request's 3-attempt ladder crosses the
        # threshold; the request itself surfaces the spawn error.
        backend.down = True
        with pytest.raises(SandboxSpawnError):
            await executor.execute("x")
        await settle(executor)
        assert lane.state == OPEN

        # -- open: fail fast, without touching the backend.
        attempts_before = backend.attempts
        with pytest.raises(CircuitOpenError) as exc_info:
            await executor.execute("x")
        assert backend.attempts == attempts_before, "open lane must not spawn"
        assert exc_info.value.retry_after == pytest.approx(30.0)
        assert executor.degraded()
        assert executor.metrics.breaker_rejections._values[("0",)] >= 1
        # Refills are suppressed while open (they would only feed failures).
        await executor.fill_pool()
        assert backend.attempts == attempts_before

        # -- open → half-open → closed: cooldown elapses, backend recovers,
        # the next request is the probe and its success closes the lane.
        clock.advance(30.1)
        assert lane.state == HALF_OPEN
        assert not executor.degraded(), "half-open accepts probe traffic"
        backend.down = False
        result = await executor.execute("x")
        assert result.exit_code == 0
        assert lane.state == CLOSED
        await settle(executor)

        # -- re-open, then a FAILED half-open probe re-opens immediately:
        # exactly one backend attempt is spent, the rest fail fast.
        # (Drain the warm pool first — recycled sandboxes would rightly
        # keep serving and never exercise the spawn path.)
        backend.down = True
        for sandbox in list(executor._pool(0)):
            executor._pool(0).remove(sandbox)
            await backend.delete(sandbox)
        with pytest.raises(SandboxSpawnError):
            await executor.execute("x")
        await settle(executor)
        assert lane.state == OPEN
        clock.advance(30.1)
        assert lane.state == HALF_OPEN
        attempts_before = backend.attempts
        with pytest.raises(CircuitOpenError):
            await executor.execute("x")
        assert backend.attempts == attempts_before + 1, (
            "a failed probe must re-open after exactly one attempt"
        )
        assert lane.state == OPEN
    finally:
        backend.down = False
        await executor.close()
    assert not backend.live


async def test_open_breaker_skips_acquire_wait(tmp_path):
    """The 300s acquire budget must NOT be burned while the lane is known
    to be down: the waiter path fails fast too (not just direct spawns)."""
    clock = FakeClock()
    board = BreakerBoard(failure_threshold=1, cooldown=60.0, clock=clock)
    backend = ScriptedBackend()
    backend.down = True
    executor = make_executor(
        backend, tmp_path, breakers=board, executor_acquire_timeout=300.0
    )
    try:
        board.lane(0).record_failure()  # breaker pre-opened
        loop = asyncio.get_running_loop()
        start = loop.time()
        with pytest.raises(CircuitOpenError):
            await executor.execute("x")
        assert loop.time() - start < 5.0, "must fail fast, not wait 300s"
    finally:
        await executor.close()


async def test_pooled_sandboxes_still_serve_while_open(tmp_path):
    """Graceful degradation serves what is already warm: an open breaker
    stops NEW spawns, not requests a pooled sandbox can satisfy."""
    clock = FakeClock()
    board = BreakerBoard(failure_threshold=1, cooldown=60.0, clock=clock)
    backend = ScriptedBackend()
    executor = make_executor(backend, tmp_path, breakers=board)
    try:
        await executor.fill_pool()
        assert len(executor._pool(0)) == 3
        backend.down = True
        board.lane(0).record_failure()
        result = await executor.execute("x")
        assert result.exit_code == 0
        await settle(executor)
    finally:
        backend.down = False
        await executor.close()


async def test_degraded_tracks_the_configured_default_lane(tmp_path):
    """Regression: degraded() must watch config.default_chip_count, not a
    literal lane 0 — a TPU deployment defaulting to 4-chip slices whose
    4-chip backend is down must flip health even though lane 0 never took
    traffic."""
    clock = FakeClock()
    board = BreakerBoard(failure_threshold=1, cooldown=30.0, clock=clock)
    backend = ScriptedBackend()
    executor = make_executor(
        backend, tmp_path, breakers=board, default_chip_count=4
    )
    try:
        assert not executor.degraded()
        board.lane(4).record_failure()
        assert executor.degraded()
        assert executor.degraded_retry_after() == pytest.approx(30.0)
        board.lane(0).record_failure()
        board.lane(4).record_success()
        assert not executor.degraded(), "lane 0 is not the default lane here"
    finally:
        await executor.close()


# ------------------------------------------------------------ health flip


async def test_grpc_health_flips_with_breaker(tmp_path):
    clock = FakeClock()
    board = BreakerBoard(failure_threshold=1, cooldown=30.0, clock=clock)
    backend = ScriptedBackend()
    executor = make_executor(backend, tmp_path, breakers=board)
    health = HealthServicer(degraded_check=executor.degraded)
    request = health_pb2.HealthCheckRequest(service="")
    try:
        response = await health.Check(request, None)
        assert response.status == health_pb2.HealthCheckResponse.SERVING

        board.lane(0).record_failure()
        response = await health.Check(request, None)
        assert response.status == health_pb2.HealthCheckResponse.NOT_SERVING

        # Half-open: probes may flow again, so the lane advertises SERVING
        # (a NOT_SERVING lane would never receive the probe that heals it).
        clock.advance(30.1)
        response = await health.Check(request, None)
        assert response.status == health_pb2.HealthCheckResponse.SERVING

        # Probe success pins it closed; manual kill switch still wins.
        board.lane(0).record_success()
        response = await health.Check(request, None)
        assert response.status == health_pb2.HealthCheckResponse.SERVING
        health.serving = False
        response = await health.Check(request, None)
        assert response.status == health_pb2.HealthCheckResponse.NOT_SERVING
    finally:
        await executor.close()
