"""Tests for the lazy fusion engine under the dispatch shim."""

import pytest

from bee_code_interpreter_fs_tpu.ops import npdispatch
from bee_code_interpreter_fs_tpu.ops.npdispatch import lazy
from bee_code_interpreter_fs_tpu.ops.npdispatch.shim import TpuArray

THRESHOLD = 1000
N = THRESHOLD * 4


@pytest.fixture
def np_shim():
    npdispatch.install(threshold=THRESHOLD)
    import numpy as np

    yield np
    npdispatch.uninstall()


def test_ops_stay_lazy_until_forced(np_shim):
    a = np_shim.ones(N)
    b = (a * 2 + 1).sum()
    assert isinstance(b, TpuArray)
    assert b._node is not None  # not executed yet
    assert b.shape == ()  # shape known without executing
    assert float(b) == 3 * N  # forcing executes the fused graph
    assert b._node is None


def test_whole_chain_is_one_graph(np_shim):
    a = np_shim.random.rand(N)
    s = (a * a).sum()
    # rand -> mul -> sum is one DAG of 3 unique nodes (a*a's shared child
    # counts once), not three executions
    assert s._node is not None
    assert s._node.n_nodes == 3
    value = float(s)
    assert 0.25 * N < value < 0.42 * N


def test_structure_cache_reuse(np_shim):
    lazy._exec_cache.clear()
    for _ in range(3):
        a = np_shim.ones(N)
        _ = float((a + 1).sum())
    # same structure every iteration -> exactly one compiled runner
    assert len(lazy._exec_cache) == 1


def test_different_statics_different_cache_entries(np_shim):
    # regression: statics must be part of the structure key — a cached runner
    # for a[0:10] must not be reused for a[5:15]
    lazy._exec_cache.clear()
    a = np_shim.arange(N, dtype="float32")
    first = a[0:10]
    second = a[5:15]
    assert float(first.sum()) == sum(range(10))
    assert float(second.sum()) == sum(range(5, 15))
    assert len(lazy._exec_cache) >= 2


def test_setitem_chain_lazy(np_shim):
    a = np_shim.zeros(N)
    a[0] = 1.0
    a[1] = 2.0
    a += 3.0
    assert a._node is not None
    assert float(a.sum()) == 1.0 + 2.0 + 3.0 * N


def test_shared_subgraph_dedup(np_shim):
    a = np_shim.ones(N)
    b = a * 2  # shared subexpression
    c = (b + b).sum()
    assert float(c) == 4 * N


def test_graph_size_cap(np_shim):
    a = np_shim.ones(N)
    for i in range(lazy.MAX_GRAPH_NODES + 50):
        a = a + 1.0
    # must not blow up; forced chunked materialization keeps it correct
    assert float(a[0]) == 1.0 + lazy.MAX_GRAPH_NODES + 50


def test_dtype_and_len_lazy(np_shim):
    a = np_shim.arange(N, dtype="float32")
    b = a.astype("int32")
    assert b._node is not None
    assert b.dtype == np_shim.dtype("int32")
    assert len(b) == N
    assert b._node is not None  # len/dtype didn't force
    assert int(b[5]) == 5


def test_reshape_matmul_lazy_correct(np_shim):
    m = np_shim.arange(64 * 64, dtype="float32").reshape(64, 64)
    identity = np_shim.eye(64, dtype="float32")
    # eye(64) is below threshold -> host ndarray; matmul mixes host + device
    product = m @ np_shim.asarray(identity)
    assert bool(np_shim.allclose(product, m))


def test_mixed_eager_fallback_still_correct(np_shim):
    a = np_shim.ones(N)
    host = a.__array__()
    assert host.sum() == N
    # forcing twice is stable
    assert float(a.sum()) == N
    assert float(a.sum()) == N


def test_transpose_varargs_and_divmod(np_shim):
    m = np_shim.arange(2000, dtype="float32").reshape(40, 50)
    t1 = m.transpose(1, 0)
    t2 = m.transpose((1, 0))
    t3 = m.T
    assert t1.shape == t2.shape == t3.shape == (50, 40)
    a = np_shim.ones(N) * 7
    q, r = divmod(a, 3)
    assert float(q[0]) == 2.0 and float(r[0]) == 1.0


def test_weak_typed_scalar_statics_not_conflated(np_shim):
    import numpy as real

    lazy._exec_cache.clear()
    a = np_shim.ones(N, dtype="float32")
    x = a * 2.0
    y = a * real.float64(2.0)
    # x stays float32 (weak python scalar); the np.float64 scalar must not
    # reuse x's cached runner
    assert float(x[0]) == 2.0 and float(y[0]) == 2.0
    assert x.dtype == real.dtype("float32")


def test_big_list_operand_not_baked_static(np_shim):
    a = np_shim.ones(N)
    b = a + [0.5] * N  # must become a leaf/eager path, not a giant static
    assert float(b[0]) == 1.5


def test_host_array_snapshot_at_call_time(np_shim):
    """numpy reads operand values at call time: mutating the caller's array
    between graph build and forcing must not change the result."""
    import numpy as real_np

    h = real_np.zeros(N)  # genuine host ndarray, big enough to dispatch
    c = np_shim.array(h)  # np.array must copy at call time
    big = np_shim.ones(N)
    b = big + h  # host leaf inside a lazy device graph
    h[:] = 7.0
    assert float(np_shim.asarray(c).sum()) == 0.0
    assert float(b.sum()) == float(N)


def test_reshape_order_f(np_shim):
    m = np_shim.arange(6 * THRESHOLD, dtype="float32").reshape(2, 3 * THRESHOLD)
    out = np_shim.asarray(m.reshape(3 * THRESHOLD, 2, order="F"))
    import numpy as real_np

    expected = real_np.asarray(np_shim.asarray(m)).reshape(3 * THRESHOLD, 2, order="F")
    assert (out == expected).all()


def test_shared_subexpression_stays_fused(np_shim):
    """x = x + x doubling: 9 unique nodes, far under the graph cap — the
    per-reference count would have exploded past 200 and forced splits."""
    x = np_shim.ones(N)
    for _ in range(8):
        x = x + x
    assert x._node is not None
    assert x._node.n_nodes == 9
    assert float(x[0]) == 256.0


def test_astype_casting_semantics(np_shim):
    a = np_shim.ones(N, dtype="float64")
    with pytest.raises(TypeError):
        a.astype("int32", casting="safe")


def test_reshape_order_a(np_shim):
    m = np_shim.arange(6 * THRESHOLD, dtype="float32").reshape(2, 3 * THRESHOLD)
    out = m.reshape(3 * THRESHOLD, 2, order="A")  # == C for device arrays
    assert float(np_shim.asarray(out)[0, 1]) == 1.0


def test_random_shuffle_tpuarray(np_shim):
    a = np_shim.arange(N, dtype="float32")
    np_shim.random.shuffle(a)
    assert float(a.sum()) == float(N * (N - 1) / 2)
