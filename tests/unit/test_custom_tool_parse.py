import pytest

from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
    CustomToolParseError,
)

parser = CustomToolExecutor(code_executor=None)


def parse(source: str):
    return parser.parse(source)


def test_basic_types():
    tool = parse(
        "def f(a: int, b: float, c: str, d: bool) -> str:\n    return ''"
    )
    props = tool.input_schema["properties"]
    assert props["a"]["type"] == "integer"
    assert props["b"]["type"] == "number"
    assert props["c"]["type"] == "string"
    assert props["d"]["type"] == "boolean"
    assert tool.input_schema["required"] == ["a", "b", "c", "d"]


def test_nested_generics():
    tool = parse(
        "import typing\n"
        "def f(m: dict[str, list[typing.Optional[int]]], "
        "t: tuple[int, str]) -> None:\n    return None"
    )
    m = tool.input_schema["properties"]["m"]
    assert m["type"] == "object"
    assert m["additionalProperties"]["type"] == "array"
    assert m["additionalProperties"]["items"]["anyOf"][1] == {"type": "null"}
    t = tool.input_schema["properties"]["t"]
    assert t["prefixItems"] == [{"type": "integer"}, {"type": "string"}]


def test_pep604_union():
    tool = parse("def f(x: int | None = None) -> None:\n    return None")
    x = tool.input_schema["properties"]["x"]
    assert {"type": "integer"} in x["anyOf"]
    assert {"type": "null"} in x["anyOf"]
    assert tool.input_schema["required"] == []


def test_kwonly_required():
    tool = parse("def f(*, x: int, y: int = 3) -> int:\n    return x")
    assert tool.input_schema["required"] == ["x"]


def test_docstring_extraction():
    tool = parse(
        'def f(x: int) -> int:\n'
        '    """Do the thing.\n\n'
        '    Longer prose here.\n\n'
        '    :param x: the x\n'
        '       continued over lines\n'
        '    :return: doubled x\n'
        '    """\n'
        '    return 2 * x'
    )
    assert tool.description.startswith("Do the thing.")
    assert tool.input_schema["properties"]["x"]["description"] == (
        "the x continued over lines"
    )
    # Tool-card parity (VERDICT r2 #5): the return contract — annotation and
    # :return: doc — is part of the description, and the schema identifies
    # itself ($schema/title) as the reference's does.
    assert tool.description.endswith("Returns: int -- doubled x")
    assert tool.input_schema["$schema"] == "http://json-schema.org/draft-07/schema#"
    assert tool.input_schema["title"] == "f"


def test_return_contract_variants():
    # annotation only
    tool = parse('def f(x: int) -> str:\n    """Go."""\n    return "s"')
    assert tool.description == "Go.\n\nReturns: str"
    # :return: doc only
    tool = parse(
        'def f(x: int):\n    """Go.\n\n    :return: a greeting\n    """\n    return 1'
    )
    assert tool.description == "Go.\n\nReturns: a greeting"
    # neither -> no Returns section
    tool = parse('def f(x: int):\n    """Go."""\n    return 1')
    assert tool.description == "Go."


@pytest.mark.parametrize(
    "source,fragment",
    [
        ("def f(*args): pass", "*args"),
        ("def f(**kw): pass", "**kwargs"),
        ("def f(a, /, b: int): pass", "positional-only"),
        ("def f(a): pass", "missing a type annotation"),
        ("x = 1\ndef f(a: int): pass", "unexpected top-level"),
        ("def f(a: int): pass\ndef g(b: int): pass", "exactly one function"),
        ("async def f(a: int): pass", "async"),
        ("import os", "must define a function"),
        ("def f(a: SomeUnknownClass): pass", "unsupported type"),
        ("def f(a: dict[int, str]): pass", "keys must be str"),
        ("def f(:", "syntax error"),
    ],
)
def test_parse_errors(source, fragment):
    with pytest.raises(CustomToolParseError) as exc_info:
        parse(source)
    assert any(fragment in m for m in exc_info.value.errors), exc_info.value.errors


def test_wrapper_script_shape():
    script = CustomToolExecutor._build_wrapper(
        "import math\ndef f(x: int) -> float:\n    return math.sqrt(x)",
        ["import math"],
        "f",
        {"x": 16},
    )
    assert script.startswith("import math")
    compile(script, "<wrapper>", "exec")  # must be valid python
