"""Store-loss tolerance: the control plane must survive the shared store
dying. Covers the ResilientStateStore wrapper's per-namespace degraded
policies (shadow / fenced / journal / fail_closed), the health breaker's
transitions and heal (journal replay, shadow drop), the seeded
store-outage fault injector's determinism, and the subsystem halves —
lease mints failing closed with fence floors queued for replay, quota
fleet windows failing open, session restore refusing typed.
"""

import pytest

from bee_code_interpreter_fs_tpu.services.backends.faults import (
    FaultInjectingStateStore,
    StoreFaultSpec,
)
from bee_code_interpreter_fs_tpu.services.errors import StateStoreDegradedError
from bee_code_interpreter_fs_tpu.services.leases import LeaseRegistry
from bee_code_interpreter_fs_tpu.services.quotas import _FleetWindows
from bee_code_interpreter_fs_tpu.services.session_store import (
    SESSION_NS,
    SessionStore,
)
from bee_code_interpreter_fs_tpu.services.state_store import (
    InMemoryStateStore,
    ResilientStateStore,
    StateStoreUnavailableError,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage


class FlakyStore(InMemoryStateStore):
    """An in-memory store with a kill switch: `down=True` makes every op
    raise the transport error — the deterministic outage the wrapper and
    the subsystems are exercised against."""

    def __init__(self) -> None:
        super().__init__(shared=True)
        self.down = False
        self.ops = 0

    def _gate(self):
        self.ops += 1
        if self.down:
            raise StateStoreUnavailableError("store is down (test)")

    def get(self, ns, key):
        self._gate()
        return super().get(ns, key)

    def put(self, ns, key, value):
        self._gate()
        return super().put(ns, key, value)

    def delete(self, ns, key):
        self._gate()
        return super().delete(ns, key)

    def items(self, ns):
        self._gate()
        return super().items(ns)

    def incr(self, ns, key, delta=1.0):
        self._gate()
        return super().incr(ns, key, delta)

    def mutate(self, ns, key, fn):
        self._gate()
        return super().mutate(ns, key, fn)


class Clock:
    def __init__(self, now=0.0):
        self.now = now

    def __call__(self):
        return self.now


def resilient(**kwargs):
    inner = FlakyStore()
    clock = kwargs.pop("clock", None) or Clock()
    kwargs.setdefault("failure_threshold", 2)
    kwargs.setdefault("cooldown", 5.0)
    wrapper = ResilientStateStore(inner, clock=clock, **kwargs)
    return wrapper, inner, clock


# ------------------------------------------------------- per-namespace policy


def test_shadow_namespaces_fail_open_replica_local():
    store, inner, clock = resilient()
    store.put("wfq", "tenant-a", {"tag": 3.0})
    inner.down = True
    # Fail open: reads fall back (shadow starts empty — fleet coherence is
    # what the outage costs), writes land replica-locally and keep working.
    assert store.get("wfq", "tenant-a") is None
    store.put("wfq", "tenant-a", {"tag": 7.0})
    assert store.get("wfq", "tenant-a") == {"tag": 7.0}
    assert store.mutate(
        "breaker", "lane-4", lambda cur: ({"state": "open"}, "ok")
    ) == "ok"
    assert store.items("breaker") == {"lane-4": {"state": "open"}}
    assert store.degraded and store.degraded_ops > 0
    # The inner store never saw the degraded writes.
    inner.down = False
    assert inner.get("wfq", "tenant-a") == {"tag": 3.0}


def test_fenced_reads_serve_cache_writes_refuse():
    store, inner, clock = resilient()
    store.put("lease_floor", "host-1", 12)
    assert store.get("lease_floor", "host-1") == 12  # primes the cache
    store.items("lease_floor")
    inner.down = True
    # Reads serve the last-known value (floors only rise: stale can only
    # under-refuse)...
    assert store.get("lease_floor", "host-1") == 12
    assert store.items("lease_floor") == {"host-1": 12}
    # ...while every write fails closed with the typed error.
    with pytest.raises(StateStoreDegradedError) as exc:
        store.put("lease_floor", "host-1", 13)
    assert exc.value.subsystem == "leases"
    assert exc.value.retry_after >= 1.0
    with pytest.raises(StateStoreDegradedError):
        store.incr("lease_gen", "host-1")
    with pytest.raises(StateStoreDegradedError):
        store.mutate("lease_fence", "host-1", lambda cur: ({}, None))


def test_fail_closed_namespace_refuses_everything():
    store, inner, clock = resilient()
    store.put("session_durable", "t/sess", {"seq": 3})
    inner.down = True
    for op in (
        lambda: store.get("session_durable", "t/sess"),
        lambda: store.items("session_durable"),
        lambda: store.put("session_durable", "t/sess", {"seq": 4}),
        lambda: store.delete("session_durable", "t/sess"),
    ):
        with pytest.raises(StateStoreDegradedError) as exc:
            op()
        assert exc.value.subsystem == "sessions"


def test_journal_incrs_replay_on_reconnect():
    store, inner, clock = resilient()
    store.incr("quota_win", "t|chip|100", 5.0)
    inner.down = True
    # Fail open: accrual keeps counting replica-locally...
    assert store.incr("quota_win", "t|chip|100", 2.0) == 2.0
    assert store.incr("quota_win", "t|chip|100", 3.0) == 5.0
    assert store.health()["journal_depth"] == 2
    # ...and the journal replays into the real store on the first healthy
    # op (increments are commutative — nothing double-counts, nothing is
    # lost).
    inner.down = False
    clock.now += 6.0  # past the breaker cooldown: next op probes through
    store.get("wfq", "anything")
    assert inner.get("quota_win", "t|chip|100") == 10.0
    assert store.health()["journal_depth"] == 0
    assert store.journal_replays == 1
    assert not store.degraded


def test_ttl_helpers_follow_namespace_policy():
    """put_ttl/get_live ride the __ttl__: sidecar namespace — policy must
    strip the prefix (a lease_fence TTL record is still FENCED)."""
    store, inner, clock = resilient()
    store.put_ttl("replicas", "r1", {"load": 2}, 30.0, now=0.0)
    inner.down = True
    # replicas is SHADOW: heartbeats keep working replica-locally.
    store.put_ttl("replicas", "r1", {"load": 5}, 30.0, now=1.0)
    assert store.get_live("replicas", "r1", now=2.0) == {"load": 5}
    with pytest.raises(StateStoreDegradedError):
        store.put_ttl("lease_fence", "host-1", {"reason": "wedged"}, 30.0)


# ------------------------------------------------------- breaker transitions


def test_breaker_opens_stops_hammering_and_heals():
    store, inner, clock = resilient(failure_threshold=2, cooldown=5.0)
    inner.down = True
    store.get("wfq", "k")
    store.get("wfq", "k")
    assert store.degraded and store.outages == 1
    # Breaker open: degraded ops stop touching the dead store entirely.
    before = inner.ops
    for _ in range(10):
        store.get("wfq", "k")
    assert inner.ops == before
    # Cooldown elapses -> half-open probe-through; the store is back, one
    # success heals.
    inner.down = False
    clock.now += 6.0
    store.get("wfq", "k")
    assert not store.degraded
    assert store.health()["state"] == "closed"
    # A second outage counts as a new outage (transition-edged).
    inner.down = True
    store.get("wfq", "k")
    assert store.outages == 2


def test_probe_forces_the_health_question():
    store, inner, clock = resilient()
    inner.down = True
    store.get("wfq", "k")
    store.get("wfq", "k")
    assert store.degraded
    inner.down = False
    assert store.probe() is False  # breaker still open, probe refused
    clock.now += 6.0
    assert store.probe() is True
    assert not store.degraded


# ------------------------------------------------- seeded outage injection


def test_store_fault_spec_outage_is_deterministic():
    spec = StoreFaultSpec.parse("outage_after:3,outage_ops:2,seed:7")
    outcomes = []
    store = FaultInjectingStateStore(InMemoryStateStore(shared=True), spec)
    for i in range(12):
        try:
            store.put("ns", f"k{i}", i)
            outcomes.append(1)
        except StateStoreUnavailableError:
            outcomes.append(0)
    # Periodic and reproducible: 3 healthy ops, then the tripping op plus
    # outage_ops more fail (3 failures), repeat.
    assert outcomes == [1, 1, 1, 0, 0, 0, 1, 1, 1, 0, 0, 0]


def test_store_fault_spec_drop_rate_seeded():
    spec = StoreFaultSpec.parse("drop:0.5,seed:1337")
    runs = []
    for _ in range(2):
        store = FaultInjectingStateStore(
            InMemoryStateStore(shared=True),
            StoreFaultSpec.parse("drop:0.5,seed:1337"),
        )
        outcome = []
        for i in range(20):
            try:
                store.incr("ns", "k")
                outcome.append(1)
            except StateStoreUnavailableError:
                outcome.append(0)
        runs.append(outcome)
    assert runs[0] == runs[1]  # same seed, same plan
    assert 0 < sum(runs[0]) < 20  # actually dropping, not all-or-nothing
    assert spec.active


def test_partition_wraps_one_replica_only():
    """An asymmetric partition: replica A's handle is faulted, replica B's
    is not — B keeps full service against the same backing state."""
    backing = InMemoryStateStore(shared=True)
    a = FaultInjectingStateStore(
        backing, StoreFaultSpec.parse("drop:1.0,seed:7")
    )
    b = backing
    with pytest.raises(StateStoreUnavailableError):
        a.put("ns", "k", 1)
    b.put("ns", "k", 2)
    assert b.get("ns", "k") == 2


# ------------------------------------------------------------ lease half


def test_lease_mint_fails_closed_during_outage():
    store, inner, clock = resilient()
    registry = LeaseRegistry(store=store)
    lease = registry.mint("host-1")
    assert lease.generation == 1
    inner.down = True
    with pytest.raises(StateStoreDegradedError):
        registry.mint("host-1")
    assert registry.degraded_mint_refusals == 1
    # The existing lease keeps serving: not revoked, floor cache empty.
    assert not registry.stale(lease)
    # Store heals (breaker cooldown elapses): minting resumes on the
    # fleet counter, strictly newer.
    inner.down = False
    clock.now += 6.0
    assert registry.mint("host-1").generation == 2


def test_fence_during_outage_queues_floor_and_replays():
    store, inner, clock = resilient()
    registry = LeaseRegistry(store=store)
    lease = registry.mint("host-1")
    inner.down = True
    registry.fence(lease, reason="wedged")
    # The local half landed: the lease is refused HERE immediately, off
    # the pending floor, before the store ever hears about it.
    assert lease.revoked
    assert registry.stale(lease)
    assert registry.snapshot()["pending_fence_floors"] == {"host-1": 1}
    # Reconnect: the next healthy lease op flushes the floor to the fleet.
    inner.down = False
    clock.now += 6.0
    registry.mint("host-2")
    assert registry.snapshot()["pending_fence_floors"] == {}
    assert inner.get("lease_floor", "host-1") == 1


def test_stale_serves_cached_floor_during_outage():
    store, inner, clock = resilient()
    registry_a = LeaseRegistry(store=store)
    lease_old = registry_a.mint("host-1")
    lease_new = registry_a.mint("host-1")
    # A peer's fence raised the floor past the old lease; a healthy stale()
    # read caches it.
    inner.put("lease_floor", "host-1", 1)
    assert registry_a.stale(lease_old)
    assert not registry_a.stale(lease_new)
    inner.down = True
    # Outage: the cached floor still refuses the stale lease and still
    # serves the live one.
    assert registry_a.stale(lease_old)
    assert not registry_a.stale(lease_new)


def test_zero_double_grants_across_replicas_through_outage():
    """The bench invariant, unit-sized: generations minted by two replicas
    around an outage never collide (fencing tokens stay unique)."""
    store_a, inner, clock_a = resilient()
    # Replica B shares the same inner store through its own wrapper.
    clock_b = Clock()
    store_b = ResilientStateStore(inner, failure_threshold=2, clock=clock_b)
    a = LeaseRegistry(store=store_a)
    b = LeaseRegistry(store=store_b)
    minted = [a.mint("host-1"), b.mint("host-1")]
    inner.down = True
    for registry in (a, b):
        with pytest.raises(StateStoreDegradedError):
            registry.mint("host-1")
    inner.down = False
    clock_a.now += 6.0
    clock_b.now += 6.0
    minted += [b.mint("host-1"), a.mint("host-1")]
    generations = [lease.generation for lease in minted]
    assert len(set(generations)) == len(generations)
    assert generations == sorted(generations)


# ------------------------------------------------------------ quota half


def test_fleet_windows_fail_open_and_reconcile():
    clock = Clock(now=1000.0)
    store, inner, _ = resilient(clock=clock)
    fleet = _FleetWindows(store, walltime=clock)
    fleet.add("tenant-a", "chip", 10.0, window=80.0)
    assert fleet.used("tenant-a", "chip", 80.0) == 10.0
    inner.down = True
    # Outage: accrual fails OPEN — publish keeps succeeding against the
    # wrapper (journal), the fleet view degrades to whatever the shadow
    # holds, and nothing raises on the admit path.
    fleet.add("tenant-a", "chip", 5.0, window=80.0)
    clock.now += 1.0  # age past the items() read TTL
    assert fleet.used("tenant-a", "chip", 80.0) == 5.0  # shadow-local view
    assert fleet.publish_errors == 0  # wrapper absorbed it: no raw failure
    # Reconnect: journaled deltas replay; within one window the fleet view
    # reconverges to the full accrual.
    inner.down = False
    clock.now += 6.0  # past the breaker cooldown
    store.get("wfq", "poke")  # heal + replay
    clock.now += 1.0
    assert fleet.used("tenant-a", "chip", 80.0) == 15.0


def test_fleet_windows_bare_store_outage_counts_publish_errors():
    """Against a BARE store (resilience wrapper off) the fleet half still
    fails open — deltas are lost to the fleet but admission never breaks."""
    clock = Clock(now=1000.0)
    inner = FlakyStore()
    fleet = _FleetWindows(inner, walltime=clock)
    inner.down = True
    fleet.add("tenant-a", "chip", 5.0, window=80.0)
    clock.now += 1.0
    assert fleet.used("tenant-a", "chip", 80.0) == 0.0
    assert fleet.publish_errors >= 1
    assert fleet.snapshot()["publish_errors"] == fleet.publish_errors


# ---------------------------------------------------------- session half


class WallClock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now


async def test_session_restore_fails_closed_observers_fail_open(tmp_path):
    store, inner, clock = resilient()
    sessions = SessionStore(
        tmp_path / "session-store",
        store,
        Storage(tmp_path / "objects"),
        clock=WallClock(),
    )
    ws = {"a.txt": await Storage(tmp_path / "objects").write(b"bytes")}
    assert (
        await sessions.save(
            "t1", "sess-a", lane=4, seq=1, interp_state={}, workspace=ws
        )
        == "admitted"
    )
    assert sessions.hibernated_by_lane() == {4: 1}
    inner.down = True
    # Restore fails CLOSED with the typed error (restoring blind would
    # fork the session when the checkpoint reappears)...
    with pytest.raises(StateStoreDegradedError) as exc:
        await sessions.load("t1", "sess-a")
    assert exc.value.subsystem == "sessions"
    # ...while observational surfaces fail open (sweep survives, counts
    # serve the last-known view, hibernated supply stays visible).
    assert sessions.sweep_expired() == 0
    assert sessions.entry_count() == 0
    assert sessions.hibernated_by_lane() == {4: 1}  # cached view
    # Save degrades to the existing "error" outcome, never an exception.
    assert (
        await sessions.save(
            "t1", "sess-b", lane=2, seq=1, interp_state={}, workspace=ws
        )
        == "error"
    )
    inner.down = False
    clock.now += 6.0
    record = await sessions.load("t1", "sess-a")
    assert record is not None and record["seq"] == 1
    assert inner.get(SESSION_NS, "t1/sess-a") is not None
