"""Pallas flash-attention kernel vs the dense oracle (interpret mode on the
CPU test platform; the identical kernel lowers via Mosaic on TPU, where it
was measured faster than XLA's fused dense attention at t=2048 bf16 and,
unlike it, never materializes the [t, t] score matrix)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    _expand_gqa,
    _plain_causal_attention,
    forward,
    init_params,
)
from bee_code_interpreter_fs_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize(
    "b,t,h,d,bq,bk",
    [
        (2, 64, 4, 16, 16, 16),
        (1, 100, 2, 32, 32, 16),  # t not divisible by blocks: padding path
        (1, 16, 1, 8, 64, 64),  # blocks larger than the sequence
        # Unequal defaults with t between them and not a tile multiple: the
        # clamped block must round back to a power of two dividing the
        # shared padded length (regression: block_k clamped to 900 over an
        # array padded to 1024 for block_q=512 satisfied neither of
        # Mosaic's rules).
        (1, 900, 1, 16, 512, 1024),
    ],
)
def test_matches_dense_oracle(b, t, h, d, bq, bk):
    key = jax.random.PRNGKey(t)
    q, k, v = (
        jax.random.normal(kk, (b, t, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    got = flash_attention(q, k, v, block_q=bq, block_k=bk, interpret=True)
    want = _plain_causal_attention(q, k, v, d ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gqa_via_expand():
    b, t, nh, nkv, d = 1, 32, 4, 2, 16
    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, nh, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, nkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, nkv, d), jnp.float32)
    ke, ve = _expand_gqa(k, v, nh)
    got = flash_attention(q, ke, ve, block_q=16, block_k=16, interpret=True)
    want = _plain_causal_attention(q, ke, ve, d ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_with_flash_impl_matches_plain():
    cfg_plain = LlamaConfig.tiny(dtype="float32")
    cfg_flash = LlamaConfig.tiny(dtype="float32", attn_impl="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_plain)
    tokens = jax.random.randint(
        jax.random.PRNGKey(14), (2, 24), 0, cfg_plain.vocab_size
    )
    want = forward(params, tokens, cfg_plain)
    got = forward(params, tokens, cfg_flash)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_partial_kernel_single_chunk_equals_full():
    """Folding one chunk from a zero carry must equal full flash/dense
    attention (the ring step's base case)."""
    from bee_code_interpreter_fs_tpu.ops.flash_attention import (
        flash_attention_partial,
    )

    b, t, h, d = 1, 64, 2, 16
    key = jax.random.PRNGKey(3)
    q, k, v = (
        jax.random.normal(kk, (b, t, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    acc = jnp.zeros((b, h, t, d), jnp.float32)
    m = jnp.full((b, h, t), -1e30, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)
    acc, m, l = flash_attention_partial(
        q, k, v, acc, m, l, q_offset=0, k_offset=0, block_q=16, block_k=16,
        interpret=True,
    )
    got = (acc / l[..., None]).transpose(0, 2, 1, 3)
    want = _plain_causal_attention(q, k, v, d ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_with_flash_kernel():
    """ring_attention(use_flash=True) on the sp mesh — the Pallas kernel
    inside the ring schedule — must match plain causal attention, including
    the fully-masked future chunks the ring streams past each device."""
    from functools import partial as fpartial

    from bee_code_interpreter_fs_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    from bee_code_interpreter_fs_tpu.parallel import (
        best_mesh_shape,
        make_mesh,
        ring_attention,
    )

    mesh = make_mesh(best_mesh_shape(8, tp=2, sp=2))
    b, t, h, d = 2, 64, 4, 16
    key = jax.random.PRNGKey(4)
    q, k, v = (
        jax.random.normal(kk, (b, t, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    want = _plain_causal_attention(q, k, v, d ** -0.5)
    got = shard_map(
        fpartial(
            ring_attention, axis_name="sp", use_flash=True,
            flash_interpret=True, flash_block=16,
        ),
        mesh=mesh,
        in_specs=(P("dp", "sp", "tp", None),) * 3,
        out_specs=P("dp", "sp", "tp", None),
        check_rep=False,
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_non_divisible_chunks():
    """Per-device chunks that don't divide the kernel blocks must pad
    internally (a config the einsum ring path always handled)."""
    from functools import partial as fpartial

    from bee_code_interpreter_fs_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    from bee_code_interpreter_fs_tpu.parallel import (
        best_mesh_shape,
        make_mesh,
        ring_attention,
    )

    mesh = make_mesh(best_mesh_shape(8, tp=2, sp=2))
    b, t, h, d = 2, 48, 4, 16  # per-device chunk 24, blocks 16 -> padding
    key = jax.random.PRNGKey(5)
    q, k, v = (
        jax.random.normal(kk, (b, t, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    want = _plain_causal_attention(q, k, v, d ** -0.5)
    got = shard_map(
        fpartial(
            ring_attention, axis_name="sp", use_flash=True,
            flash_interpret=True, flash_block=16,
        ),
        mesh=mesh,
        in_specs=(P("dp", "sp", "tp", None),) * 3,
        out_specs=P("dp", "sp", "tp", None),
        check_rep=False,
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_ring_flash_composition():
    """Full model: sp mesh + attn_impl='flash' routes attention through the
    ring schedule with the Pallas partial kernel inside."""
    from bee_code_interpreter_fs_tpu.parallel import (
        best_mesh_shape,
        make_mesh,
        shard_pytree,
    )
    from bee_code_interpreter_fs_tpu.models import param_specs

    cfg = LlamaConfig.tiny(dtype="float32", attn_impl="flash")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(18), (2, 32), 0, cfg.vocab_size)
    want = forward(params, tokens, LlamaConfig.tiny(dtype="float32"))

    mesh = make_mesh(best_mesh_shape(8, tp=2, sp=2))
    sharded = shard_pytree(mesh, params, param_specs(cfg))
    got = jax.jit(lambda p, t: forward(p, t, cfg, mesh=mesh))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-3, atol=5e-3)


def test_shape_mismatch_rejected():
    q = jnp.zeros((1, 8, 2, 4))
    k = jnp.zeros((1, 8, 1, 4))
    with pytest.raises(ValueError, match="shapes differ"):
        flash_attention(q, k, k)


def test_sliding_window_matches_plain():
    """flash_attention(window=w) == the masked-dense formulation with the
    same window, including non-divisible lengths (padding) and a window
    that doesn't align with tile boundaries."""
    from bee_code_interpreter_fs_tpu.models.llama import _plain_causal_attention
    from bee_code_interpreter_fs_tpu.ops.flash_attention import flash_attention

    b, t, h, d = 2, 100, 2, 16
    q, k, v = (
        jax.random.normal(s, (b, t, h, d), jnp.float32)
        for s in jax.random.split(jax.random.PRNGKey(11), 3)
    )
    for w in (1, 7, 33, 100, 0):
        want = _plain_causal_attention(q, k, v, d ** -0.5, window=w)
        got = flash_attention(
            q, k, v, block_q=16, block_k=32, window=w, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=f"window={w}",
        )


def test_attention_sinks_match_plain():
    """window + sinks in the kernel == the masked-dense formulation,
    including sink counts that don't align with tile boundaries and sinks
    inside/outside the window's reach."""
    from bee_code_interpreter_fs_tpu.models.llama import _plain_causal_attention
    from bee_code_interpreter_fs_tpu.ops.flash_attention import flash_attention

    b, t, h, d = 2, 100, 2, 16
    q, k, v = (
        jax.random.normal(s, (b, t, h, d), jnp.float32)
        for s in jax.random.split(jax.random.PRNGKey(12), 3)
    )
    for w, sinks in ((7, 4), (7, 33), (33, 1), (100, 4)):
        want = _plain_causal_attention(q, k, v, d ** -0.5, window=w, sinks=sinks)
        got = flash_attention(
            q, k, v, block_q=16, block_k=32, window=w, sinks=sinks,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=f"window={w} sinks={sinks}",
        )
