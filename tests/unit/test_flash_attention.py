"""Pallas flash-attention kernel vs the dense oracle (interpret mode on the
CPU test platform; the identical kernel lowers via Mosaic on TPU, where it
was measured faster than XLA's fused dense attention at t=2048 bf16 and,
unlike it, never materializes the [t, t] score matrix)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    _expand_gqa,
    _plain_causal_attention,
    forward,
    init_params,
)
from bee_code_interpreter_fs_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize(
    "b,t,h,d,bq,bk",
    [
        (2, 64, 4, 16, 16, 16),
        (1, 100, 2, 32, 32, 16),  # t not divisible by blocks: padding path
        (1, 16, 1, 8, 64, 64),  # blocks larger than the sequence
    ],
)
def test_matches_dense_oracle(b, t, h, d, bq, bk):
    key = jax.random.PRNGKey(t)
    q, k, v = (
        jax.random.normal(kk, (b, t, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    got = flash_attention(q, k, v, block_q=bq, block_k=bk, interpret=True)
    want = _plain_causal_attention(q, k, v, d ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gqa_via_expand():
    b, t, nh, nkv, d = 1, 32, 4, 2, 16
    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, nh, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, nkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, nkv, d), jnp.float32)
    ke, ve = _expand_gqa(k, v, nh)
    got = flash_attention(q, ke, ve, block_q=16, block_k=16, interpret=True)
    want = _plain_causal_attention(q, ke, ve, d ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_with_flash_impl_matches_plain():
    cfg_plain = LlamaConfig.tiny(dtype="float32")
    cfg_flash = LlamaConfig.tiny(dtype="float32", attn_impl="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_plain)
    tokens = jax.random.randint(
        jax.random.PRNGKey(14), (2, 24), 0, cfg_plain.vocab_size
    )
    want = forward(params, tokens, cfg_plain)
    got = forward(params, tokens, cfg_flash)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_shape_mismatch_rejected():
    q = jnp.zeros((1, 8, 2, 4))
    k = jnp.zeros((1, 8, 1, 4))
    with pytest.raises(ValueError, match="shapes differ"):
        flash_attention(q, k, k)
