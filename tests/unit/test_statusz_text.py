"""Dedicated tests for the /statusz (and /usage) text renderers — the
satellite's edge cases: an empty fleet, `_overflow` tenant rows, a wedged
host with its evidence fields, and the new usage section. The renderers
are module-level pure functions over statusz/usage bodies, so every edge
case is a dict in, a string out — no stack required (plus one end-to-end
leg through the real HTTP route).
"""

import pytest

pytest.importorskip("aiohttp")

from aiohttp.test_utils import TestClient, TestServer
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.http_server import (
    create_http_app,
    statusz_text,
    usage_text,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage
from bee_code_interpreter_fs_tpu.services.usage import OVERFLOW_TENANT


def empty_body(**overrides):
    body = {
        "status": "ok",
        "inflight": 0,
        "lanes": {},
        "sessions": [],
        "batching": {"enabled": False, "window_ms": 10.0, "max_jobs": 8},
        "compile_cache": {"enabled": False, "entries": 0, "bytes": 0},
        "device_health": {"enabled": False},
        "otlp": {"enabled": False},
        "usage": {"enabled": False},
    }
    body.update(overrides)
    return body


def test_empty_fleet_renders_every_section():
    text = statusz_text(empty_body())
    assert "status: ok   inflight: 0" in text
    assert "(no lanes)" in text
    assert "device health: probe disabled" in text
    assert "otlp: disabled" in text
    assert "usage: metering disabled" in text
    assert "sessions: 0" in text
    assert text.endswith("\n")


def test_minimal_body_never_raises():
    """A degraded statusz() (half-initialized executor, future fields
    removed) must render, not crash — the renderer uses .get throughout."""
    text = statusz_text({})
    assert "status: unknown" in text


def test_wedged_host_row_carries_evidence():
    body = empty_body(
        device_health={
            "enabled": True,
            "last_poll_age_s": 1.2,
            "states": {"healthy": 1, "busy": 0, "suspect": 0, "wedged": 1},
            "hosts": [
                {
                    "lane": 8,
                    "host": "http://10.0.0.7:8777",
                    "state": "wedged",
                    "reason": "attach_stalled",
                    "stall_s": 301.5,
                },
                {
                    "lane": 0,
                    "host": "http://10.0.0.8:8777",
                    "state": "healthy",
                },
            ],
        }
    )
    text = statusz_text(body)
    # The wedged host is flagged (!!) with its full evidence chain.
    assert "!!lane 8 http://10.0.0.7:8777 [wedged] attach_stalled" in text
    assert "stall=301.5s" in text
    assert "wedged=1" in text
    # The healthy host renders unflagged, without empty evidence fields.
    assert "  lane 0 http://10.0.0.8:8777 [healthy]" in text


def test_usage_section_with_overflow_tenant_rows():
    body = empty_body(
        usage={
            "enabled": True,
            "tenant_count": 3,
            "max_tenants": 2,
            "flushes": 12,
            "journal_lines": 40,
            "tenants": {
                "acme": {
                    "chip_seconds": 12.5,
                    "queue_wait_seconds": 0.75,
                    "requests": 10,
                    "batch_jobs": 8,
                    "upload_bytes": 2048,
                    "download_bytes": 0,
                    "compile_cache_recompiles": 2,
                    "violations": {"oom": 1, "cpu_time": 2},
                },
                OVERFLOW_TENANT: {
                    "chip_seconds": 3.0,
                    "queue_wait_seconds": 0.0,
                    "requests": 4,
                    "batch_jobs": 0,
                    "upload_bytes": 0,
                    "download_bytes": 0,
                    "compile_cache_recompiles": 0,
                    "violations": {},
                },
            },
        }
    )
    text = statusz_text(body)
    assert "usage: tenants=3/2 flushes=12" in text
    assert (
        "  acme: chip_s=12.5 queue_s=0.75 requests=10 batch_jobs=8 "
        "up_bytes=2048 down_bytes=0 recompiles=2 "
        "violations[cpu_time=2 oom=1]" in text
    )
    # The overflow row renders like any tenant — the aggregate past the
    # cap must stay visible, not vanish.
    assert f"  {OVERFLOW_TENANT}: chip_s=3.0" in text


def test_lane_rows_render_queue_pressure():
    body = empty_body(
        lanes={
            "0": {
                "pool_depth": 2,
                "pool_target": 4,
                "in_use": 1,
                "session_held": 1,
                "spawning": 0,
                "queued": 3,
                "queue_wait_ewma_s": 0.25,
                "batch_occupancy": 0.9,
                "breaker": "open",
            }
        }
    )
    text = statusz_text(body)
    assert (
        "lane 0: pool=2/4 in_use=1 sessions=1 spawning=0 queued=3 "
        "wait_ewma=0.25s batch_occ=0.9 breaker=open" in text
    )


def test_autoscaler_section_renders():
    enabled = empty_body(
        autoscaler={
            "enabled": True,
            "min_target": 1,
            "max_target": 16,
            "static_target": 5,
            "lanes": {
                "0": {
                    "target": 7,
                    "raw_demand": 6.4,
                    "arrival_rate_per_s": 3.2,
                    "scale_ups": 2,
                    "scale_downs": 1,
                    "reaped": 3,
                }
            },
        }
    )
    text = statusz_text(enabled)
    assert "autoscaler: bounds=[1..16] static=5" in text
    assert (
        "lane 0: target=7 demand=6.4 rate=3.2/s ups=2 downs=1 reaped=3"
        in text
    )
    disabled = empty_body(
        autoscaler={"enabled": False, "static_target": 5}
    )
    assert "autoscaler: disabled (static target 5)" in statusz_text(disabled)


def test_usage_text_disabled_and_empty():
    assert usage_text({"enabled": False}) == "usage metering: disabled\n"
    text = usage_text(
        {
            "enabled": True,
            "tenant_count": 0,
            "max_tenants": 256,
            "flushes": 0,
            "journal_lines": 0,
            "tenants": {},
        }
    )
    assert "(no usage recorded)" in text


async def test_statusz_and_usage_text_end_to_end(tmp_path):
    """The real routes: a live stack's ?format=text renders both surfaces
    (including the usage section fed by a real recorded request)."""
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
        batching_enabled=False,
    )
    executor = CodeExecutor(FakeBackend(), Storage(config.file_storage_path), config)

    async def fake_post(client, base, payload, timeout, sandbox):
        return {
            "stdout": "ok\n",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "warm": True,
            "device_op_seconds": 0.5,
            "duration_s": 0.5,
        }

    executor._post_execute = fake_post
    app = create_http_app(executor, CustomToolExecutor(executor), executor.storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await executor.execute("print(1)", tenant="acme")
        resp = await client.get("/statusz", params={"format": "text"})
        assert resp.status == 200
        text = await resp.text()
        assert "usage: tenants=" in text
        assert "acme: chip_s=0.5" in text
        resp = await client.get("/usage", params={"format": "text"})
        assert resp.status == 200
        text = await resp.text()
        assert "acme: chip_s=0.5" in text
        # Per-tenant route, both formats.
        resp = await client.get("/usage/acme")
        body = await resp.json()
        assert body["usage"]["chip_seconds"] == 0.5
        resp = await client.get("/usage/nosuch")
        assert resp.status == 404
    finally:
        await client.close()
        await executor.close()


# ---------------------------------------- perf + recovery text legs (ISSUE 14)


def test_perf_section_renders_series_and_regressed_marker():
    body = empty_body(
        perf={
            "enabled": True,
            "status": "regressed",
            "window_seconds": 30.0,
            "drift_quantile": 0.95,
            "bands": {"degraded_factor": 1.5, "regressed_factor": 3.0},
            "series": {
                "4/exec": {
                    "state": "regressed",
                    "p50_s": 0.12,
                    "p95_s": 0.61,
                    "p99_s": 0.8,
                    "baseline_s": 0.13,
                    "count": 412,
                    "windows": 9,
                    "regressions": 2,
                },
                "0/exec": {
                    "state": "normal",
                    "p50_s": 0.05,
                    "p95_s": 0.07,
                    "p99_s": 0.09,
                    "baseline_s": 0.06,
                    "count": 900,
                    "windows": 12,
                    "regressions": 0,
                },
            },
            "auto_profile": {"enabled": True, "captured": 3},
            "profile_store": {"entries": 3, "bytes": 120000},
        }
    )
    text = statusz_text(body)
    assert "perf observer: status=regressed window=30.0s drift_q=p95" in text
    # The regressed series is flagged (!!) with its evidence; the healthy
    # one renders unflagged.
    assert (
        "!!4/exec: [regressed] p50=0.12s p95=0.61s p99=0.8s baseline=0.13s "
        "n=412 windows=9 regressions=2" in text
    )
    assert "  0/exec: [normal] p50=0.05s" in text
    assert "profiles: 3 entries 120000 bytes" in text


def test_perf_section_disabled_line():
    assert "perf observer: disabled" in statusz_text(empty_body())
    assert "perf observer: disabled" in statusz_text(
        empty_body(perf={"enabled": False})
    )


def test_perf_text_renderer_standalone():
    from bee_code_interpreter_fs_tpu.services.http_server import perf_text

    assert perf_text({"enabled": False}) == "perf observer: disabled\n"
    text = perf_text(
        {
            "enabled": True,
            "status": "normal",
            "window_seconds": 30.0,
            "drift_quantile": 0.95,
            "bands": {"degraded_factor": 1.5, "regressed_factor": 3.0},
            "series": {},
            "tenants": {
                "acme": {
                    "state": "normal",
                    "p50_s": 0.1,
                    "p95_s": 0.2,
                    "p99_s": 0.3,
                    "baseline_s": 0.1,
                    "count": 4,
                    "windows": 1,
                }
            },
            "auto_profile": {"enabled": True, "captured": 0},
            "profile_store": {"entries": 0, "bytes": 0, "evictions": 0},
        }
    )
    assert "(no latency series yet)" in text
    assert "tenant acme: [normal]" in text
    assert "profiles: 0 entries 0 bytes" in text


def test_recovery_block_renders_in_text():
    """The PR 13 recovery block's ?format=text legs (previously untested
    in text form): the standing-quarantine line with streak evidence, and
    the fencing-disabled line."""
    body = empty_body(
        recovery={
            "fencing_enabled": True,
            "fences_total": 2,
            "readmissions_total": 1,
            "readmit_streak": 3,
            "fence_budget": {"max_per_window": 4, "window_seconds": 600.0},
            "recovering": {
                "lane-4": {
                    "streak": 1,
                    "need": 3,
                    "reason": "wedged",
                    "for_s": 42.5,
                    "relapses": 1,
                }
            },
        }
    )
    text = statusz_text(body)
    assert (
        "recovery: fences=2 readmissions=1 budget=4/600.0s streak=3" in text
    )
    assert (
        "  recovering lane-4: 1/3 clean (wedged, 42.5s, 1 relapse(s))"
        in text
    )
    disabled = empty_body(recovery={"fencing_enabled": False})
    assert "recovery: fencing disabled" in statusz_text(disabled)


async def test_perf_and_profiles_routes_end_to_end(tmp_path):
    """GET /perf (json + text), GET /profiles with the X-Total-* paging
    header discipline (the PR 8 /traces rule: a paged listing must never
    LOOK complete), GET /profiles/{id}, and the kill-switch 404s."""
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
        batching_enabled=False,
    )
    executor = CodeExecutor(
        FakeBackend(), Storage(config.file_storage_path), config
    )
    app = create_http_app(
        executor, CustomToolExecutor(executor), executor.storage
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        ids = []
        for i in range(3):
            ids.append(
                executor.perf.store.add(
                    b"zip-%d" % i,
                    {"lane": 0, "reason": "regression:exec",
                     "trace_id": f"{i:032x}"},
                )
            )
        resp = await client.get("/perf")
        assert resp.status == 200
        body = await resp.json()
        assert body["enabled"] is True
        resp = await client.get("/perf", params={"format": "text"})
        assert "perf observer: status=" in await resp.text()
        # Paged listing with the truncation headers.
        resp = await client.get(
            "/profiles", params={"limit": "1", "offset": "1"}
        )
        assert resp.status == 200
        assert resp.headers["X-Total-Profiles"] == "3"
        assert resp.headers["X-Limit"] == "1"
        assert resp.headers["X-Offset"] == "1"
        body = await resp.json()
        assert body["total"] == 3 and len(body["profiles"]) == 1
        # One artifact, bytes + cross-link headers.
        target = body["profiles"][0]["id"]
        resp = await client.get(f"/profiles/{target}")
        assert resp.status == 200
        assert resp.content_type == "application/zip"
        assert resp.headers["X-Trace-Id"] == body["profiles"][0]["trace_id"]
        assert (await resp.read()).startswith(b"zip-")
        resp = await client.get("/profiles/" + "0" * 32)
        assert resp.status == 404
        resp = await client.get("/profiles/..evil")
        assert resp.status == 400
        # The xprof summary verdict route: a real trace-event member gets
        # parsed; the zip-less artifacts above degrade to "unparseable".
        import gzip
        import io
        import json
        import zipfile

        payload = json.dumps(
            {
                "traceEvents": [
                    {"ph": "M", "name": "process_name", "pid": 1,
                     "args": {"name": "/device:TPU:0"}},
                    {"ph": "X", "pid": 1, "name": "fusion.1",
                     "ts": 0, "dur": 500},
                ]
            }
        ).encode()
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as archive:
            archive.writestr(
                "plugins/profile/r/h.trace.json.gz", gzip.compress(payload)
            )
        traced = executor.perf.store.add(
            buf.getvalue(), {"lane": 0, "reason": "p99_outlier:exec",
                             "trace_id": "f" * 32}
        )
        resp = await client.get(f"/profiles/{traced}/summary")
        assert resp.status == 200
        assert resp.headers["X-Trace-Id"] == "f" * 32
        body = await resp.json()
        assert body["id"] == traced
        assert body["top_ops"][0]["name"] == "fusion.1"
        assert body["device_op_wall_share"] == 1.0
        assert body["meta"]["reason"] == "p99_outlier:exec"
        resp = await client.get(f"/profiles/{target}/summary")
        assert resp.status == 200
        assert (await resp.json())["verdict"] == "unparseable"
        assert (await client.get("/profiles/" + "0" * 32 + "/summary")).status == 404
        assert (await client.get("/profiles/..evil/summary")).status == 400
    finally:
        await client.close()
        await executor.close()


async def test_perf_routes_404_with_kill_switch(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        perf_observer_enabled=False,
        batching_enabled=False,
    )
    executor = CodeExecutor(
        FakeBackend(), Storage(config.file_storage_path), config
    )
    app = create_http_app(
        executor, CustomToolExecutor(executor), executor.storage
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        assert (await client.get("/perf")).status == 404
        assert (await client.get("/profiles")).status == 404
        assert (await client.get("/profiles/" + "a" * 32)).status == 404
        assert (
            await client.get("/profiles/" + "a" * 32 + "/summary")
        ).status == 404
        # And statusz renders the disabled posture, text included.
        resp = await client.get("/statusz", params={"format": "text"})
        assert "perf observer: disabled" in await resp.text()
    finally:
        await client.close()
        await executor.close()
