"""Shared in-memory sandbox-backend fake for orchestrator-level unit tests
(pool, sessions, streaming). One implementation with counters and knobs so
the Sandbox/SandboxBackend contract has a single test double to keep in sync.
"""

from bee_code_interpreter_fs_tpu.services.backends.base import Sandbox


class FakeBackend:
    """In-memory backend: spawn/reset/delete counters, no processes.

    `capacity` mimics a TPU host's slot limit (None = unconstrained CPU);
    `resettable=False` makes every recycle attempt fail (single-use pods,
    the reference's model)."""

    # Each fake sandbox is its own little world (there is no shared dir to
    # cross-contaminate), matching the k8s emptyDir / local per-sandbox
    # reality most orchestrator tests model. Tests exercising the shared or
    # externally-writable cache-dir postures override per instance.
    compile_cache_dir_scope = "private"

    # Fake sandboxes are not real HTTP hosts: the executor skips the
    # POST /lease token push (minting and the control-plane revocation
    # check still run) — real-socket connect failures against fake URLs
    # would make the seeded chaos suites' interleaving nondeterministic.
    supports_lease_push = False

    def __init__(self, capacity=None, resettable=True, distinct_urls=False):
        self.capacity = capacity
        self.resettable = resettable
        # distinct_urls gives each sandbox its own host URL (like any real
        # backend) — the device-health probe keys its state table by host,
        # so probe tests need hosts that are actually distinguishable.
        self.distinct_urls = distinct_urls
        self.spawns = 0
        self.resets = 0
        self.deletes = 0
        self.live = set()

    async def spawn(self, chip_count: int = 0) -> Sandbox:
        self.spawns += 1
        url = (
            f"http://fake-{self.spawns}" if self.distinct_urls else "http://fake"
        )
        sandbox = Sandbox(
            id=f"sb-{self.spawns}", url=url, chip_count=chip_count
        )
        self.live.add(sandbox.id)
        return sandbox

    def pool_capacity(self, chip_count: int):
        return self.capacity

    async def reset(self, sandbox: Sandbox):
        self.resets += 1
        if not self.resettable or sandbox.id not in self.live:
            return None
        sandbox.meta["generation"] = sandbox.meta.get("generation", 0) + 1
        return sandbox

    async def delete(self, sandbox: Sandbox) -> None:
        self.deletes += 1
        self.live.discard(sandbox.id)

    async def close(self) -> None:
        self.live.clear()
