"""Seeded slow_exec chaos for the performance anomaly plane (faults.py ->
perf_observer.py), CHAOS_SEED-parameterized like the other chaos suites:
CI pins the {7, 23, 1337} matrix; a red leg replays exactly with
``CHAOS_SEED=<n> pytest tests/unit/test_perf_observer_chaos.py``.

The injected fault is a LATENCY REGRESSION, not an error: the affected
dispatches succeed, only slower. The drift detector must flip the slowed
lane's exec series to regressed within one window while the clean lane's
baseline holds — and the whole pipeline (transport draw order, window
verdicts, profile arming) must replay identically under one seed.
"""

import asyncio
import os
import tempfile

import httpx
import pytest

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    SLOW_EXEC,
    FaultInjectingBackend,
    FaultSpec,
    SlowExecTransport,
)
from bee_code_interpreter_fs_tpu.services.perf_observer import (
    NORMAL,
    REGRESSED,
    PerfObserver,
)

from fakes import FakeBackend
from test_perf_observer import FakeClock

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "7"))


# ----------------------------------------------------------- spec grammar


def test_slow_exec_spec_parses():
    spec = FaultSpec.parse(
        f"slow_exec:0.5,slow_exec_seconds:0.4,slow_exec_lane:4,"
        f"seed:{CHAOS_SEED}"
    )
    assert spec.slow_exec == 0.5
    assert spec.slow_exec_seconds == 0.4
    assert spec.slow_exec_lane == 4
    assert spec.active


def test_slow_exec_spec_validation_fails_loudly():
    with pytest.raises(ValueError):
        FaultSpec.parse("slow_exec:1.5")
    with pytest.raises(ValueError):
        FaultSpec.parse("slow_exec:0.5,slow_exec_seconds:-1")
    with pytest.raises(ValueError):
        FaultSpec.parse("slow_exec_typo:0.5")


def test_slow_exec_seconds_alone_is_not_active():
    # The delay magnitude without a rate injects nothing — it must not
    # flip the "fault injection ACTIVE" posture.
    assert not FaultSpec.parse("slow_exec_seconds:0.5").active


def test_backend_wraps_transport_and_records_lanes():
    backend = FaultInjectingBackend(
        FakeBackend(),
        FaultSpec.parse(f"slow_exec:1.0,seed:{CHAOS_SEED}"),
    )
    transport = backend.http_transport()
    assert isinstance(transport, SlowExecTransport)

    async def spawn():
        sandbox = await backend.spawn(4)
        return sandbox

    sandbox = asyncio.run(spawn())
    parsed = httpx.URL(sandbox.url)
    assert backend._host_lanes[f"{parsed.host}:{parsed.port}"] == 4


# ------------------------------------------------------ transport behavior


def _transport(rate, lane, host_lanes, fired, delay=0.0):
    import random

    async def inner_handler(request):
        return httpx.Response(200, json={"ok": True})

    return SlowExecTransport(
        rate,
        delay,
        lane,
        random.Random(f"{CHAOS_SEED}:{SLOW_EXEC}"),
        host_lanes,
        on_fault=lambda kind: fired.append(kind),
        inner=httpx.MockTransport(inner_handler),
    )


def test_transport_delays_only_the_restricted_lane():
    async def run():
        host_lanes = {"slow-host:8001": 4, "fast-host:8001": 0}
        fired: list[str] = []
        transport = _transport(1.0, 4, host_lanes, fired)
        client = httpx.AsyncClient(transport=transport)
        for _ in range(5):
            await client.post("http://slow-host:8001/execute")
            await client.post("http://fast-host:8001/execute")
        await client.aclose()
        # rate 1.0: every slow-host dispatch fired; no fast-host one did.
        assert len(fired) == 5
        return fired

    asyncio.run(run())


def test_transport_draw_sequence_is_seed_stable():
    async def run(order):
        host_lanes = {"a:8001": 0, "b:8001": 0}
        fired: list[str] = []
        transport = _transport(0.5, -1, host_lanes, fired)
        client = httpx.AsyncClient(transport=transport)
        outcomes = []
        for host in order:
            before = len(fired)
            await client.post(f"http://{host}:8001/execute")
            outcomes.append(len(fired) > before)
        await client.aclose()
        return outcomes

    # The SAME dispatch sequence replays the SAME fire pattern (its own
    # seeded stream), and non-execute routes never consume a draw.
    first = asyncio.run(run(["a", "b", "a", "b", "a", "b", "a", "b"]))
    second = asyncio.run(run(["a", "b", "a", "b", "a", "b", "a", "b"]))
    assert first == second
    assert any(first), "rate 0.5 over 8 draws should fire at least once"


def test_non_execute_routes_never_draw():
    async def run():
        fired: list[str] = []
        transport = _transport(1.0, -1, {}, fired)
        client = httpx.AsyncClient(transport=transport)
        await client.get("http://x:8001/healthz")
        await client.get("http://x:8001/device-stats")
        await client.post("http://x:8001/reset")
        assert fired == []
        await client.post("http://x:8001/execute")
        assert fired == [SLOW_EXEC]
        await client.aclose()

    asyncio.run(run())


# ---------------------------------------------- drift verdict under chaos


def test_slowed_lane_regresses_while_clean_lane_holds():
    """The acceptance shape, fake-clocked: one lane's exec latencies pick
    up the injected delay, the other's stay at baseline. The detector
    must flip ONLY the slowed lane — under every pinned seed."""
    import random

    clock = FakeClock()
    tmp = tempfile.mkdtemp(prefix="perf-chaos-")
    observer = PerfObserver(
        Config(
            file_storage_path=tmp,
            perf_window_seconds=10.0,
            perf_min_window_samples=5,
            perf_min_band_seconds=0.0,
        ),
        clock=clock,
    )
    rng = random.Random(CHAOS_SEED)
    base = lambda: 0.05 + rng.random() * 0.01  # noqa: E731
    # Two baseline windows for both lanes.
    for _ in range(2):
        for _ in range(10):
            observer.record(0, "exec", base())
            observer.record(4, "exec", base())
        clock.advance(10.01)
    observer.record(0, "exec", base())
    observer.record(4, "exec", base())
    assert observer.lane_phase_states()["0/exec"] == NORMAL
    assert observer.lane_phase_states()["4/exec"] == NORMAL
    # The fault lands on lane 4: +0.4s on every dispatch (slow_exec shape).
    for _ in range(10):
        observer.record(0, "exec", base())
        observer.record(4, "exec", base() + 0.4)
    clock.advance(10.01)
    observer.record(0, "exec", base())
    observer.record(4, "exec", base() + 0.4)
    states = observer.lane_phase_states()
    assert states["4/exec"] == REGRESSED, states
    assert states["0/exec"] == NORMAL, states
    # The regressed lane armed an auto-profile; the clean one did not.
    assert observer.take_profile_arm(4, None) is not None
    assert observer.take_profile_arm(0, None) is None


def test_partial_rate_regression_still_flips_within_one_window():
    """At slow_exec:0.5 only half the window's dispatches are slow — the
    p95 drift quantile still catches it (tail quantiles are exactly why
    the detector doesn't read medians)."""
    import random

    clock = FakeClock()
    tmp = tempfile.mkdtemp(prefix="perf-chaos-")
    observer = PerfObserver(
        Config(
            file_storage_path=tmp,
            perf_window_seconds=10.0,
            perf_min_window_samples=5,
            perf_min_band_seconds=0.0,
        ),
        clock=clock,
    )
    rng = random.Random(f"{CHAOS_SEED}:partial")
    for _ in range(2):
        for _ in range(12):
            observer.record(0, "exec", 0.05 + rng.random() * 0.01)
        clock.advance(10.01)
    observer.record(0, "exec", 0.05)
    assert observer.lane_phase_states()["0/exec"] == NORMAL
    for _ in range(12):
        slow = rng.random() < 0.5
        observer.record(0, "exec", 0.05 + (0.4 if slow else 0.0))
    # Guarantee the tail is present whatever the seed drew (rate noise
    # must not make the LEG flaky; the detector still had to see through
    # the mixed window).
    observer.record(0, "exec", 0.45)
    observer.record(0, "exec", 0.45)
    clock.advance(10.01)
    observer.record(0, "exec", 0.05)
    assert observer.lane_phase_states()["0/exec"] == REGRESSED
