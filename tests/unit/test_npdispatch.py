"""Tests for the numpy→jax.numpy dispatch shim (on the CPU JAX backend)."""

import sys

import pytest

from bee_code_interpreter_fs_tpu.ops import npdispatch
from bee_code_interpreter_fs_tpu.ops.npdispatch.shim import TpuArray

THRESHOLD = 1000


@pytest.fixture
def np_shim():
    npdispatch.install(threshold=THRESHOLD)
    import numpy as np

    yield np
    npdispatch.uninstall()


def test_install_replaces_module(np_shim):
    import numpy

    assert numpy is np_shim
    assert sys.modules["numpy.random"] is np_shim.random
    npdispatch.uninstall()
    import numpy as real

    assert hasattr(real, "ndarray") and not hasattr(real, "TpuArray")
    npdispatch.install(threshold=THRESHOLD)  # fixture will uninstall again


def test_small_arrays_stay_on_host(np_shim):
    import numpy.random  # the shimmed submodule

    small = np_shim.zeros(10)
    assert type(small).__name__ == "ndarray"
    r = numpy.random.rand(5)
    assert type(r).__name__ == "ndarray"
    assert isinstance(np_shim.sum(small), np_shim.floating)


def test_big_arrays_go_to_device(np_shim):
    big = np_shim.zeros(THRESHOLD * 2)
    assert isinstance(big, TpuArray)
    r = np_shim.random.rand(THRESHOLD * 2)
    assert isinstance(r, TpuArray)
    assert r.shape == (THRESHOLD * 2,)


def test_benchmark_numpy_shape(np_shim):
    # the reference's headline workload (examples/benchmark-numpy.py):
    # sum of squares over random doubles
    a = np_shim.random.rand(THRESHOLD * 10)
    result = (a * a).sum()
    assert isinstance(result, TpuArray)
    value = float(result)
    assert 0.25 * THRESHOLD * 10 < value < 0.42 * THRESHOLD * 10


def test_matmul_and_einsum(np_shim):
    a = np_shim.ones((64, 64))
    b = np_shim.arange(64 * 128, dtype="float32").reshape(64, -1)
    big = np_shim.asarray(b)
    product = np_shim.matmul(np_shim.asarray(a), big)
    assert isinstance(product, TpuArray)
    reference = np_shim.einsum("ij,jk->ik", np_shim.asarray(a), big)
    assert bool(np_shim.allclose(product, reference))


def test_mutation_setitem(np_shim):
    a = np_shim.zeros(THRESHOLD * 2)
    a[3] = 7.0
    a[10:20] = 1.0
    assert float(a[3]) == 7.0
    assert float(a.sum()) == 7.0 + 10.0
    a += 1
    assert float(a[0]) == 1.0
    assert isinstance(a, TpuArray)


def test_reductions_and_methods(np_shim):
    a = np_shim.arange(THRESHOLD * 2, dtype="float32")
    assert float(a.mean()) == pytest.approx((THRESHOLD * 2 - 1) / 2)
    assert int(a.argmax()) == THRESHOLD * 2 - 1
    assert a.reshape(2, -1).shape == (2, THRESHOLD)
    assert isinstance(a.astype("int32"), TpuArray)
    assert a.tolist()[:3] == [0.0, 1.0, 2.0]


def test_mixed_host_device_ops(np_shim):
    big = np_shim.ones(THRESHOLD * 2)
    small_host = np_shim.zeros(1)  # real ndarray
    out = big + 2.0
    assert isinstance(out, TpuArray)
    out2 = np_shim.maximum(big, 0.5)
    assert isinstance(out2, TpuArray)
    host = np_shim.asarray(small_host)
    assert type(host).__name__ == "ndarray"


def test_interop_with_real_numpy(np_shim):
    big = np_shim.ones(THRESHOLD * 2)
    host = big.__array__()  # explicit host materialization stays ndarray
    assert type(host).__name__ == "ndarray"
    assert host.sum() == THRESHOLD * 2
    # numpy defers to TpuArray via __array_priority__
    import numpy as np

    mixed = np.float64(2.0) * big
    assert isinstance(mixed, TpuArray)
    assert float(mixed[0]) == 2.0


def test_linalg_fft(np_shim):
    a = np_shim.random.randn(THRESHOLD * 2)
    norm = np_shim.linalg.norm(a)
    assert isinstance(norm, TpuArray)
    assert float(norm) > 0
    spectrum = np_shim.fft.fft(a)
    assert isinstance(spectrum, TpuArray)
    assert spectrum.shape == a.shape


def test_random_seeded_reproducible(np_shim):
    np_shim.random.seed(42)
    a = np_shim.random.rand(THRESHOLD * 2)
    np_shim.random.seed(42)
    b = np_shim.random.rand(THRESHOLD * 2)
    assert bool(np_shim.allclose(a, b))
    # distinct draws differ
    c = np_shim.random.rand(THRESHOLD * 2)
    assert not bool(np_shim.allclose(b, c))


def test_structural_passthrough(np_shim):
    assert np_shim.pi == pytest.approx(3.14159265)
    assert np_shim.dtype("float32").itemsize == 4
    assert np_shim.ndarray is sys.modules["numpy"].__getattr__("ndarray")
    # object arrays fall back to host numpy without error
    obj = np_shim.array(["a", "b"])
    assert type(obj).__name__ == "ndarray"


def test_sum_matches_numpy(np_shim):
    import numpy  # the shim

    data = list(range(THRESHOLD * 3))
    device = np_shim.asarray(numpy.array(data, dtype="float64"))
    host_total = sum(data)
    assert float(device.sum()) == pytest.approx(host_total, rel=1e-6)


def test_float64_requests_are_explicitly_float32(np_shim):
    """Precision policy (VERDICT r1 #4): 64-bit dtype requests canonicalize
    to 32-bit EXPLICITLY under the default x64-off policy — reported dtype ==
    stored dtype, and no per-call jax truncation warnings leak out."""
    import warnings

    import numpy as real_np_check  # the shim, actually

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        a = np_shim.ones(THRESHOLD * 2, dtype=np_shim.float64)
        assert a.dtype == real_np_check.dtype("float32")
        b = a.astype("float64")
        assert b.dtype == real_np_check.dtype("float32")
        assert b._arr.dtype == b.dtype  # reported == stored, no lying
        s = np_shim.sum(a, dtype=np_shim.float64)
        assert s.dtype == real_np_check.dtype("float32")
    truncations = [
        w for w in caught if "truncated to dtype float32" in str(w.message)
    ]
    assert not truncations, "policy must canonicalize, not rely on jax warnings"


def test_integer_policy_arange_default_stays_host(np_shim):
    """numpy's default arange dtype is int64 — the device would wrap it to
    int32, so integer arange stays on host and sums exactly (VERDICT r2 #4,
    the np.arange(3e9).sum() class of case at test-friendly size)."""
    n = THRESHOLD * 50
    a = np_shim.arange(n)
    assert type(a).__name__ == "ndarray"
    assert a.dtype.name == "int64"
    assert int(a.sum()) == n * (n - 1) // 2
    # and a genuinely wide-valued sum is exact (would wrap in int32)
    big = np_shim.arange(2_000_000_000, 2_000_000_000 + n)
    assert type(big).__name__ == "ndarray"
    assert int(big.sum()) == sum(range(2_000_000_000, 2_000_000_000 + n))


def test_integer_policy_wide_dtype_requests_stay_host(np_shim):
    a = np_shim.zeros(THRESHOLD * 2, dtype=np_shim.int64)
    assert type(a).__name__ == "ndarray" and a.dtype.name == "int64"
    b = np_shim.full(THRESHOLD * 2, 7, dtype="uint64")
    assert type(b).__name__ == "ndarray" and b.dtype.name == "uint64"
    # conversions of 64-bit-int ndarrays stay host too
    import bee_code_interpreter_fs_tpu.ops.npdispatch.shim as shim_mod

    raw = shim_mod.real_np.arange(THRESHOLD * 3, dtype=shim_mod.real_np.int64)
    converted = np_shim.asarray(raw)
    assert type(converted).__name__ == "ndarray"


def test_integer_policy_device_reductions_promote_on_host(np_shim):
    """int32 arrays DO dispatch to device, but sum/prod promote their
    accumulator in numpy (int32 -> int64) — the shim computes those on host,
    exactly, instead of wrapping in int32 on device."""
    import bee_code_interpreter_fs_tpu.ops.npdispatch.shim as shim_mod

    n = THRESHOLD * 2
    a = np_shim.full(n, 2**30, dtype=np_shim.int32)
    assert isinstance(a, TpuArray)  # int32 itself is device-legal
    total = a.sum()
    assert not isinstance(total, TpuArray)
    expected = shim_mod.real_np.full(n, 2**30, dtype="int32").sum()
    assert int(total) == int(expected)  # exact, far beyond int32 range
    assert int(total) == n * 2**30
    # module-level np.sum routes identically
    assert int(np_shim.sum(a)) == n * 2**30
    # explicit accumulator dtype follows numpy (int32 wraps in BOTH)
    wrapped_host = shim_mod.real_np.full(n, 2**30, dtype="int32").sum(
        dtype=shim_mod.real_np.int32
    )
    wrapped_shim = a.sum(dtype=np_shim.int32)
    assert int(wrapped_shim) == int(wrapped_host)


def test_integer_policy_astype_wide_goes_host(np_shim):
    a = np_shim.zeros(THRESHOLD * 2, dtype=np_shim.float32)
    assert isinstance(a, TpuArray)
    widened = a.astype(np_shim.int64)
    assert type(widened).__name__ == "ndarray"
    assert widened.dtype.name == "int64"


def test_integer_policy_binop_with_wide_ndarray_goes_host(np_shim):
    """`a + wide_int64_ndarray` must match np.add(a, ...)'s host routing —
    the device would cast the int64 operand to int32 and wrap."""
    import bee_code_interpreter_fs_tpu.ops.npdispatch.shim as shim_mod

    n = THRESHOLD * 2
    a = np_shim.full(n, 2**30, dtype=np_shim.int32)
    assert isinstance(a, TpuArray)
    wide = shim_mod.real_np.full(n, 2**31 + 5, dtype=shim_mod.real_np.int64)
    out = a + wide
    assert type(out).__name__ == "ndarray"
    assert int(out[0]) == 2**30 + 2**31 + 5  # exact, not wrapped
    out_r = wide + a  # reflected path
    assert int(out_r[0]) == 2**30 + 2**31 + 5


def test_integer_policy_method_explicit_wide_dtype_goes_host(np_shim):
    """a.sum(dtype=np.int64) explicitly requests a 64-bit accumulator; jax
    would silently truncate it to int32 — must compute on host."""
    n = THRESHOLD * 2
    a = np_shim.full(n, 2**30, dtype=np_shim.int32)
    total = a.sum(dtype=np_shim.int64)
    assert int(total) == n * 2**30


def test_integer_policy_nansum_exact(np_shim):
    n = THRESHOLD * 2
    a = np_shim.full(n, 2**30, dtype=np_shim.int32)
    assert int(np_shim.nansum(a)) == n * 2**30


def test_integer_policy_elementwise_int32_stays_device(np_shim):
    """Fixed-width elementwise int arithmetic wraps identically in numpy
    and on device — no reason to leave the accelerator."""
    a = np_shim.zeros(THRESHOLD * 2, dtype=np_shim.int32)
    b = (a + 7) * 3
    assert isinstance(b, TpuArray)
    assert int(b[0]) == 21


def test_matmul_precision_scoped_not_global(np_shim):
    """The shim's float32-parity matmul precision must apply to SHIM ops
    only: (a) a float32 matmul through the shim keeps values a bf16 MXU
    pass would round (257 -> 256), and (b) the process-global
    jax_default_matmul_precision stays untouched — a global "highest" broke
    Pallas kernels sharing the sandbox (bf16 dots lower with an fp32
    contract precision Mosaic rejects).

    Assertion (a) only bites on a real TPU MXU — CPU/GPU matmuls are f32
    regardless of jax_default_matmul_precision, so on CI it is (b) plus the
    install-time precision_scope validation that guard this behavior."""
    import jax

    assert jax.config.jax_default_matmul_precision is None  # (b)

    n = 64
    a = np_shim.full((THRESHOLD, n), 1.0, dtype=np_shim.float32)
    a[0, :] = 257.0  # representable in f32, rounds to 256 in bf16
    b = np_shim.eye(n, dtype=np_shim.float32)
    assert isinstance(a, TpuArray)
    out = a @ b
    assert float(out[0, 0]) == 257.0  # (a) exact under f32 contraction


def test_headline_sum_of_squares_divergence_bounded(np_shim):
    """The BASELINE.json headline workload shape (sum of squares over random
    doubles) computed by the shim in float32 must stay within rtol=1e-5 of
    real numpy's float64 pairwise summation. This is the tested bound behind
    the precision policy: XLA reduces in tiles, so f32 accumulation error
    grows ~eps*log(n), not eps*n — the bound is n-insensitive, so the test
    uses 1e7 elements to stay CI-sized (the 1e8 headline run goes through
    bench.py on the real machine)."""
    import numpy as real_np

    rng = real_np.random.default_rng(42)
    n = 10**7
    data = rng.random(n)  # float64 host data, as benchmark-numpy.py makes it
    reference = float(real_np.sum(data * data))
    device = np_shim.array(data)  # canonicalizes to f32 on device, by policy
    assert device.dtype == real_np.dtype("float32")
    got = float((device * device).sum())
    assert got == pytest.approx(reference, rel=1e-5)


def test_iteration_and_len(np_shim):
    a = np_shim.arange(THRESHOLD * 2)
    assert len(a) == THRESHOLD * 2
    first_three = []
    for value in a:
        first_three.append(float(value))
        if len(first_three) == 3:
            break
    assert first_three == [0.0, 1.0, 2.0]
