"""Seeded wedge-recovery chaos (faults.py attach_hang* -> device_health ->
fence_host), CHAOS_SEED-parameterized like the other chaos suites: CI pins
the {7, 23, 1337} matrix and a red leg replays exactly with
``CHAOS_SEED=<n> pytest tests/unit/test_recovery_chaos.py``.

Legs:
- fence under load: one lane's host wedges under concurrent traffic on
  another lane — the wedge is fenced/disposed/replaced with zero failed
  requests on the healthy lane, and the fenced lease refuses stale claims;
- actuation cap under a probe storm: every host of a lane reports wedged
  (the false-positive-storm shape) — disposals stop at the per-window
  budget instead of mass-disposing the lane;
- the full lifecycle on the recovering fault (attach_hang_recover):
  wedge -> drain -> dispose -> respawn -> clean-streak -> re-admit, ending
  with the lane serving again;
- constrained-lane re-admission gating: while the only pooled host is
  recovering, an acquire parks (instead of fighting it for the chip) and
  completes the moment the streak re-admits.
"""

import asyncio
import os
import random
import tempfile

import httpx
import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    ATTACH_HANG,
    AttachHangTransport,
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CodeExecutor,
    StaleLeaseError,
)
from bee_code_interpreter_fs_tpu.services.device_health import (
    HEALTHY,
    RECOVERING,
    WEDGED,
    DeviceHealthProbe,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "7"))


def _healthy_stats() -> dict:
    return {
        "status": "ok",
        "warm": True,
        "warm_state": "ready",
        "backend": "cpu",
        "device_kind": "cpu",
        "device_count": 1,
        "attach_pending_s": 0.0,
        "attach_seconds": 1.0,
        "op_in_flight": False,
        "op_age_s": 0.0,
        "op_timeout_s": 0.0,
        "last_device_op_age_s": 1.0,
        "runner_heartbeat_age_s": 0.1,
        "runner_alive": True,
        "rss_bytes": 1,
        "runner_rss_bytes": 1,
    }


class _Stack:
    """Executor + probe over the fault-injecting backend, with the seeded
    attach-hang transport on the sandbox HTTP wire (its inner transport is
    an always-healthy mock, so only the injected fault misbehaves) and a
    test-driven clock for the synthesized hang ages."""

    def __init__(self, spec_str: str, **config_overrides):
        self.tmp = tempfile.mkdtemp(prefix="recovery-chaos-")
        defaults = dict(
            file_storage_path=self.tmp,
            executor_pod_queue_target_length=1,
            compile_cache_enabled=False,
            executor_fault_spec=spec_str,
            device_probe_attach_budget=10.0,
            device_probe_op_grace=5.0,
            device_probe_wedge_after=10.0,
            device_probe_readmit_streak=2,
        )
        defaults.update(config_overrides)
        self.config = Config(**defaults)
        self.spec = FaultSpec.parse(spec_str)
        self.faults: list[str] = []
        self.backend = FaultInjectingBackend(
            FakeBackend(distinct_urls=True),
            self.spec,
            on_fault=self.faults.append,
        )
        self.executor = CodeExecutor(
            self.backend, Storage(self.tmp), self.config
        )
        self.now = [0.0]

        def handler(request: httpx.Request) -> httpx.Response:
            if request.url.path == "/lease":
                return httpx.Response(200, json={"ok": True})
            return httpx.Response(200, json=_healthy_stats())

        self.transport = AttachHangTransport(
            self.spec.attach_hang,
            self.spec.attach_hang_lane,
            random.Random(f"{self.spec.seed}:{ATTACH_HANG}"),
            self.backend._host_lanes,
            self.faults.append,
            inner=httpx.MockTransport(handler),
            clock=lambda: self.now[0],
            max_hosts=self.spec.attach_hang_max,
            recover_draws=self.spec.attach_hang_recover,
        )
        self._client = httpx.AsyncClient(transport=self.transport)
        self.executor._http_client = lambda: self._client

        async def post(client, base, payload, timeout, sandbox):
            return {
                "stdout": "ok\n",
                "stderr": "",
                "exit_code": 0,
                "files": [],
                "warm": True,
                "duration_s": 0.01,
            }

        self.executor._post_execute = post
        self.probe = DeviceHealthProbe(self.executor)
        self.executor.device_health = self.probe

    async def settle(self):
        for _ in range(80):
            pending = list(self.executor._dispose_tasks) + list(
                self.executor._fill_tasks
            )
            if not pending:
                return
            await asyncio.gather(*pending, return_exceptions=True)

    def fences(self) -> dict:
        return {
            (labels["lane"], labels["outcome"]): value
            for labels, value in self.executor.metrics.device_fences.samples()
        }

    async def close(self):
        await self._client.aclose()
        await self.executor.close()


async def test_fence_under_load_spares_the_healthy_lane():
    """One lane-2 host wedges while lane-0 serves concurrent traffic: the
    wedge is fenced and replaced, every lane-0 request succeeds, and the
    fenced lease refuses stale claims."""
    stack = _Stack(
        f"attach_hang:1.0,attach_hang_lane:2,attach_hang_max:1,"
        f"seed:{CHAOS_SEED}"
    )
    try:
        await stack.executor.execute("print(1)")  # lane 0 up
        await stack.executor.execute("print(1)", chip_count=2)  # lane 2 up
        await stack.settle()
        doomed = next(
            s for lane, s in stack.executor.live_hosts() if lane == 2
        )
        old_lease = doomed.meta["lease"]
        # Concurrent lane-0 load racing the wedge escalation + fence.
        load = asyncio.gather(
            *(stack.executor.execute("print(2)") for _ in range(6))
        )
        await stack.probe.probe_once()  # hang starts (busy)
        stack.now[0] += 100.0  # stall far past budget + wedge threshold
        states = await stack.probe.probe_once()
        assert states[doomed.url] == WEDGED
        results = await load
        assert all(r.exit_code == 0 for r in results)
        await stack.settle()
        # Fenced, disposed, replaced; the replacement holds a newer
        # generation and starts in the recovering quarantine.
        assert stack.executor.live_sandbox(doomed.id) is None
        assert stack.fences()[("2", "fenced")] == 1
        assert old_lease.revoked
        replacement = stack.executor._pool(2)[0]
        assert replacement.meta["lease"].generation > old_lease.generation
        assert replacement.meta["device_health"] == "recovering"
        # The stale claim dies typed, before any wire hop.
        with pytest.raises(StaleLeaseError):
            stack.executor._check_lease(doomed)
        # attach_hang_max=1: the replacement came up clean — two clean
        # cycles re-admit the scope and the lane serves again.
        await stack.probe.probe_once()
        states = await stack.probe.probe_once()
        assert states[replacement.url] == HEALTHY
        result = await stack.executor.execute("print(3)", chip_count=2)
        assert result.exit_code == 0
        # The healthy lane never saw a verdict worse than healthy/busy.
        assert stack.executor.leases.recovering("lane-0") is False
    finally:
        await stack.close()


async def test_probe_storm_stops_at_the_actuation_budget():
    """Every host of the lane reports wedged (the probe-false-positive
    storm): disposals stop at the per-window cap instead of mass-disposing
    the lane, and the deferred verdicts are counted."""
    stack = _Stack(
        f"attach_hang:1.0,attach_hang_lane:0,seed:{CHAOS_SEED}",
        device_fence_max_per_window=2,
        device_fence_window_seconds=600.0,
    )
    try:
        for _ in range(4):
            sandbox = await stack.executor._spawn_with_retry(0)
            stack.executor._pool(0).append(sandbox)
        deletes_before = stack.backend.inner.deletes
        await stack.probe.probe_once()  # hangs start
        stack.now[0] += 100.0
        await stack.probe.probe_once()  # every host wedged
        await stack.settle()
        fences = stack.fences()
        assert fences.get(("0", "fenced"), 0) == 2
        assert fences.get(("0", "budget_exhausted"), 0) >= 2
        # Only the budgeted hosts were disposed; the rest are deferred,
        # still live, waiting for the window (or an operator).
        assert stack.backend.inner.deletes - deletes_before == 2
        wedged_live = [
            s
            for _, s in stack.executor.live_hosts()
            if s.meta.get("device_health") == "wedged"
        ]
        assert len(wedged_live) >= 2
    finally:
        await stack.close()


async def test_full_lifecycle_on_the_recovering_fault():
    """wedge -> drain -> dispose -> respawn -> clean-streak -> re-admit,
    with the seeded attach_hang_recover fault: the replacement's own hang
    clears after its draws, the streak completes, and the lane serves."""
    stack = _Stack(
        f"attach_hang:1.0,attach_hang_lane:0,attach_hang_recover:2,"
        f"seed:{CHAOS_SEED}"
    )
    try:
        sandbox = await stack.executor._spawn_with_retry(0)
        stack.executor._pool(0).append(sandbox)
        await stack.probe.probe_once()  # draw 1: hang starts (busy)
        stack.now[0] += 100.0
        states = await stack.probe.probe_once()  # draw 2: wedged
        assert states[sandbox.url] == WEDGED
        await stack.settle()
        assert stack.executor.live_sandbox(sandbox.id) is None
        assert stack.executor.leases.recovering("lane-0")
        replacement = stack.executor._pool(0)[0]
        # The replacement hangs too (rate 1.0), but its hang clears after
        # its 2 draws — its early "attaching" probes count clean (busy),
        # the streak completes, and the scope re-admits.
        states = await stack.probe.probe_once()
        assert states[replacement.url] == RECOVERING
        states = await stack.probe.probe_once()
        assert states[replacement.url] == HEALTHY
        assert not stack.executor.leases.recovering("lane-0")
        # Post-recovery the transport serves REAL stats (the hang cleared
        # for good) and the lane serves requests again.
        states = await stack.probe.probe_once()
        assert states[replacement.url] == HEALTHY
        result = await stack.executor.execute("print(1)")
        assert result.exit_code == 0
    finally:
        await stack.close()


async def test_constrained_lane_acquire_parks_until_readmission():
    """Capacity-1 lane whose only pooled host is recovering: an acquire
    must not spawn a competitor for the chip the quarantined host still
    owns — it parks, and completes the moment the streak re-admits."""
    stack = _Stack(f"attach_hang:0.0,seed:{CHAOS_SEED}")
    stack.backend.inner.capacity = 1
    try:
        sandbox = await stack.executor._spawn_with_retry(0)
        stack.executor._pool(0).append(sandbox)
        lease = sandbox.meta["lease"]
        # Fence the scope by hand (the probe path is covered above), then
        # put a fresh recovering host in the pool.
        stack.executor.leases.fence(lease)
        stack.executor._pool(0).remove(sandbox)
        await stack.executor._dispose(sandbox)
        replacement = await stack.executor._spawn_with_retry(0)
        assert replacement.meta["device_health"] == "recovering"
        stack.executor._pool(0).append(replacement)
        spawns_before = stack.backend.inner.spawns
        request = asyncio.create_task(stack.executor.execute("print(1)"))
        await asyncio.sleep(0.05)
        assert not request.done()  # parked, not spawning a competitor
        assert stack.backend.inner.spawns == spawns_before
        # Two clean probe cycles re-admit the scope; the settle kicks the
        # parked waiter, which pops the re-admitted host.
        await stack.probe.probe_once()
        await stack.probe.probe_once()
        result = await asyncio.wait_for(request, timeout=5.0)
        assert result.exit_code == 0
    finally:
        await stack.close()
