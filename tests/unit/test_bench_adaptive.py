"""Adaptive headline sampling in bench.py (VERDICT r4 #2).

The driver's r4 headline under-sampled: a fixed ``runs=4`` cut the loop off
mid-warm-up (3.7 → 15.8 → 19.0 → 19.1 GFLOPS, still climbing) and recorded
less than half the chip's steady state. These tests drive ``run_gflops``
against a simulated slow-warm-up backend and assert the adaptive loop keeps
sampling until the plateau (or stops early on the budget), with the plateau
value landing in the artifact info.
"""

import time

import pytest

import bench


class _FakeResult:
    def __init__(self, gflops: float):
        self.exit_code = 0
        self.stdout = f"backend: jax\nGFLOPS={gflops}\n"
        self.stderr = ""
        self.phases = {}


class _FakeExecutor:
    """Stands in for CodeExecutor: returns a scripted GFLOPS ramp."""

    script: list[float] = []
    sleep_s: float = 0.0

    def __init__(self, *a, **kw):
        self.calls = 0

    async def fill_pool(self):
        pass

    async def execute(self, source, timeout=None):
        idx = min(self.calls, len(self.script) - 1)
        self.calls += 1
        if self.sleep_s:
            time.sleep(self.sleep_s)
        return _FakeResult(self.script[idx])

    async def close(self):
        pass


@pytest.fixture
def fake_backend(monkeypatch):
    monkeypatch.setattr(bench, "LocalSandboxBackend", lambda *a, **kw: None)
    monkeypatch.setattr(bench, "Storage", lambda *a, **kw: None)
    monkeypatch.setattr(bench, "CodeExecutor", _FakeExecutor)
    monkeypatch.setattr(bench, "_DEADLINE_AT", None)
    return _FakeExecutor


async def test_slow_warmup_reaches_plateau(fake_backend, tmp_path):
    # r4's observed ramp, then the steady state a fixed runs=4 never saw.
    fake_backend.script = [3.7, 15.8, 19.0, 25.0, 38.0, 45.0, 45.2, 45.2]
    fake_backend.sleep_s = 0.0
    best, info = await bench.run_gflops(
        dispatch=True, runs=4, tmp=tmp_path, adaptive=True, budget_s=60.0
    )
    assert best == pytest.approx(45.2)
    assert len(info["gflops_samples"]) > 4  # kept going past the old cutoff
    assert info["gflops_plateaued"] is True
    # stopped at the plateau, not at max_runs
    assert len(info["gflops_samples"]) <= 8


async def test_midclimb_flat_spot_does_not_stop(fake_backend, tmp_path):
    # The EXACT r4 driver failure: 19.0 -> 19.1 is a two-sample flat spot
    # in the middle of the climb to ~45. A last-two plateau rule stops
    # there with the >2x understatement; the last-three rule must ride
    # through it to the real steady state.
    fake_backend.script = [3.7, 15.8, 19.0, 19.1, 30.0, 44.0, 45.0, 45.1]
    fake_backend.sleep_s = 0.0
    best, info = await bench.run_gflops(
        dispatch=True, runs=4, tmp=tmp_path, adaptive=True, budget_s=60.0
    )
    assert best == pytest.approx(45.1)
    assert info["gflops_plateaued"] is True


async def test_fixed_mode_unchanged(fake_backend, tmp_path):
    fake_backend.script = [3.7, 15.8, 19.0, 19.1, 45.0]
    fake_backend.sleep_s = 0.0
    best, info = await bench.run_gflops(dispatch=True, runs=4, tmp=tmp_path)
    assert len(info["gflops_samples"]) == 4
    assert best == pytest.approx(19.1)
    assert "gflops_plateaued" not in info


async def test_budget_stops_a_climbing_ramp(fake_backend, tmp_path):
    # Monotonic ramp that never plateaus; per-run cost ~0.05s with a budget
    # that only fits a few extra runs past the minimum.
    fake_backend.script = [float(i * 10 + 1) for i in range(50)]
    fake_backend.sleep_s = 0.05
    best, info = await bench.run_gflops(
        dispatch=True, runs=4, tmp=tmp_path, adaptive=True, budget_s=0.35
    )
    n = len(info["gflops_samples"])
    assert 4 <= n < 12
    assert info["gflops_plateaued"] is False
    assert best == pytest.approx(info["gflops_samples"][-1])


async def test_max_runs_backstop(fake_backend, tmp_path):
    fake_backend.script = [float(i * 10 + 1) for i in range(50)]
    fake_backend.sleep_s = 0.0
    best, info = await bench.run_gflops(
        dispatch=True, runs=4, tmp=tmp_path, adaptive=True, budget_s=600.0,
        max_runs=7,
    )
    assert len(info["gflops_samples"]) == 7


def test_plateau_predicate():
    assert not bench._plateaued([], 0.05)
    assert not bench._plateaued([10.0], 0.05)
    assert not bench._plateaued([10.0, 10.2], 0.05)  # two is not enough
    assert bench._plateaued([10.0, 10.2, 10.1], 0.05)
    assert not bench._plateaued([10.0, 19.0, 19.1], 0.05)  # mid-climb flat
    # only the last three matter
    assert bench._plateaued([3.0, 44.0, 45.0, 44.8], 0.05)
