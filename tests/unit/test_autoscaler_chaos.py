"""Seeded-chaos coverage for warm-pool autoscaling: spawn faults mid-ramp.

The invariant under fire: the TARGET is a pure function of demand, so spawn
failures (supply-side noise) must never oscillate it — a fault-riddled ramp
converges by retrying spawns toward a steady target, not by flapping the
target itself. Seeds pin the fault pattern (CHAOS_SEED env in CI's matrix,
the PR 2 discipline).
"""

import asyncio
import os

import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.autoscaler import LaneSnapshot
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage

SEEDS = [int(s) for s in os.environ.get("CHAOS_SEED", "7 23 1337").split()]


class FakeSandboxServer:
    def __init__(self, executor: CodeExecutor):
        async def fake_post_execute(client, base, payload, timeout, sandbox):
            return {
                "stdout": "ok\n",
                "stderr": "",
                "exit_code": 0,
                "files": [],
                "warm": True,
            }

        executor._post_execute = fake_post_execute


def make_executor(backend, tmp_path, **config_kwargs) -> CodeExecutor:
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
        compile_cache_prewarm=False,
        # The breaker has its own suites; keep it out of the ramp's way.
        breaker_failure_threshold=1000,
        **config_kwargs,
    )
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    FakeSandboxServer(executor)
    return executor


async def settle(executor: CodeExecutor) -> None:
    for _ in range(400):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


@pytest.mark.parametrize("seed", SEEDS)
async def test_spawn_faults_mid_ramp_do_not_oscillate_target(tmp_path, seed):
    """50% seeded spawn failure while a queued burst ramps the target: the
    target must move monotonically up during the ramp (faults are not
    demand), and the burst-capped refill must still converge the pool to
    the target by retrying."""
    inner = FakeBackend()
    backend = FaultInjectingBackend(inner, FaultSpec(spawn_fail=0.5, seed=seed))
    executor = make_executor(backend, tmp_path, pool_spawn_burst=2)
    try:
        observed: list[int] = []
        original = executor.autoscaler.evaluate

        def spy(lane, snapshot):
            target = original(lane, snapshot)
            observed.append(target)
            return target

        executor.autoscaler.evaluate = spy
        # Demand: a held burst of 5 queued acquisitions' worth.
        executor.autoscaler.observe_arrival(0, LaneSnapshot(queued=4), jobs=1)
        target = executor.autoscaler.target(0)
        assert target == 5
        # Ramp under fire: sweeps keep re-arming fill_pool through faults.
        for _ in range(40):
            await executor.autoscale_sweep()
            await settle(executor)
            if len(executor._pool(0)) >= target:
                break
        assert len(executor._pool(0)) == target, (
            f"pool never converged under seed={seed}"
        )
        # No sweep ever LOWERED the target mid-ramp: hysteresis holds it
        # while spawn failures rage (supply noise is not demand).
        assert observed, "sweep never evaluated the lane"
        assert all(t == target for t in observed), observed
    finally:
        await executor.close()


@pytest.mark.parametrize("seed", SEEDS)
async def test_chaotic_burst_traffic_converges_and_serves(tmp_path, seed):
    """End to end under 30% spawn faults: a concurrent burst is fully
    served, the dynamic target retains recycled supply, and a follow-up
    wave rides warm pops."""
    inner = FakeBackend()
    backend = FaultInjectingBackend(inner, FaultSpec(spawn_fail=0.3, seed=seed))
    executor = make_executor(backend, tmp_path)
    try:
        results = await asyncio.gather(
            *(executor.execute("print('x')") for _ in range(6))
        )
        assert all(r.exit_code == 0 for r in results)
        await settle(executor)
        assert executor._lane_target(0) > 1
        assert len(executor._pool(0)) >= 1
        again = await asyncio.gather(
            *(executor.execute("print('y')") for _ in range(3))
        )
        assert all(r.exit_code == 0 for r in again)
    finally:
        await executor.close()


@pytest.mark.parametrize("seed", SEEDS)
async def test_kill_switch_under_chaos_keeps_static_pool(tmp_path, seed):
    """The kill switch holds under fire too: with autoscaling off, a burst
    through a faulty backend leaves the static-target pool bound intact."""
    inner = FakeBackend()
    backend = FaultInjectingBackend(inner, FaultSpec(spawn_fail=0.3, seed=seed))
    executor = make_executor(
        backend, tmp_path, pool_autoscale_enabled=False
    )
    try:
        results = await asyncio.gather(
            *(executor.execute("print('x')") for _ in range(5))
        )
        assert all(r.exit_code == 0 for r in results)
        await settle(executor)
        assert executor._lane_target(0) == 1
        assert len(executor._pool(0)) <= 1
    finally:
        await executor.close()
