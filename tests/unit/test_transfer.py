"""Pure unit coverage for the delta-transfer state machine
(services/transfer.py): upload-delta computation, execute-response parsing,
host-manifest lifecycle transitions, and the stats accounting the metrics
and Result.phases surfaces consume.
"""

from bee_code_interpreter_fs_tpu.services.transfer import (
    HostManifest,
    SandboxTransfer,
    TransferStats,
    compute_upload_delta,
    parse_files_field,
)

SHA_A = "a" * 64
SHA_B = "b" * 64
SHA_C = "c" * 64


# ------------------------------------------------------------- upload delta


def test_delta_skips_exact_matches_only():
    manifest = {"kept.txt": SHA_A, "changed.txt": SHA_B}
    uploads = {
        "kept.txt": SHA_A,  # same rel, same sha -> skip
        "changed.txt": SHA_C,  # same rel, different sha -> upload
        "new.txt": SHA_B,  # same sha exists under ANOTHER rel -> upload
    }
    to_upload, skipped = compute_upload_delta(manifest, uploads)
    assert skipped == {"kept.txt": SHA_A}
    assert to_upload == {"changed.txt": SHA_C, "new.txt": SHA_B}


def test_delta_unknown_manifest_uploads_everything():
    to_upload, skipped = compute_upload_delta(None, {"a.txt": SHA_A})
    assert to_upload == {"a.txt": SHA_A}
    assert skipped == {}


def test_delta_legacy_object_ids_never_skip():
    # A legacy opaque id is not a content sha: it can never be negotiated,
    # even if a stale manifest entry happens to carry the same string.
    manifest = {"a.txt": "legacy-id-1"}
    to_upload, skipped = compute_upload_delta(manifest, {"a.txt": "legacy-id-1"})
    assert to_upload == {"a.txt": "legacy-id-1"}
    assert skipped == {}


def test_delta_empty_known_manifest_uploads_everything():
    to_upload, skipped = compute_upload_delta({}, {"a.txt": SHA_A})
    assert to_upload == {"a.txt": SHA_A}
    assert skipped == {}


# --------------------------------------------------------- response parsing


def test_parse_files_field_hashed_entries():
    entries, has_hashes = parse_files_field(
        [{"path": "a.txt", "sha256": SHA_A}, {"path": "b.txt"}]
    )
    assert entries == [("a.txt", SHA_A), ("b.txt", None)]
    assert has_hashes is True  # a missing sha on one entry is not legacy


def test_parse_files_field_legacy_strings():
    entries, has_hashes = parse_files_field(["a.txt", "b.txt"])
    assert entries == [("a.txt", None), ("b.txt", None)]
    assert has_hashes is False


def test_parse_files_field_empty_is_not_evidence():
    entries, has_hashes = parse_files_field([])
    assert entries == []
    assert has_hashes is True


def test_parse_files_field_rejects_malformed_shas():
    entries, _ = parse_files_field(
        [{"path": "a.txt", "sha256": "NOT-A-SHA"}, {"sha256": SHA_A}]
    )
    # Bad sha -> entry kept hash-less; entry without a path dropped.
    assert entries == [("a.txt", None)]


# --------------------------------------------------- host manifest lifecycle


def test_manifest_starts_empty_known_and_records_uploads():
    manifest = HostManifest()
    assert manifest.entries == {}
    manifest.record_upload("a.txt", SHA_A)
    assert manifest.entries == {"a.txt": SHA_A}
    assert manifest.supports is True


def test_manifest_hashless_upload_response_marks_legacy():
    manifest = HostManifest()
    manifest.record_upload("a.txt", None)
    assert manifest.entries is None
    assert manifest.supports is False
    # Legacy is sticky: later uploads change nothing and delta never skips.
    to_upload, skipped = manifest.delta({"a.txt": SHA_A})
    assert to_upload and not skipped


def test_manifest_execute_response_updates_and_deletes():
    manifest = HostManifest()
    manifest.record_upload("a.txt", SHA_A)
    manifest.record_upload("b.txt", SHA_B)
    manifest.apply_execute_response([("a.txt", SHA_C)], deleted=["b.txt"])
    assert manifest.entries == {"a.txt": SHA_C}
    # A hash-less entry (file vanished mid-scan) drops from the cache so the
    # next turn re-uploads rather than wrongly skipping.
    manifest.apply_execute_response([("a.txt", None)], deleted=[])
    assert manifest.entries == {}


def test_manifest_invalidate_then_resync():
    manifest = HostManifest()
    manifest.record_upload("a.txt", SHA_A)
    manifest.invalidate()
    assert manifest.entries is None
    assert manifest.supports is True  # protocol memo survives doubt
    manifest.resynced({"a.txt": SHA_B})
    assert manifest.entries == {"a.txt": SHA_B}


def test_manifest_reset_restores_empty_known():
    manifest = HostManifest()
    manifest.record_upload("a.txt", SHA_A)
    manifest.reset()
    assert manifest.entries == {}
    assert manifest.supports is True


def test_sandbox_transfer_disabled_pins_legacy():
    transfer = SandboxTransfer(enabled=False)
    manifest = transfer.host("http://h0")
    assert manifest.supports is False
    assert manifest.entries is None


def test_sandbox_transfer_reset_covers_all_hosts():
    transfer = SandboxTransfer()
    transfer.host("http://h0").record_upload("a.txt", SHA_A)
    transfer.host("http://h1").record_upload("a.txt", SHA_A)
    transfer.reset()
    assert transfer.host("http://h0").entries == {}
    assert transfer.host("http://h1").entries == {}


# ------------------------------------------------------------------- stats


def test_stats_phases_blob():
    stats = TransferStats(
        upload_bytes=10,
        upload_skipped_bytes=20,
        download_bytes=30,
        download_skipped_bytes=40,
    )
    assert stats.as_phases() == {
        "upload_bytes": 10.0,
        "upload_skipped_bytes": 20.0,
        "download_bytes": 30.0,
        "download_skipped_bytes": 40.0,
    }


def test_stats_emit_feeds_transfer_metrics():
    from bee_code_interpreter_fs_tpu.utils.metrics import ExecutorMetrics

    metrics = ExecutorMetrics()
    TransferStats(
        upload_bytes=100,
        upload_files=2,
        upload_skipped_bytes=50,
        upload_skipped_files=1,
        download_bytes=7,
        download_files=1,
    ).emit(metrics)
    rendered = metrics.registry.render()
    assert (
        'code_interpreter_transfer_bytes_total{direction="upload"} 100'
        in rendered
    )
    assert (
        'code_interpreter_transfer_skipped_bytes_total{direction="upload"} 50'
        in rendered
    )
    assert (
        'code_interpreter_transfer_files_total{direction="download"} 1'
        in rendered
    )
    assert "code_interpreter_transfer_phase_bytes_bucket" in rendered
