"""Fault-injection backend unit tests (services/backends/faults.py):
spec-grammar parsing, seeded determinism, per-category fault behavior, and
the injectable httpx transport that drops requests on the wire."""

import httpx
import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.services.backends.base import (
    Sandbox,
    SandboxSpawnError,
)
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    DroppingTransport,
    FaultInjectingBackend,
    FaultSpec,
)


# ------------------------------------------------------------------- parsing


def test_parse_full_grammar():
    spec = FaultSpec.parse(
        "spawn_fail:0.3, slow_ready:1.5,reset_fail:0.2,"
        "delete_hang:0.5 , exec_drop:0.1, seed:7"
    )
    assert spec == FaultSpec(
        spawn_fail=0.3,
        slow_ready=1.5,
        reset_fail=0.2,
        delete_hang=0.5,
        exec_drop=0.1,
        seed=7,
    )
    assert spec.active


def test_parse_empty_is_null_plan():
    spec = FaultSpec.parse("")
    assert spec == FaultSpec()
    assert not spec.active


def test_parse_rejects_unknown_keys_and_bad_values():
    with pytest.raises(ValueError, match="bad fault spec item"):
        FaultSpec.parse("spawn_fial:0.3")  # typo must fail loudly
    with pytest.raises(ValueError, match="bad fault spec value"):
        FaultSpec.parse("spawn_fail:lots")
    with pytest.raises(ValueError, match="must be in"):
        FaultSpec.parse("spawn_fail:1.5")
    with pytest.raises(ValueError, match=">= 0"):
        FaultSpec.parse("slow_ready:-1")
    with pytest.raises(ValueError, match="bad fault spec item"):
        FaultSpec.parse("spawn_fail=0.3")  # wrong separator


# -------------------------------------------------------------- determinism


async def spawn_outcomes(seed: int, n: int = 24) -> list[bool]:
    backend = FaultInjectingBackend(
        FakeBackend(), FaultSpec(spawn_fail=0.5, seed=seed)
    )
    outcomes = []
    for _ in range(n):
        try:
            await backend.spawn()
            outcomes.append(True)
        except SandboxSpawnError:
            outcomes.append(False)
    return outcomes


async def test_same_seed_reproduces_the_same_fault_plan():
    assert await spawn_outcomes(7) == await spawn_outcomes(7)


async def test_fault_categories_draw_from_independent_streams():
    """Interleaving reset rolls must not perturb the spawn sequence — per-
    category RNG streams are what make a concurrent chaos run replayable."""
    spec = FaultSpec(spawn_fail=0.5, reset_fail=0.5, seed=7)
    plain = FaultInjectingBackend(FakeBackend(), spec)
    interleaved = FaultInjectingBackend(FakeBackend(), spec)

    async def outcome(backend):
        try:
            await backend.spawn()
            return True
        except SandboxSpawnError:
            return False

    first = [await outcome(plain) for _ in range(12)]
    second = []
    for _ in range(12):
        second.append(await outcome(interleaved))
        await interleaved.reset(Sandbox(id="x", url="http://fake"))
    assert first == second


# ------------------------------------------------------------ fault behavior


async def test_spawn_fail_raises_and_counts():
    faults: list[str] = []
    backend = FaultInjectingBackend(
        FakeBackend(),
        FaultSpec(spawn_fail=1.0, seed=1),
        on_fault=faults.append,
    )
    with pytest.raises(SandboxSpawnError, match="injected spawn failure"):
        await backend.spawn(chip_count=4)
    assert faults == ["spawn_fail"]
    assert backend.inner.spawns == 0, "the real backend was never reached"


async def test_reset_fail_refuses_recycle():
    inner = FakeBackend()
    backend = FaultInjectingBackend(
        inner, FaultSpec(reset_fail=1.0, seed=1)
    )
    sandbox = await backend.spawn()
    assert await backend.reset(sandbox) is None
    assert inner.resets == 0


async def test_delete_hang_still_deletes():
    inner = FakeBackend()
    backend = FaultInjectingBackend(
        inner, FaultSpec(delete_hang=0.01, seed=1)
    )
    sandbox = await backend.spawn()
    await backend.delete(sandbox)
    assert inner.deletes == 1
    assert not inner.live


async def test_slow_ready_spawn_still_succeeds():
    inner = FakeBackend()
    backend = FaultInjectingBackend(
        inner, FaultSpec(slow_ready=0.01, seed=1)
    )
    sandbox = await backend.spawn()
    assert sandbox.id in inner.live


async def test_capacity_passthrough():
    backend = FaultInjectingBackend(
        FakeBackend(capacity=2), FaultSpec(seed=1)
    )
    assert backend.pool_capacity(0) == 2


# ---------------------------------------------------------------- transport


async def test_http_transport_absent_without_exec_drop():
    backend = FaultInjectingBackend(FakeBackend(), FaultSpec(spawn_fail=0.5))
    assert backend.http_transport() is None


async def test_dropping_transport_raises_connect_error():
    faults: list[str] = []
    backend = FaultInjectingBackend(
        FakeBackend(),
        FaultSpec(exec_drop=1.0, seed=3),
        on_fault=faults.append,
    )
    transport = backend.http_transport()
    assert isinstance(transport, DroppingTransport)
    async with httpx.AsyncClient(transport=transport) as client:
        with pytest.raises(httpx.ConnectError, match="injected connection drop"):
            await client.get("http://sandbox.invalid/execute")
    assert faults == ["exec_drop"]


async def test_dropping_transport_passes_through_below_rate():
    inner = httpx.MockTransport(lambda request: httpx.Response(200, json={"ok": True}))
    backend = FaultInjectingBackend(
        FakeBackend(), FaultSpec(exec_drop=0.0, seed=3)
    )
    assert backend.http_transport() is None
    # rate 0 via a directly-built transport: every request reaches the inner.
    import random

    transport = DroppingTransport(0.0, random.Random(0), inner=inner)
    async with httpx.AsyncClient(transport=transport) as client:
        resp = await client.get("http://sandbox.invalid/healthz")
    assert resp.status_code == 200


def test_parse_attach_hang_recovery_modifiers():
    """The wedge-recovery chaos knobs: attach_hang_max bounds how many
    hosts ever wedge, attach_hang_recover clears a host's hang after n
    wedged stats draws. Modifiers only — neither activates the plan by
    itself (a max with no rate injects nothing)."""
    spec = FaultSpec.parse(
        "attach_hang:1.0,attach_hang_lane:2,attach_hang_max:1,"
        "attach_hang_recover:3,seed:9"
    )
    assert spec.attach_hang == 1.0
    assert spec.attach_hang_max == 1
    assert spec.attach_hang_recover == 3
    assert spec.active
    assert not FaultSpec.parse("attach_hang_max:2,attach_hang_recover:5").active
    with pytest.raises(ValueError):
        FaultSpec.parse("attach_hang_max:lots")
