"""Unit tests for the first-party tracing subsystem (utils/tracing.py):
W3C traceparent handling, contextvar span nesting, head-based sampling,
exporters, and the no-op fast paths the 0%-overhead gate depends on.
"""

import asyncio
import json
import random

from bee_code_interpreter_fs_tpu.utils import tracing
from bee_code_interpreter_fs_tpu.utils.tracing import (
    GLOBAL_RING,
    NOOP,
    JsonlExporter,
    TraceRing,
    Tracer,
    format_traceparent,
    parse_traceparent,
)

TRACE_ID = "a" * 32
SPAN_ID = "b" * 16


def make_tracer(**kwargs):
    kwargs.setdefault("ring", TraceRing(64))
    return Tracer(**kwargs)


# ------------------------------------------------------------- traceparent


def test_traceparent_roundtrip():
    header = format_traceparent(TRACE_ID, SPAN_ID, True)
    assert header == f"00-{TRACE_ID}-{SPAN_ID}-01"
    assert parse_traceparent(header) == (TRACE_ID, SPAN_ID, True)
    header = format_traceparent(TRACE_ID, SPAN_ID, False)
    assert parse_traceparent(header) == (TRACE_ID, SPAN_ID, False)


def test_parse_traceparent_rejects_malformed():
    assert parse_traceparent(None) is None
    assert parse_traceparent("") is None
    assert parse_traceparent("garbage") is None
    assert parse_traceparent(f"00-{TRACE_ID}-{SPAN_ID}") is None  # no flags
    assert parse_traceparent(f"ff-{TRACE_ID}-{SPAN_ID}-01") is None  # version
    assert parse_traceparent(f"00-{'0' * 32}-{SPAN_ID}-01") is None  # zero id
    assert parse_traceparent(f"00-{TRACE_ID}-{'0' * 16}-01") is None
    assert parse_traceparent(f"00-{TRACE_ID.upper()}-{SPAN_ID}-01") == (
        TRACE_ID,
        SPAN_ID,
        True,
    )  # case-normalized


# --------------------------------------------------------- nesting/parents


def test_span_nesting_records_parent_ids():
    tracer = make_tracer()
    with tracer.start_trace("root") as root:
        with tracer.span("child") as child:
            with tracer.span("grandchild") as grandchild:
                pass
    spans = {s["name"]: s for s in tracer.ring.trace(root.trace_id)}
    assert spans["root"]["parent_id"] is None
    assert spans["child"]["parent_id"] == root.span_id
    assert spans["grandchild"]["parent_id"] == child.span_id
    assert {s["trace_id"] for s in spans.values()} == {root.trace_id}


def test_incoming_traceparent_joins_trace():
    tracer = make_tracer()
    header = format_traceparent(TRACE_ID, SPAN_ID, True)
    with tracer.start_trace("root", traceparent=header) as root:
        assert root.trace_id == TRACE_ID
        assert root.parent_id == SPAN_ID
    [span] = tracer.ring.trace(TRACE_ID)
    assert span["parent_id"] == SPAN_ID


async def test_concurrent_tasks_keep_independent_current_spans():
    """gather() runs children in separate tasks with copied contexts: each
    task's span parents to the root, never to a sibling."""
    tracer = make_tracer()

    async def leaf(i):
        with tracer.span(f"leaf-{i}"):
            await asyncio.sleep(0.01)

    with tracer.start_trace("root") as root:
        await asyncio.gather(*(leaf(i) for i in range(4)))
    spans = tracer.ring.trace(root.trace_id)
    leaves = [s for s in spans if s["name"].startswith("leaf-")]
    assert len(leaves) == 4
    assert all(s["parent_id"] == root.span_id for s in leaves)


def test_span_error_status_still_exports():
    tracer = make_tracer()
    try:
        with tracer.start_trace("root") as root:
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    [span] = tracer.ring.trace(root.trace_id)
    assert span["status"] == "error"
    assert "boom" in span["attributes"]["error"]


# -------------------------------------------------------------- sampling


def test_unsampled_incoming_propagates_ids_but_records_nothing():
    tracer = make_tracer()
    header = format_traceparent(TRACE_ID, SPAN_ID, False)
    with tracer.start_trace("root", traceparent=header) as root:
        assert not root.recording
        assert root.traceparent() == header  # same ids, flag 00, onward
        with tracer.span("child") as child:
            assert not child.recording
            assert child.traceparent() == header  # parent's ids onward
    assert len(tracer.ring) == 0


async def test_unsampled_concurrent_children_do_not_corrupt_context():
    """Regression: concurrently gathered tasks each enter a child of an
    unsampled root. Shared context-manager state across task contexts would
    pop another task's ContextVar token (ValueError); children must be
    per-call instances that never touch the contextvar."""
    tracer = make_tracer()
    header = format_traceparent(TRACE_ID, SPAN_ID, False)

    async def hop(i):
        with tracer.span(f"hop-{i}") as span:
            await asyncio.sleep(0.01 * (3 - i))  # exits in reverse order
            assert span.traceparent() == header
        assert tracing.current_trace_id() == TRACE_ID  # parent still current

    with tracer.start_trace("root", traceparent=header):
        await asyncio.gather(*(hop(i) for i in range(3)))
    assert len(tracer.ring) == 0


def test_sample_ratio_zero_records_nothing():
    # tail_enabled=False: this pins the pure HEAD-sampling contract (with
    # tail sampling on, an unsampled root records tentatively — covered by
    # the tail-sampling tests below).
    tracer = make_tracer(sample_ratio=0.0, tail_enabled=False)
    with tracer.start_trace("root") as root:
        assert not root.recording
        assert root.trace_id  # ids still propagate downstream (flag 00)
        assert root.traceparent().endswith("-00")
        assert tracing.current_trace_id() == root.trace_id  # propagation
    assert len(tracer.ring) == 0
    assert tracing.current_trace_id() is None  # reset on exit


def test_sample_ratio_is_respected():
    tracer = make_tracer(
        sample_ratio=0.5, rng=random.Random(42), tail_enabled=False
    )
    recorded = sum(
        1 for _ in range(200) if tracer.start_trace("t").recording
    )
    assert 60 < recorded < 140  # deterministic given the seeded rng


def test_incoming_sampled_flag_beats_local_ratio():
    tracer = make_tracer(sample_ratio=0.0)
    header = format_traceparent(TRACE_ID, SPAN_ID, True)
    with tracer.start_trace("root", traceparent=header) as root:
        assert root.recording  # upstream already decided: record


def test_disabled_tracer_is_fully_noop():
    tracer = make_tracer(enabled=False)
    root = tracer.start_trace("root", traceparent=format_traceparent(TRACE_ID, SPAN_ID, True))
    assert root is NOOP
    assert root.traceparent() is None  # nothing propagates at all
    with root:
        assert tracer.span("child") is NOOP
        tracing.add_event("ignored")
    assert len(tracer.ring) == 0
    tracer.record_span(
        "grafted", trace_id=TRACE_ID, parent_id=None, start_unix=0.0,
        duration_s=1.0,
    )
    assert len(tracer.ring) == 0


# -------------------------------------------------------------- exporters


def test_ring_capacity_bound():
    tracer = Tracer(ring=TraceRing(capacity=8))
    for _ in range(20):
        with tracer.start_trace("t"):
            pass
    assert len(tracer.ring) == 8


def test_ring_recent_summaries():
    tracer = make_tracer()
    ids = []
    for i in range(3):
        with tracer.start_trace(f"root-{i}") as root:
            with tracer.span("child"):
                pass
        ids.append(root.trace_id)
    recent = tracer.ring.recent(limit=2)
    assert [r["trace_id"] for r in recent] == [ids[2], ids[1]]
    assert recent[0]["root"] == "root-2"
    assert recent[0]["spans"] == 2


def test_ring_jsonl_export_parses():
    tracer = make_tracer()
    with tracer.start_trace("root") as root:
        with tracer.span("child"):
            pass
    lines = tracer.ring.export_jsonl(root.trace_id).splitlines()
    assert len(lines) == 2
    parsed = [json.loads(line) for line in lines]
    assert {s["trace_id"] for s in parsed} == {root.trace_id}


def test_jsonl_file_exporter(tmp_path):
    path = tmp_path / "spans.jsonl"
    tracer = make_tracer(jsonl_path=str(path))
    with tracer.start_trace("root") as root:
        pass
    [line] = path.read_text().splitlines()
    assert json.loads(line)["trace_id"] == root.trace_id


def test_jsonl_exporter_disables_on_write_failure(tmp_path):
    exporter = JsonlExporter(str(tmp_path / "nope" / "spans.jsonl"))
    exporter.add({"name": "x"})  # parent dir missing: must not raise
    assert exporter._broken


def test_global_ring_receives_every_tracers_spans():
    GLOBAL_RING.clear()
    tracer = make_tracer()
    with tracer.start_trace("root") as root:
        pass
    assert any(
        s["trace_id"] == root.trace_id for s in GLOBAL_RING.trace(root.trace_id)
    )


def test_record_span_grafts_child():
    tracer = make_tracer()
    tracer.record_span(
        "sandbox.exec",
        trace_id=TRACE_ID,
        parent_id=SPAN_ID,
        start_unix=123.0,
        duration_s=0.5,
        attributes={"host": "http://h0"},
    )
    [span] = tracer.ring.trace(TRACE_ID)
    assert span["parent_id"] == SPAN_ID
    assert span["start_unix"] == 123.0
    assert span["duration_s"] == 0.5
    assert span["attributes"]["host"] == "http://h0"


# ---------------------------------------------------------------- metrics


class _HistogramStub:
    def __init__(self):
        self.observed = []

    def observe(self, value, **labels):
        self.observed.append((value, labels))


class _MetricsStub:
    def __init__(self):
        self.span_seconds = _HistogramStub()


def test_spans_feed_the_stage_histogram():
    metrics = _MetricsStub()
    tracer = make_tracer(metrics=metrics)
    with tracer.start_trace("root"):
        with tracer.span("transfer.upload"):
            pass
    names = [labels["span"] for _, labels in metrics.span_seconds.observed]
    assert names == ["transfer.upload", "root"]


# ------------------------------------------------------------ module utils


def test_add_event_without_current_span_is_noop():
    assert tracing.current_span() is None
    tracing.add_event("orphan", x=1)  # must not raise


def test_current_trace_id_inside_span():
    tracer = make_tracer()
    with tracer.start_trace("root") as root:
        assert tracing.current_trace_id() == root.trace_id
    assert tracing.current_trace_id() is None


# ------------------------------------------------------- tail-based sampling
# Head sampling's coin flip said NO, but the trace turned out to matter:
# error status, a typed limit.violation event, or a slow root. Those traces
# are kept anyway (recorded tentatively, retained at the root's finish) —
# the flight recorder that makes a batched dispatch's one bad request
# reconstructible at 1% head ratios. Ordinary unsampled traces still drop.


class FakeClock:
    """Injectable clock/walltime pair for deterministic duration tests."""

    def __init__(self) -> None:
        self.now = 1000.0

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


def make_tail_tracer(**kwargs):
    kwargs.setdefault("sample_ratio", 0.0)  # head sampling always says no
    kwargs.setdefault("tail_slow_seconds", 5.0)
    return make_tracer(**kwargs)


def test_tail_drops_ordinary_unsampled_traces():
    tracer = make_tail_tracer()
    with tracer.start_trace("root"):
        with tracer.span("child"):
            pass
    assert len(tracer.ring) == 0
    assert tracer._tentative == {}  # nothing buffered after the decision


def test_tail_keeps_error_traces_with_all_their_spans():
    tracer = make_tail_tracer()
    try:
        with tracer.start_trace("root") as root:
            trace_id = root.trace_id
            with tracer.span("scheduler.queue_wait"):
                pass
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    spans = tracer.ring.trace(trace_id)
    assert {s["name"] for s in spans} == {"root", "scheduler.queue_wait"}
    assert all(s["attributes"]["sampled"] == "tail" for s in spans)
    assert any(s["status"] == "error" for s in spans)


def test_tail_keeps_violation_event_traces():
    tracer = make_tail_tracer()
    with tracer.start_trace("root") as root:
        trace_id = root.trace_id
        with tracer.span("executor.execute"):
            tracing.add_event("limit.violation", kind="oom", lane=4)
    spans = tracer.ring.trace(trace_id)
    assert len(spans) == 2  # kept: the violation is exactly what to keep


def test_tail_keeps_slow_roots():
    clock = FakeClock()
    tracer = make_tail_tracer(
        clock=clock, walltime=clock, tail_slow_seconds=2.0
    )
    with tracer.start_trace("root") as root:
        trace_id = root.trace_id
        clock.advance(3.0)
    assert len(tracer.ring.trace(trace_id)) == 1
    # ...and a fast clean root still drops.
    with tracer.start_trace("root2") as root2:
        clock.advance(0.5)
    assert tracer.ring.trace(root2.trace_id) == []


def test_tail_respects_upstream_unsampled_flag():
    # An incoming flag-00 traceparent is an upstream DECISION, not a local
    # coin flip — tail sampling must not override it (W3C restart rule).
    tracer = make_tail_tracer()
    header = format_traceparent(TRACE_ID, SPAN_ID, False)
    with tracer.start_trace("root", traceparent=header) as root:
        assert not root.recording


def test_tail_buffer_is_bounded():
    tracer = make_tail_tracer()
    roots = [tracer.start_trace(f"r{i}") for i in range(tracer.TAIL_MAX_TRACES + 8)]
    tentative = sum(1 for r in roots if r.recording)
    assert tentative == tracer.TAIL_MAX_TRACES  # overflow falls back to drop
    for root in roots:
        with root:
            pass
    assert tracer._tentative == {}


def test_tail_disabled_restores_head_only_behavior():
    tracer = make_tail_tracer(tail_enabled=False)
    try:
        with tracer.start_trace("root") as root:
            assert not root.recording
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert len(tracer.ring) == 0


def test_tail_keeps_root_when_span_buffer_overflows():
    """The root exports OUTSIDE the span-buffer cap: a busy slow request
    that accumulates > TAIL_MAX_SPANS children before its root finishes is
    exactly the tail-keep target, and a kept trace without its root would
    have no duration and no tree anchor (found in review)."""
    tracer = make_tail_tracer()
    try:
        with tracer.start_trace("root") as root:
            trace_id = root.trace_id
            for i in range(tracer.TAIL_MAX_SPANS + 16):
                with tracer.span(f"child-{i}"):
                    pass
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    spans = tracer.ring.trace(trace_id)
    # The cap held for children, but the root itself is among the exports
    # (it lands last, so the bounded ring retains it).
    assert any(s["name"] == "root" and s["status"] == "error" for s in spans)
    assert tracer._tentative == {}
