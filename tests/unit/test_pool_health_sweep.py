"""Regression tests for sweep_pool_health (ISSUE 1 satellite).

Pins two behaviors that existed but had no test coverage:
- the disposal race window: a sandbox popped by a request BETWEEN the
  failed probe and ``pool.remove`` must be left alone (the request owns it
  now — disposing it under a live request would kill the execution);
- multi-host probes run concurrently per sandbox (serialized 3s timeouts
  across a hung slice's hosts would make one sweep take minutes).
"""

import asyncio

import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.base import Sandbox
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage


class FakeProbeClient:
    """Stands in for the executor's httpx client inside sweep_pool_health.

    Each GET consults ``responses`` (url -> status code, default 200).
    ``gate`` (when set) makes every probe wait until the test releases it —
    the window the disposal-race test widens. Concurrency is tracked so the
    multi-host test can assert parallel fan-out."""

    def __init__(self) -> None:
        self.responses: dict[str, int] = {}
        self.gate: asyncio.Event | None = None
        self.probing = asyncio.Event()
        self.active = 0
        self.max_active = 0

    async def get(self, url: str, timeout=None):
        self.active += 1
        self.max_active = max(self.max_active, self.active)
        self.probing.set()
        try:
            if self.gate is not None:
                await self.gate.wait()
            else:
                await asyncio.sleep(0.01)
            base = url.rsplit("/healthz", 1)[0]
            status = self.responses.get(base, 200)

            class Response:
                status_code = status

            return Response()
        finally:
            self.active -= 1


def make_executor(tmp_path, backend=None):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
    )
    backend = backend or FakeBackend()
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    client = FakeProbeClient()
    executor._http_client = lambda: client
    return executor, backend, client


async def test_unresponsive_pooled_sandbox_is_disposed(tmp_path):
    executor, backend, client = make_executor(tmp_path)
    try:
        dead = Sandbox(id="dead", url="http://dead")
        live = Sandbox(id="live", url="http://live")
        backend.live.update({"dead", "live"})
        executor._pool(0).extend([dead, live])
        client.responses["http://dead"] = 500

        removed = await executor.sweep_pool_health()
        assert removed == 1
        assert [s.id for s in executor._pool(0)] == ["live"]
        # Dispose runs via a tracked background task; let it land.
        await asyncio.gather(*executor._dispose_tasks, return_exceptions=True)
        assert "dead" not in backend.live
    finally:
        await executor.close()


async def test_sandbox_popped_mid_probe_is_not_disposed(tmp_path):
    """The race window: the probe fails, but a request pops the sandbox
    before the sweep's ``pool.remove`` runs. The sweep must skip it — that
    sandbox now belongs to the request, and its "failure" may simply be
    the probe losing to the pop."""
    executor, backend, client = make_executor(tmp_path)
    try:
        sandbox = Sandbox(id="contested", url="http://contested")
        backend.live.add("contested")
        executor._pool(0).append(sandbox)
        client.responses["http://contested"] = 500
        client.gate = asyncio.Event()  # hold every probe open

        sweep = asyncio.create_task(executor.sweep_pool_health())
        await client.probing.wait()  # the probe is in flight...
        popped = executor._pool(0).popleft()  # ...and a request wins the pop
        client.gate.set()

        removed = await sweep
        assert removed == 0, "a popped sandbox must not count as swept"
        assert not executor._dispose_tasks
        assert backend.deletes == 0, "the request's sandbox must survive"
        assert popped.id == "contested"
    finally:
        await executor.close()


async def test_multi_host_probes_fan_out_concurrently(tmp_path):
    executor, backend, client = make_executor(tmp_path)
    try:
        slice_sandbox = Sandbox(
            id="slice",
            url="http://host0",
            chip_count=8,
            host_urls=["http://host0", "http://host1", "http://host2"],
        )
        backend.live.add("slice")
        executor._pool(8).append(slice_sandbox)

        removed = await executor.sweep_pool_health()
        assert removed == 0
        assert client.max_active == 3, "per-sandbox host probes must overlap"
        assert [s.id for s in executor._pool(8)] == ["slice"]
    finally:
        await executor.close()


async def test_one_dead_host_fails_the_whole_slice(tmp_path):
    """A multi-host sandbox is one scheduling unit: any dead host means the
    jax.distributed mesh is gone, so the whole slice is disposed."""
    executor, backend, client = make_executor(tmp_path)
    try:
        slice_sandbox = Sandbox(
            id="slice",
            url="http://host0",
            chip_count=8,
            host_urls=["http://host0", "http://host1"],
        )
        backend.live.add("slice")
        executor._pool(8).append(slice_sandbox)
        client.responses["http://host1"] = 500

        removed = await executor.sweep_pool_health()
        assert removed == 1
        assert not executor._pool(8)
        await asyncio.gather(*executor._dispose_tasks, return_exceptions=True)
        assert "slice" not in backend.live
    finally:
        await executor.close()
