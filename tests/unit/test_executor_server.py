"""Tests driving the real C++ executor server binary over HTTP.

The reference had no tests for its executor at all (SURVEY.md §4); these
exercise upload/download with path confinement, /execute (warm-runner mode
with JAX import disabled for speed), timeout kill + runner restart, and
recursive changed-file detection.
"""

import json
import os
import re
import signal
import subprocess
import time
from pathlib import Path

import httpx
import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
EXECUTOR_DIR = REPO_ROOT / "executor"
# CI points this at the ASan/TSan builds to run the same suite under
# sanitizers (SURVEY.md §5: the C++ rebuild earns its safety story in CI).
BINARY = Path(
    os.environ.get("TEST_EXECUTOR_BINARY", EXECUTOR_DIR / "build" / "executor-server")
)


def _server_env(ws, rp) -> dict:
    """Server env based on os.environ so CI's ASAN_OPTIONS/TSAN_OPTIONS
    (halt_on_error etc.) actually reach the sanitized process — a hand-built
    env dict would leave the sanitizer jobs blind."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "APP_LISTEN_ADDR": "127.0.0.1:0",
            "APP_WORKSPACE": str(ws),
            "APP_RUNTIME_PACKAGES": str(rp),
            "APP_WARM_IMPORT_JAX": "0",
            # Short cooperative-cancellation grace so the forced-kill tests
            # don't stall the suite waiting out the production default.
            "APP_RUNNER_INTERRUPT_GRACE_S": "2",
        }
    )
    return env


@pytest.fixture(scope="module")
def executor(tmp_path_factory):
    if "TEST_EXECUTOR_BINARY" not in os.environ:
        subprocess.run(
            ["make", "-C", str(EXECUTOR_DIR)], check=True, capture_output=True
        )
    root = tmp_path_factory.mktemp("executor")
    ws = root / "ws"
    rp = root / "rp"
    ws.mkdir()
    rp.mkdir()
    proc = subprocess.Popen(
        [str(BINARY)],
        env=_server_env(ws, rp),
        stdout=subprocess.PIPE,
        stderr=None,  # inherit: sanitizer reports must reach the test log
    )
    line = proc.stdout.readline().decode()
    port = int(re.search(r"port=(\d+)", line).group(1))
    client = httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=30.0)
    # The port is announced before warm-up (that's the round-2 design);
    # wait for the background warm thread to finish before tests run.
    for _ in range(200):
        try:
            if client.get("/healthz").json().get("warm"):
                break
        except httpx.TransportError:
            pass
        time.sleep(0.1)
    yield client, ws
    client.close()
    proc.kill()
    proc.wait()


def execute(client, source, **kwargs):
    resp = client.post("/execute", json={"source_code": source, **kwargs})
    assert resp.status_code == 200, resp.text
    return resp.json()


def file_paths(result):
    """Changed-file rel paths from an execute response. Manifest-enabled
    binaries report [{"path", "sha256"}, ...]; legacy mode plain strings."""
    return [
        entry["path"] if isinstance(entry, dict) else entry
        for entry in result["files"]
    ]


def test_healthz_warm(executor):
    client, _ = executor
    health = client.get("/healthz").json()
    assert health["status"] == "ok"
    assert health["warm"] is True
    assert health["warm_state"] == "ready"


def test_readyz_ready(executor):
    client, _ = executor
    resp = client.get("/readyz")
    assert resp.status_code == 200
    assert resp.json()["warm"] is True


def test_warmup_idempotent(executor):
    client, _ = executor
    resp = client.post("/warmup")
    assert resp.status_code == 200
    assert resp.json()["warm_state"] == "ready"


def test_upload_download_roundtrip(executor):
    client, ws = executor
    resp = client.put("/workspace/dir/sub/file.txt", content=b"payload")
    assert resp.status_code == 200
    assert (ws / "dir/sub/file.txt").read_bytes() == b"payload"
    got = client.get("/workspace/dir/sub/file.txt")
    assert got.status_code == 200
    assert got.content == b"payload"


def test_double_prefix_tolerated(executor):
    # The reference control plane produced /workspace//workspace/x URLs
    # (SURVEY.md §0.4); they must land at workspace root, not a nested dir.
    client, ws = executor
    client.put("/workspace//workspace/legacy.txt", content=b"legacy")
    assert (ws / "legacy.txt").read_bytes() == b"legacy"


def test_path_traversal_blocked(executor):
    client, _ = executor
    assert client.put("/workspace/../escape.txt", content=b"x").status_code in (400, 403)
    assert client.get("/workspace/../../etc/passwd").status_code in (400, 403, 404)
    assert client.get("/unknown-prefix/foo").status_code == 404


def test_symlink_escape_blocked(executor):
    client, ws = executor
    (ws / "link").symlink_to("/etc")
    resp = client.get("/workspace/link/passwd")
    assert resp.status_code == 403


def test_execute_stdout_stderr_exit(executor):
    client, _ = executor
    result = execute(client, "import sys\nprint('out')\nprint('err', file=sys.stderr)\nsys.exit(5)")
    assert result["stdout"] == "out\n"
    assert result["stderr"].strip() == "err"
    assert result["exit_code"] == 5
    assert result["warm"] is True


def test_execute_changed_files_recursive(executor):
    client, _ = executor
    result = execute(
        client,
        "import os\nos.makedirs('deep/nested', exist_ok=True)\n"
        "open('deep/nested/new.txt', 'w').write('x')\nopen('top.txt', 'w').write('y')",
    )
    assert result["exit_code"] == 0
    assert "deep/nested/new.txt" in file_paths(result)
    assert "top.txt" in file_paths(result)


def test_execute_timeout_cooperative_cancel(executor):
    """An interruptible runaway (the common case) is cancelled via SIGINT:
    the response carries timeout semantics, but the warm runner SURVIVES —
    no background restart, and the very next request is served warm. On a
    leased accelerator this is what keeps a timeout from abandoning the
    device claim (SIGKILL mid-op wedged the tunneled TPU for ~25 min)."""
    client, _ = executor
    result = execute(client, "while True: pass", timeout=1)
    assert result["exit_code"] == -1
    assert "timed out" in result["stderr"]
    assert result["runner_restarted"] is False
    result = execute(client, "print('still warm')")
    assert result["stdout"] == "still warm\n"
    assert result["warm"] is True


def test_execute_timeout_and_recovery(executor):
    """An UNinterruptible runaway (ignores SIGINT outright) exhausts the
    cancellation grace and exercises the forced-kill + background-rewarm
    path."""
    client, _ = executor
    result = execute(
        client,
        "import signal\n"
        "signal.signal(signal.SIGINT, signal.SIG_IGN)\n"
        "while True: pass",
        timeout=1,
    )
    assert result["exit_code"] == -1
    assert "timed out" in result["stderr"]
    # The runner restart happens in the BACKGROUND (VERDICT r1 #9): the very
    # next request must not pay runner re-init on its critical path — it is
    # served by the cold subprocess immediately.
    t0 = time.monotonic()
    result = execute(client, "print('recovered')")
    elapsed = time.monotonic() - t0
    assert result["stdout"] == "recovered\n"
    assert result["exit_code"] == 0
    assert result["warm"] is False
    assert elapsed < 10, f"cold fallback took {elapsed:.1f}s"
    # and the background restart eventually restores warm service
    for _ in range(100):
        if client.get("/healthz").json().get("warm"):
            break
        time.sleep(0.1)
    else:
        pytest.fail("runner did not restart in the background")
    result = execute(client, "print('warm again')")
    assert result["warm"] is True


def test_execute_stream_chunks_arrive_live(executor):
    """POST /execute/stream: NDJSON chunks must arrive while the code is
    still running (not buffered until completion), and the final event must
    be the complete /execute response body."""
    client, _ = executor
    src = (
        "import time\n"
        "for i in range(4):\n"
        "    print('tick', i, flush=True)\n"
        "    time.sleep(0.3)\n"
        "open('streamed.txt', 'w').write('done')\n"
    )
    events = []
    t0 = time.monotonic()
    with client.stream(
        "POST", "/execute/stream", json={"source_code": src}
    ) as resp:
        assert resp.status_code == 200
        buf = ""
        for text in resp.iter_text():
            buf += text
            while "\n" in buf:
                line, buf = buf.split("\n", 1)
                if line.strip():
                    events.append((time.monotonic() - t0, json.loads(line)))
    chunks = [e for _, e in events if "stream" in e]
    assert chunks, "no stream chunks arrived"
    # First chunk must beat the full runtime (~1.2 s) by a wide margin.
    assert events[0][0] < 0.9, f"first chunk too late: {events[0][0]:.2f}s"
    final = events[-1][1]
    assert final["exit_code"] == 0
    assert final["stdout"] == "tick 0\ntick 1\ntick 2\ntick 3\n"
    assert "streamed.txt" in file_paths(final)
    assert final["runner_restarted"] is False
    joined = "".join(c["data"] for c in chunks if c["stream"] == "stdout")
    assert joined == final["stdout"]


def test_execute_stream_utf8_never_split(executor):
    """Multi-byte UTF-8 output streamed in many flushes must decode cleanly
    per event: a chunk boundary through a codepoint would turn both halves
    into U+FFFD. Joined chunks must equal the final stdout exactly."""
    client, _ = executor
    src = (
        "import sys, time\n"
        "for i in range(40):\n"
        "    sys.stdout.write('\\u6f22\\u5b57\\U0001f600' * 50)\n"
        "    sys.stdout.flush()\n"
        "    time.sleep(0.02)\n"
    )
    events = []
    with client.stream(
        "POST", "/execute/stream", json={"source_code": src}
    ) as resp:
        buf = ""
        for text in resp.iter_text():
            buf += text
            while "\n" in buf:
                line, buf = buf.split("\n", 1)
                if line.strip():
                    events.append(json.loads(line))
    chunks = [e for e in events if e.get("stream") == "stdout"]
    final = events[-1]
    assert final["exit_code"] == 0
    joined = "".join(c["data"] for c in chunks)
    assert "�" not in joined
    assert joined == final["stdout"]


def test_execute_stream_timeout(executor):
    """Timeout during a streamed execute: the final event carries the same
    timeout semantics as /execute (exit -1, marker in stderr)."""
    client, _ = executor
    events = []
    with client.stream(
        "POST",
        "/execute/stream",
        json={"source_code": "import time\ntime.sleep(30)", "timeout": 1},
    ) as resp:
        buf = ""
        for text in resp.iter_text():
            buf += text
            while "\n" in buf:
                line, buf = buf.split("\n", 1)
                if line.strip():
                    events.append(json.loads(line))
    final = events[-1]
    assert final["exit_code"] == -1
    assert "timed out" in final["stderr"]
    # time.sleep is SIGINT-interruptible, so cooperative cancellation keeps
    # the runner (and a real deployment's device lease) alive — no restart.
    assert final["runner_restarted"] is False
    assert client.get("/healthz").json().get("warm") is True


def test_execute_mixed_shell_python(executor):
    """Mixed Python/shell snippets (the xonsh role, reference server.rs:
    197-207) execute through the warm runner via the shellfb transform."""
    result = execute(
        client_of(executor),
        "x = 21\necho marker-line > shell_out.txt\n"
        "print(open('shell_out.txt').read().strip())\nprint(x * 2)",
    )
    assert result["exit_code"] == 0
    assert result["stdout"] == "marker-line\n42\n"
    assert "shell_out.txt" in file_paths(result)


def client_of(executor):
    client, _ = executor
    return client


def test_execute_exception_traceback(executor):
    client, _ = executor
    result = execute(client, "1/0")
    assert result["exit_code"] == 1
    assert "ZeroDivisionError" in result["stderr"]


def test_execute_env_passthrough(executor):
    client, _ = executor
    result = execute(
        client, "import os\nprint(os.environ['MY_FLAG'])", env={"MY_FLAG": "tpu"}
    )
    assert result["stdout"] == "tpu\n"


def test_execute_source_file(executor):
    client, _ = executor
    client.put("/workspace/prog.py", content=b"print('from file')")
    resp = client.post("/execute", json={"source_file": "/workspace/prog.py"})
    assert resp.json()["stdout"] == "from file\n"
    # and confinement on source_file
    resp = client.post("/execute", json={"source_file": "/../../etc/passwd"})
    assert resp.status_code == 403


def test_execute_bad_request(executor):
    client, _ = executor
    assert client.post("/execute", content=b"not json").status_code == 400
    assert client.post("/execute", json={}).status_code == 400


def test_unicode_roundtrip(executor):
    client, _ = executor
    result = execute(client, "print('héllo ✓ 日本語')")
    assert result["stdout"] == "héllo ✓ 日本語\n"


def test_reset_scrubs_generation(executor):
    """POST /reset is the generation turnover that lets the control plane
    reuse the warm device process (VERDICT r2 #1): the previous sandbox's
    files, env mutations, workspace module imports, and stray child
    processes must all be gone; the warm runner must stay alive."""
    client, ws = executor
    result = execute(
        client,
        "import os, subprocess, sys\n"
        "open('leftover.txt', 'w').write('secret')\n"
        "open('shadow.py', 'w').write('VALUE = 1')\n"
        "sys.path.insert(0, os.getcwd())\n"
        "import shadow\n"
        "print(shadow.VALUE)\n"
        "os.environ['LEAKED_VAR'] = 'oops'\n"
        "child = subprocess.Popen(['sleep', '600'])\n"
        "print(child.pid)\n",
    )
    assert result["exit_code"] == 0, result["stderr"]
    lines = result["stdout"].split()
    assert lines[0] == "1"
    child_pid = int(lines[1])

    resp = client.post("/reset")
    assert resp.status_code == 200, resp.text
    assert resp.json()["ok"] is True
    assert resp.json()["warm"] is True  # the device process survived

    assert list(ws.iterdir()) == []  # workspace wiped in place
    with pytest.raises(ProcessLookupError):
        os.kill(child_pid, 0)  # stray child reaped

    result = execute(
        client,
        "import os, sys\n"
        "print(sorted(os.listdir('.')))\n"
        "print(os.environ.get('LEAKED_VAR'))\n"
        "open('shadow.py', 'w').write('VALUE = 2')\n"
        "sys.path.insert(0, os.getcwd())\n"
        "import shadow\n"
        "print(shadow.VALUE)\n",
    )
    assert result["exit_code"] == 0, result["stderr"]
    out = result["stdout"].splitlines()
    assert out[0] == "[]"  # fresh workspace
    assert out[1] == "None"  # env restored
    assert out[2] == "2"  # no module-cache shadow from the last generation
    assert result["warm"] is True
    client.post("/reset")  # leave a clean workspace for the next test


def test_reset_refused_when_user_thread_survives(executor):
    """A thread the previous generation started cannot be killed from
    outside — the runner must refuse the reset so the control plane
    disposes the whole process instead of recycling it."""
    client, _ = executor
    result = execute(
        client,
        "import threading, time\n"
        "threading.Thread(target=time.sleep, args=(600,), daemon=True).start()\n"
        "print('spawned')\n",
    )
    assert result["exit_code"] == 0, result["stderr"]
    resp = client.post("/reset")
    assert resp.status_code == 409
    assert resp.json()["ok"] is False
    # The refusal marks the runner failed; restore warm service for the
    # remaining tests the way the control plane would not (it would dispose)
    # — this dev server can just rewarm.
    client.post("/warmup")
    for _ in range(100):
        if client.get("/healthz").json().get("warm"):
            break
        time.sleep(0.1)
    else:
        pytest.fail("runner did not rewarm after refused reset")


def test_reset_wipes_extra_dirs_and_tmpdir(tmp_path):
    """APP_RESET_EXTRA_WIPE_DIRS closes the cross-generation channels
    outside workspace/runtime-packages (sandbox-private tmp, ~/.local)."""
    ws = tmp_path / "ws"
    rp = tmp_path / "rp"
    extra = tmp_path / "scratch-tmp"
    ws.mkdir()
    rp.mkdir()
    extra.mkdir()
    env = _server_env(ws, rp)
    env["APP_RESET_EXTRA_WIPE_DIRS"] = str(extra) + ":" + str(
        tmp_path / "never-created"
    )
    env["TMPDIR"] = str(extra)
    proc = subprocess.Popen(
        [str(BINARY)], env=env, stdout=subprocess.PIPE, stderr=None
    )
    try:
        line = proc.stdout.readline().decode()
        port = int(re.search(r"port=(\d+)", line).group(1))
        with httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=30.0) as c:
            for _ in range(200):
                if c.get("/healthz").json().get("warm"):
                    break
                time.sleep(0.05)
            result = c.post(
                "/execute",
                json={
                    "source_code": "import tempfile, os\n"
                    "fd, path = tempfile.mkstemp()\n"
                    "os.write(fd, b'stash')\n"
                    "os.close(fd)\n"
                    "print(path)\n"
                },
            ).json()
            assert result["exit_code"] == 0, result["stderr"]
            stash_path = result["stdout"].strip()
            assert stash_path.startswith(str(extra))  # TMPDIR honored
            resp = c.post("/reset")
            assert resp.status_code == 200, resp.text
        assert list(extra.iterdir()) == []  # scratch tmp wiped
    finally:
        proc.kill()
        proc.wait()


def test_runner_dead_at_request_flags_restart(tmp_path):
    """A warm runner that died BETWEEN requests (OOM-kill etc.) must be
    detected at the next /execute: the response reports
    runner_restarted=true (sessions key their state-loss signal off it) and
    a background rewarm starts — without this, the sandbox would serve
    every subsequent request cold forever and sessions would silently lose
    their in-process state. (Detection happens inside the runner protocol —
    the dead/zombie runner's pipe EOFs -> kDied; alive() alone cannot see a
    zombie.)"""
    ws = tmp_path / "ws"
    rp = tmp_path / "rp"
    ws.mkdir()
    rp.mkdir()
    proc = subprocess.Popen(
        [str(BINARY)], env=_server_env(ws, rp), stdout=subprocess.PIPE, stderr=None
    )
    try:
        line = proc.stdout.readline().decode()
        port = int(re.search(r"port=(\d+)", line).group(1))
        with httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=30.0) as c:
            for _ in range(200):
                if c.get("/healthz").json().get("warm"):
                    break
                time.sleep(0.1)
            else:
                pytest.fail("runner never warmed")
            # Kill the runner out-of-band: it is the server's only child.
            children = [
                int(p)
                for p in os.listdir("/proc")
                if p.isdigit() and _ppid_of(int(p)) == proc.pid
            ]
            assert children, "no runner child found"
            for pid in children:
                os.kill(pid, signal.SIGKILL)
            time.sleep(0.3)

            resp = c.post("/execute", json={"source_code": "print('x')"})
            body = resp.json()
            # The request hits the dead runner: reported honestly (the code
            # never ran) and flagged so the control plane ends any session.
            assert body["exit_code"] == -1
            assert "runner crashed" in body["stderr"].lower()
            assert body["runner_restarted"] is True
            # The background rewarm restores warm service.
            for _ in range(200):
                if c.get("/healthz").json().get("warm"):
                    break
                time.sleep(0.1)
            else:
                pytest.fail("runner did not restart in the background")
            body = c.post(
                "/execute", json={"source_code": "print('warm')"}
            ).json()
            assert body["warm"] is True
            assert body["runner_restarted"] is False
    finally:
        proc.kill()
        proc.wait()


def _ppid_of(pid: int) -> int:
    """Exact ppid (field 2 after the parenthesized comm) — matching the pid
    loosely against all stat fields could hit unrelated processes' counters
    and SIGKILL them."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read()
        return int(stat.rsplit(b") ", 1)[1].split()[1])
    except (OSError, IndexError, ValueError):
        return -1


def test_reset_refused_when_runner_cold(tmp_path):
    """A sandbox whose runner never warmed (or was killed) must not be
    recycled: /reset answers 409 so the control plane disposes it."""
    ws = tmp_path / "ws"
    rp = tmp_path / "rp"
    ws.mkdir()
    rp.mkdir()
    env = _server_env(ws, rp)
    env["APP_WARM_EAGER"] = "0"  # warm-up waits for /warmup that never comes
    proc = subprocess.Popen(
        [str(BINARY)], env=env, stdout=subprocess.PIPE, stderr=None
    )
    try:
        line = proc.stdout.readline().decode()
        port = int(re.search(r"port=(\d+)", line).group(1))
        with httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=10.0) as c:
            resp = c.post("/reset")
            assert resp.status_code == 409
            assert resp.json()["ok"] is False
    finally:
        proc.kill()
        proc.wait()


def test_reset_without_warm_runner_wipes(tmp_path):
    """Warm mode off (plumbing/dev): /reset still wipes both prefixes."""
    ws = tmp_path / "ws"
    rp = tmp_path / "rp"
    ws.mkdir()
    rp.mkdir()
    (ws / "old.txt").write_text("x")
    (rp / "pkg").mkdir()
    (rp / "pkg" / "mod.py").write_text("y")
    env = _server_env(ws, rp)
    env["APP_WARM_RUNNER"] = "0"
    proc = subprocess.Popen(
        [str(BINARY)], env=env, stdout=subprocess.PIPE, stderr=None
    )
    try:
        line = proc.stdout.readline().decode()
        port = int(re.search(r"port=(\d+)", line).group(1))
        with httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=10.0) as c:
            resp = c.post("/reset")
            assert resp.status_code == 200
            assert resp.json()["ok"] is True
        assert list(ws.iterdir()) == []
        assert list(rp.iterdir()) == []
    finally:
        proc.kill()
        proc.wait()


def test_deps_scanner():
    out = subprocess.run(
        [
            "python",
            str(EXECUTOR_DIR / "deps.py"),
            "/dev/stdin",
        ],
        input=b"import os\nimport numpy\nimport definitely_not_installed_pkg\nfrom PIL import Image\n",
        capture_output=True,
        check=True,
    )
    missing = out.stdout.decode().split()
    assert "definitely_not_installed_pkg" in missing
    assert "numpy" not in missing  # installed
    assert "os" not in missing  # stdlib


def test_sigterm_reaps_runner_session(tmp_path):
    """SIGTERM to the server must take the warm runner down with it even
    though the runner sits in its own session (kubelet pod stop and the
    local backend's graceful teardown both rely on this; a GIL-wedged
    runner cannot be trusted to notice pipe EOF itself)."""
    import signal

    ws = tmp_path / "ws"
    rp = tmp_path / "rp"
    ws.mkdir()
    rp.mkdir()
    proc = subprocess.Popen(
        [str(BINARY)],
        env=_server_env(ws, rp),
        stdout=subprocess.PIPE,
        stderr=None,
        start_new_session=True,
    )
    try:
        assert b"port=" in proc.stdout.readline()
        # the warm runner is forked by a background warm-up thread now —
        # poll for the server's only child to appear
        deadline = time.time() + 10
        children: list[str] = []
        while time.time() < deadline and not children:
            children = subprocess.run(
                ["pgrep", "-P", str(proc.pid)], capture_output=True, text=True
            ).stdout.split()
            time.sleep(0.05)
        assert len(children) == 1, children
        runner_pid = int(children[0])

        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=5)
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                os.kill(runner_pid, 0)
            except ProcessLookupError:
                break  # runner reaped by the server's handler
            time.sleep(0.05)
        else:
            pytest.fail(f"runner {runner_pid} survived server SIGTERM")
    finally:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass


def test_allocation_bomb_gets_memoryerror_not_host_oom(tmp_path):
    """APP_MAX_USER_MEMORY_BYTES bounds user-code address-space growth with
    a soft RLIMIT_AS window (runner.py:_apply_user_rlimits): an allocation
    bomb gets a clean in-process MemoryError — traceback in its own stderr,
    exit_code 1 — instead of inviting the host OOM killer, and the warm
    runner (limits restored) keeps serving (VERDICT r3 #6; the reference
    delegates this wholesale to the cluster runtime, README.md:56-57)."""
    ws = tmp_path / "ws"
    rp = tmp_path / "rp"
    ws.mkdir()
    rp.mkdir()
    env = _server_env(ws, rp)
    env["APP_MAX_USER_MEMORY_BYTES"] = str(256 * 1024 * 1024)  # 256 MiB window
    proc = subprocess.Popen(
        [str(BINARY)], env=env, stdout=subprocess.PIPE, stderr=None
    )
    try:
        line = proc.stdout.readline().decode()
        port = int(re.search(r"port=(\d+)", line).group(1))
        with httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=60.0) as c:
            for _ in range(200):
                if c.get("/healthz").json().get("warm"):
                    break
                time.sleep(0.05)
            bomb = c.post(
                "/execute",
                json={
                    "source_code": "chunks = []\n"
                    "while True:\n"
                    "    chunks.append(bytearray(64 * 1024 * 1024))\n"
                },
            ).json()
            assert bomb["exit_code"] == 1, bomb
            assert "MemoryError" in bomb["stderr"], bomb["stderr"][-400:]
            assert not bomb.get("runner_restarted"), bomb
            # Limits were restored: the runner still serves normal requests
            # and can allocate modestly again.
            after = c.post(
                "/execute",
                json={"source_code": "b = bytearray(8 * 1024 * 1024)\nprint(len(b))\n"},
            ).json()
            assert after["exit_code"] == 0, after["stderr"]
            assert after["stdout"].strip() == str(8 * 1024 * 1024)
            # The knob is operator policy: a request-supplied env override
            # must NOT reach the run (else the bomb could disarm the limit).
            override = c.post(
                "/execute",
                json={
                    "source_code": "import os\n"
                    "print(os.environ.get('APP_MAX_USER_MEMORY_BYTES'))\n",
                    "env": {"APP_MAX_USER_MEMORY_BYTES": "0"},
                },
            ).json()
            assert override["stdout"].strip() == str(256 * 1024 * 1024)
    finally:
        proc.kill()
        proc.wait()


TRACEPARENT = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"


def test_execute_trace_block_with_traceparent(executor):
    """A traceparent header makes the response carry a `trace` block: the
    echoed context plus install/exec/collect phase spans with offsets
    relative to the request's own start (ISSUE 4 tentpole — the control
    plane grafts these into the request's trace as child spans)."""
    client, ws = executor
    result = client.post(
        "/execute",
        json={"source_code": "print('traced')"},
        headers={"traceparent": TRACEPARENT},
    ).json()
    assert result["exit_code"] == 0
    trace = result["trace"]
    assert trace["traceparent"] == TRACEPARENT
    spans = {s["name"]: s for s in trace["spans"]}
    assert set(spans) == {"install", "exec", "collect"}
    for span in spans.values():
        assert span["start_offset_s"] >= 0
        assert span["duration_s"] >= 0
    # Phases run in order: install, then exec, then collect.
    assert spans["install"]["start_offset_s"] <= spans["exec"]["start_offset_s"]
    assert spans["exec"]["start_offset_s"] <= spans["collect"]["start_offset_s"]
    # The exec span is the duration_s the response already reported.
    assert spans["exec"]["duration_s"] == result["duration_s"]


def test_execute_no_trace_block_without_traceparent(executor):
    """No trace context, no trace block — the wire format is unchanged for
    untraced callers (and old control planes)."""
    client, ws = executor
    result = execute(client, "print('untraced')")
    assert "trace" not in result


def test_execute_stream_trace_block(executor):
    """The streaming surface's final event carries the same trace block."""
    client, ws = executor
    with client.stream(
        "POST",
        "/execute/stream",
        json={"source_code": "print('streamed')"},
        headers={"traceparent": TRACEPARENT},
    ) as resp:
        assert resp.status_code == 200
        lines = [json.loads(l) for l in resp.iter_lines() if l.strip()]
    final = lines[-1]
    assert final["exit_code"] == 0
    assert final["trace"]["traceparent"] == TRACEPARENT
    assert {s["name"] for s in final["trace"]["spans"]} == {
        "install",
        "exec",
        "collect",
    }


def test_unwritable_tmpdir_falls_back_to_tmp(tmp_path):
    """ISSUE 4 satellite: a bogus TMPDIR (operator typo, missing mount)
    must not fail every request opaquely at mkdtemp — the server falls back
    to /tmp with a logged warning and keeps serving."""
    ws = tmp_path / "ws"
    rp = tmp_path / "rp"
    ws.mkdir()
    rp.mkdir()
    env = _server_env(ws, rp)
    env["TMPDIR"] = str(tmp_path / "does-not-exist")
    proc = subprocess.Popen(
        [str(BINARY)], env=env, stdout=subprocess.PIPE, stderr=None
    )
    try:
        line = proc.stdout.readline().decode()
        port = int(re.search(r"port=(\d+)", line).group(1))
        with httpx.Client(base_url=f"http://127.0.0.1:{port}", timeout=30.0) as c:
            for _ in range(200):
                if c.get("/healthz").json().get("warm"):
                    break
                time.sleep(0.1)
            else:
                pytest.fail("runner never warmed")
            result = c.post(
                "/execute", json={"source_code": "print('fallback ok')"}
            ).json()
            assert result["exit_code"] == 0, result
            assert result["stdout"] == "fallback ok\n"
    finally:
        proc.kill()
        proc.wait()


# ---------------------------------------------------------------------------
# GET /device-stats (the device-health telemetry plane)


def test_device_stats_basic_shape(executor):
    """Warm idle host: the probe-facing signals are all present, ages are
    server-computed, and the op window is closed."""
    client, _ = executor
    execute(client, "print('prime the op counters')")
    stats = client.get("/device-stats").json()
    assert stats["status"] == "ok"
    assert stats["warm"] is True
    assert stats["warm_state"] == "ready"
    assert stats["runner_alive"] is True
    assert stats["runner_pid"] > 0
    assert stats["device_count"] == 0  # APP_WARM_IMPORT_JAX=0 in this suite
    assert stats["op_in_flight"] is False
    assert stats["op_age_s"] == 0
    # The warm-up that made this runner ready was measured.
    assert stats["attach_seconds"] >= 0
    assert stats["attach_pending_s"] == 0
    # A device op just succeeded (the execute above).
    assert 0 <= stats["last_device_op_age_s"] < 30
    # Passive heartbeat: the runner wrote its response moments ago.
    assert 0 <= stats["runner_heartbeat_age_s"] < 30
    # RSS for both processes via /proc.
    assert stats["rss_bytes"] > 0
    assert stats["runner_rss_bytes"] > 0
    assert stats["uptime_s"] > 0


def test_device_stats_answers_during_inflight_op(executor):
    """THE design requirement: while a device op is running (exec_mutex and
    runner_mutex held — exactly the wedged state), /device-stats must still
    answer, report the op in flight with a growing age, and carry the op's
    declared budget so the probe can judge the stall."""
    client, _ = executor
    import threading

    done = threading.Event()

    def run_slow():
        try:
            execute(client, "import time; time.sleep(2)", timeout=30)
        finally:
            done.set()

    thread = threading.Thread(target=run_slow)
    thread.start()
    try:
        probe = httpx.Client(base_url=str(client.base_url), timeout=5.0)
        seen_inflight = None
        for _ in range(100):
            stats = probe.get("/device-stats").json()
            if stats["op_in_flight"]:
                seen_inflight = stats
                break
            time.sleep(0.05)
        assert seen_inflight is not None, "never observed the op in flight"
        assert seen_inflight["op_age_s"] >= 0
        # The budget rides along (timeout 30 + the server's 0.5s pad).
        assert 29 < seen_inflight["op_timeout_s"] < 32
        probe.close()
    finally:
        done.wait(timeout=30)
        thread.join(timeout=30)
    # After completion the window closes and the success stamp moves.
    stats = client.get("/device-stats").json()
    assert stats["op_in_flight"] is False
    assert 0 <= stats["last_device_op_age_s"] < 30


def test_device_stats_runner_identity_after_kill(executor):
    """A forced runner kill flips runner_alive until the background rewarm
    lands — the probe's 'runner died while idle' signal."""
    client, _ = executor
    result = execute(
        client,
        "import signal\n"
        "signal.signal(signal.SIGINT, signal.SIG_IGN)\n"
        "while True: pass",
        timeout=1,
    )
    assert result["exit_code"] == -1
    # Immediately after the kill (before the background rewarm finishes)
    # the mirror may already be re-ready; assert only the eventual state.
    for _ in range(100):
        stats = client.get("/device-stats").json()
        if stats["runner_alive"] and stats["warm_state"] == "ready":
            break
        time.sleep(0.1)
    else:
        pytest.fail("runner never returned to ready after forced kill")
    # The rewarm recorded a fresh attach latency.
    assert stats["attach_seconds"] >= 0


def test_device_stats_detects_silently_dead_runner(executor):
    """A runner OOM-killed BETWEEN requests leaves no trace until the next
    execute — except in /device-stats, whose waitid(WNOWAIT) peek exposes
    the corpse: runner_alive flips false while warm_state still says ready
    (the probe classifies this suspect/runner_dead). The next execute then
    recovers via the normal dead-runner restart path."""
    client, _ = executor
    stats = client.get("/device-stats").json()
    assert stats["runner_alive"] is True
    runner_pid = int(stats["runner_pid"])
    os.kill(runner_pid, signal.SIGKILL)
    for _ in range(100):
        stats = client.get("/device-stats").json()
        if stats["runner_alive"] is False:
            break
        time.sleep(0.05)
    else:
        pytest.fail("silently killed runner still reported alive")
    # The next execute discovers the corpse on the wire (EPIPE -> kDied),
    # reports runner_restarted, and kicks the background rewarm; the one
    # after that is served. Restores warm service for the rest of the
    # module.
    result = execute(client, "print('finds the corpse')")
    assert result["runner_restarted"] is True
    result = execute(client, "print('recovered')")
    assert result["stdout"] == "recovered\n"
    for _ in range(200):
        if client.get("/healthz").json().get("warm"):
            break
        time.sleep(0.1)
    else:
        pytest.fail("runner did not rewarm after silent death")


def test_stale_lease_claim_refused_with_typed_409(executor):
    """Per-chip lease fencing, executor side: once a lease token is
    recorded (POST /lease), an execute dispatch presenting an OLDER token
    is refused with the typed 409 — before the body is processed and
    before exec_mutex, so a stale claim can never even queue behind the
    device plane. Tokenless requests and the current token keep serving
    (old-control-plane compatibility)."""
    client, ws = executor
    # No token recorded yet: any claim passes through.
    r = client.post(
        "/execute",
        json={"source_code": "print('pre')"},
        headers={"x-lease-token": "lane-0:1"},
    )
    assert r.status_code == 200
    # Record generation 2 for this sandbox's chips.
    r = client.post("/lease", json={"token": "lane-0:2"})
    assert r.status_code == 200 and r.json()["ok"] is True
    assert client.get("/device-stats").json()["lease_token"] == "lane-0:2"
    # A stale (generation-1) claim is refused, typed.
    r = client.post(
        "/execute",
        json={"source_code": "print('stale')"},
        headers={"x-lease-token": "lane-0:1"},
    )
    assert r.status_code == 409
    body = r.json()
    assert body["error"] == "stale_lease"
    # The HELD token is log-only: echoing the successor's valid credential
    # to whoever presented a stale one would let any sandbox-internal
    # caller harvest it with a junk claim. The caller's own (stale) token
    # echoes back for diagnostics.
    assert "held" not in body
    assert body["offered"] == "lane-0:1"
    # /execute-batch and /reset refuse the same stale claim (a retry
    # racing a dispose must not wipe the successor's workspace).
    r = client.post(
        "/execute-batch",
        json={"jobs": [{"source_code": "print(1)"}] * 2, "timeout": 10},
        headers={"x-lease-token": "lane-0:1"},
    )
    assert r.status_code == 409 and r.json()["error"] == "stale_lease"
    r = client.post("/reset", headers={"x-lease-token": "lane-0:1"})
    assert r.status_code == 409 and r.json()["error"] == "stale_lease"
    # The CURRENT token serves, as does a tokenless dispatch.
    r = client.post(
        "/execute",
        json={"source_code": "print('current')"},
        headers={"x-lease-token": "lane-0:2"},
    )
    assert r.status_code == 200 and r.json()["stdout"] == "current\n"
    r = client.post("/execute", json={"source_code": "print('bare')"})
    assert r.status_code == 200 and r.json()["stdout"] == "bare\n"
    # Bad /lease bodies are client errors, not token rotations.
    assert client.post("/lease", json={}).status_code == 400
    # First-write-wins: re-pushing the SAME token is an idempotent 200
    # (control-plane push retries), but a ROTATION is refused — tenant
    # code inside the sandbox must not be able to make the control
    # plane's real token read stale.
    assert client.post("/lease", json={"token": "lane-0:2"}).json()["ok"]
    r = client.post("/lease", json={"token": "lane-0:999"})
    assert r.status_code == 409
    assert r.json()["error"] == "lease_already_recorded"
    assert client.get("/device-stats").json()["lease_token"] == "lane-0:2"


def test_snapshot_restore_round_trips_interpreter_state(executor):
    """The session-durability wire protocol against the real binary: a turn
    mutates the interpreter (env var + workspace-module global), /snapshot
    captures it, /reset wipes it, and /restore on a re-uploaded workspace
    brings it back byte-for-byte. This is exactly the hibernate -> evict ->
    lazy-restore path the control plane drives."""
    client, ws = executor
    client.post("/reset")
    assert client.put("/workspace/durmod.py", content=b"counter = 0\n").status_code == 200
    # Workspace-module imports resolve however user code arranges them —
    # here the usual cwd insert (cwd IS the workspace in the warm runner).
    result = execute(
        client,
        "import os, sys\nsys.path.insert(0, os.getcwd())\nimport durmod\n"
        "os.environ['DURABLE_PROBE'] = '42'\ndurmod.counter = 7\n",
    )
    assert result["exit_code"] == 0, result

    snap = client.post("/snapshot", json={})
    assert snap.status_code == 200, snap.text
    body = snap.json()
    assert body["ok"] is True
    state = body["state"]
    assert state["env_set"]["DURABLE_PROBE"] == "42"
    assert "durmod" in [m["name"] for m in state["modules"]]

    # Reset = the hibernate dispose: env gone, workspace gone, modules gone.
    assert client.post("/reset").json()["ok"] is True
    wiped = execute(client, "import os; print(os.environ.get('DURABLE_PROBE'))")
    assert wiped["stdout"] == "None\n"
    assert not (ws / "durmod.py").exists()

    # Restore = what _restore_session does: workspace files first, then the
    # interpreter overlay.
    client.put("/workspace/durmod.py", content=b"counter = 0\n")
    rest = client.post("/restore", json={"state": state})
    assert rest.status_code == 200, rest.text
    assert rest.json()["ok"] is True
    back = execute(
        client,
        "import os, sys\nsys.path.insert(0, os.getcwd())\nimport durmod\n"
        "print(os.environ['DURABLE_PROBE'], durmod.counter)",
    )
    assert back["stdout"] == "42 7\n"
    client.post("/reset")


def test_restore_refusals_leave_runner_untouched(executor):
    """Corrupt or version-skewed state is refused typed BEFORE any mutation
    lands — the never-half-restored invariant at the runner boundary. The
    runner must keep serving normally afterwards."""
    client, _ = executor
    client.post("/reset")
    execute(client, "import os; os.environ['CANARY'] = 'intact'")
    r = client.post("/restore", json={"state": {"version": 99}})
    assert r.status_code == 200
    assert r.json() == {"ok": False, "reason": "bad_state_version"}
    r = client.post(
        "/restore",
        json={
            "state": {
                "version": 1,
                "env_set": {},
                "env_del": [],
                "cwd": ".",
                "modules": [{"name": "x", "values": {"v": "!!!not-base64!!!"}}],
            }
        },
    )
    assert r.status_code == 200
    assert r.json() == {"ok": False, "reason": "corrupt_state"}
    # Neither refusal disturbed the live interpreter.
    result = execute(client, "import os; print(os.environ['CANARY'])")
    assert result["stdout"] == "intact\n"
    client.post("/reset")


def test_snapshot_respects_max_bytes_budget(executor):
    """An oversized interpreter refuses to snapshot (state_too_large) rather
    than shipping an unbounded blob to the control plane; the session then
    just stays resident instead of hibernating."""
    client, _ = executor
    client.post("/reset")
    execute(client, "import os; os.environ['BIG'] = 'x' * 4096")
    r = client.post("/snapshot", json={"max_bytes": 1})
    assert r.status_code == 200
    assert r.json() == {"ok": False, "reason": "state_too_large"}
    # An adequate budget still snapshots the same interpreter.
    assert client.post("/snapshot", json={}).json()["ok"] is True
    client.post("/reset")
