"""Chaos suite for usage metering: attribution stays EXACT under injected
executor faults. Seed-parameterized via ``CHAOS_SEED`` (CI pins
{7, 23, 1337}); every seed replays exactly.

Pinned invariants:
- a request that fails after consuming device time is STILL billed (the
  acceptance criterion verbatim): every attempt that reached the wire
  contributes chip-seconds, successful or not;
- successful attempts bill exactly the executor-reported device-op time —
  the billed total is the reported sum plus the (strictly positive)
  wall-measured cost of faulted attempts;
- request counts stay exact: one logical request per execute() regardless
  of how many retry attempts it burned;
- violations injected by the seeded plan land under their kind in the
  tenant's ledger row;
- the durable journal round-trips the chaos run's exact totals.
"""

import asyncio
import os
import random

import pytest
from fakes import FakeBackend

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.errors import (
    ExecutorError,
    LimitExceededError,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage
from bee_code_interpreter_fs_tpu.services.usage import UsageLedger

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "7"))


def make_executor(tmp_path, **kwargs):
    kwargs.setdefault("file_storage_path", str(tmp_path / "storage"))
    kwargs.setdefault("executor_pod_queue_target_length", 1)
    kwargs.setdefault("batching_enabled", False)
    config = Config(**kwargs)
    return CodeExecutor(FakeBackend(), Storage(config.file_storage_path), config)


class SeededWire:
    """A deterministic faulty wire: each /execute draws from the seeded
    RNG stream — drop (ExecutorError), violate, or answer with a drawn
    device-op time. Tracks exactly what it reported, so the test can
    assert the ledger against ground truth."""

    def __init__(self, executor, seed: int, drop_rate=0.3, violation_rate=0.15):
        self.rng = random.Random(seed)
        self.drop_rate = drop_rate
        self.violation_rate = violation_rate
        self.reported_device_op = 0.0  # sum over bodies actually returned
        self.faulted_attempts = 0
        self.violations = 0
        executor._post_execute = self.post

    async def post(self, client, base, payload, timeout, sandbox):
        draw = self.rng.random()
        if draw < self.drop_rate:
            self.faulted_attempts += 1
            raise ExecutorError("chaos: exec connection dropped")
        device_op = round(self.rng.uniform(0.05, 0.5), 6)
        self.reported_device_op += device_op
        body = {
            "stdout": "ok\n",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "warm": True,
            "duration_s": device_op,
            "device_op_seconds": device_op,
        }
        if draw < self.drop_rate + self.violation_rate:
            self.violations += 1
            body["violation"] = "cpu_time"
            body["exit_code"] = -1
        return body


async def test_attribution_exact_under_injected_faults(tmp_path):
    executor = make_executor(tmp_path)
    wire = SeededWire(executor, CHAOS_SEED)
    requests = 24
    try:
        outcomes = await asyncio.gather(
            *(
                executor.execute(f"print({i})", tenant="chaos-tenant")
                for i in range(requests)
            ),
            return_exceptions=True,
        )
        row = executor.usage.snapshot()["tenants"]["chaos-tenant"]
        # Request count exact: one per logical request, regardless of how
        # many retry attempts each burned.
        assert row["requests"] == requests
        assert sum(row["outcomes"].values()) == requests
        # Every returned body billed exactly its reported device-op time;
        # faulted attempts add wall-measured time ON TOP (never free).
        assert row["device_op_seconds"] >= wire.reported_device_op
        if wire.faulted_attempts:
            assert row["device_op_seconds"] > wire.reported_device_op
        # The wall-clock surcharge for faulted attempts is bounded: a fake
        # wire faults in microseconds, so the overshoot stays far below
        # one real op's worth per faulted attempt.
        assert row["device_op_seconds"] < wire.reported_device_op + 0.05 * (
            wire.faulted_attempts + 1
        )
        # CPU lane: chips factor 1, so chip == device_op.
        assert row["chip_seconds"] == pytest.approx(
            row["device_op_seconds"]
        )
        # Violations landed under their kind, exactly as many as the
        # seeded plan produced (violation bodies are never retried).
        violation_outcomes = [
            o for o in outcomes if isinstance(o, LimitExceededError)
        ]
        assert row["violations"].get("cpu_time", 0) == len(
            violation_outcomes
        )
        assert row["outcomes"].get("limit_violation", 0) == len(
            violation_outcomes
        )
    finally:
        await executor.close()


async def test_chaos_totals_survive_journal_round_trip(tmp_path):
    """The durable half under chaos: flush mid-storm, reload a fresh
    ledger from the same dir, byte-exact totals."""
    executor = make_executor(tmp_path)
    SeededWire(executor, CHAOS_SEED + 1)
    try:
        await asyncio.gather(
            *(
                executor.execute(f"print({i})", tenant="chaos-tenant")
                for i in range(12)
            ),
            return_exceptions=True,
        )
        before = executor.usage.snapshot()["tenants"]
        assert executor.usage.flush() > 0
        restored = UsageLedger(executor.config)
        assert restored.snapshot()["tenants"] == before
    finally:
        await executor.close()


async def test_faulted_batch_dispatch_never_free_never_double_counts(
    tmp_path,
):
    """Batched chaos: the fused wire faults on a seeded draw; jobs rerun
    serially. Every job still counts exactly once, and the tenant is
    billed for BOTH the faulted fused attempt (wall-measured) and the
    serial reruns (reported) — chips really ran twice."""
    executor = make_executor(
        tmp_path,
        batching_enabled=True,
        batch_window_ms=20.0,
        batch_max_jobs=4,
    )
    rng = random.Random(CHAOS_SEED)
    serial_wire = SeededWire(executor, CHAOS_SEED + 2, drop_rate=0.0,
                             violation_rate=0.0)

    batch_attempts = []

    async def chaotic_batch(client, base, payload, timeout, sandbox):
        batch_attempts.append(len(payload["jobs"]))
        if rng.random() < 0.5:
            raise ExecutorError("chaos: batch wire dropped")
        n = len(payload["jobs"])
        return {
            "results": [
                {
                    "workdir": f".batch-1/job-{i}",
                    "stdout": f"j{i}\n",
                    "stderr": "",
                    "exit_code": 0,
                    "files": [],
                    "duration_s": 0.1,
                    "device_op_seconds": 0.1,
                    "start_offset_s": 0.0,
                }
                for i in range(n)
            ],
            "warm": True,
            "runner_restarted": False,
            "device_op_seconds": 0.1,
        }

    executor._post_execute_batch = chaotic_batch
    try:
        for _round in range(3):
            results = await asyncio.gather(
                *(
                    executor.execute(
                        f"print({i})", chip_count=4, tenant="chaos-tenant"
                    )
                    for i in range(4)
                )
            )
            assert all(r.exit_code == 0 for r in results)
        row = executor.usage.snapshot()["tenants"]["chaos-tenant"]
        assert row["requests"] == 12
        assert row["outcomes"] == {"ok": 12.0}
        # Every fused attempt that returned a body billed 0.1s x 4 chips;
        # serial reruns billed their own reported ops; faulted fused
        # attempts billed wall > 0. Nothing is free:
        assert row["chip_seconds"] > 0
        # And job counts never double: batch_jobs counts only jobs that
        # actually rode a SUCCESSFUL fused dispatch.
        fused_ok_jobs = row["batch_jobs"]
        serial_reruns = serial_wire.reported_device_op  # serial ops ran
        if fused_ok_jobs < 12:
            assert serial_reruns > 0  # the fallback really did the work
    finally:
        await executor.close()
