"""Test harness config.

- Forces JAX onto a virtual 8-device CPU mesh so all sharding/collective logic
  is exercised without TPU hardware (the driver separately dry-runs the
  multi-chip path via __graft_entry__.dryrun_multichip).
- Provides a minimal async-test runner (pytest-asyncio is not available in
  this environment): any ``async def test_*`` is run via asyncio.run().
"""

import asyncio
import inspect
import os
import sys
from pathlib import Path

# Must happen before anything imports jax. Force (not default) CPU: the host
# machine may pin JAX_PLATFORMS to a TPU plugin platform, but tests need the
# virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
# The TPU plugin's own sitecustomize may have already pinned the platform via
# jax.config (which beats the env var) — override it back, and strip the
# plugin's trigger env so sandbox subprocesses spawned by e2e tests also run
# on CPU.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Sandboxes inherit this process's env: keep the executor's cooperative-
# cancellation grace short so forced-kill timeout tests don't idle for the
# 20 s production default.
os.environ.setdefault("APP_RUNNER_INTERRUPT_GRACE_S", "2")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests via asyncio.run, driving async-generator
    fixtures (which plugin-less pytest passes through unresolved) in the same
    event loop as the test."""
    fn = pyfuncitem.obj
    if not inspect.iscoroutinefunction(fn):
        return None

    async def run():
        import contextlib

        kwargs = {}
        cleanups = []
        for name in pyfuncitem._fixtureinfo.argnames:
            value = pyfuncitem.funcargs[name]
            if inspect.isasyncgen(value):
                kwargs[name] = await value.__anext__()
                cleanups.append(value)
            elif inspect.iscoroutine(value):
                kwargs[name] = await value
            else:
                kwargs[name] = value
        try:
            await fn(**kwargs)
        finally:
            for gen in reversed(cleanups):
                with contextlib.suppress(StopAsyncIteration):
                    await gen.__anext__()

    asyncio.run(run())
    return True


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled-program state at every module boundary.

    XLA's CPU backend segfaults INSIDE backend_compile after the suite
    accumulates several hundred live compiled programs (observed
    deterministically at tests/unit scale in round 5, same class as the
    round-4 note in test_serving.py: fine standalone, crashes at suite
    position — an upstream compiler fragility, not a model bug). Modules
    share almost no compiled programs (each has its own tiny-config
    fixtures), so clearing between modules costs little and keeps the
    accumulation bounded."""
    yield
    jax.clear_caches()


@pytest.fixture
def tmp_storage(tmp_path):
    from bee_code_interpreter_fs_tpu.services.storage import Storage

    return Storage(tmp_path / "storage")


def pytest_sessionfinish(session, exitstatus):
    """CI post-mortem for seeded chaos legs: when CHAOS_TRACE_EXPORT names a
    path and the run FAILED, dump the tracing flight recorder (every span
    any tracer exported this process, bounded ring) as JSONL so the workflow
    can upload it as an artifact — a red seed is then diagnosable without
    re-running locally."""
    path = os.environ.get("CHAOS_TRACE_EXPORT")
    if not path or exitstatus == 0:
        return
    try:
        from bee_code_interpreter_fs_tpu.utils.tracing import GLOBAL_RING

        Path(path).write_text(GLOBAL_RING.export_jsonl())
        print(f"\n[chaos] exported {len(GLOBAL_RING)} trace spans to {path}")
    except Exception as error:  # noqa: BLE001 — diagnostics must not mask the failure
        print(f"\n[chaos] trace export failed: {error}")
