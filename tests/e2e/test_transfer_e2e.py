"""End-to-end workspace-sync tests through the real local backend + C++
executor: delta uploads across session turns, hash-negotiated downloads,
and the old-binary fallback (the same binary in APP_WORKSPACE_MANIFEST=0
legacy mode) passing the execute/session flows with full transfers.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")

import asyncio

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage


def _make_stack(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


@pytest.fixture
async def stack(tmp_path):
    executor = _make_stack(tmp_path)
    yield executor
    await executor.close()


@pytest.fixture
async def legacy_stack(tmp_path, monkeypatch):
    """The same stack against a sandbox server in legacy wire mode — a
    stand-in for an old executor binary without manifest endpoints."""
    monkeypatch.setenv("APP_WORKSPACE_MANIFEST", "0")
    executor = _make_stack(tmp_path)
    yield executor
    await executor.close()


async def test_session_unchanged_files_move_no_bytes(stack):
    executor = stack
    payload = b"A" * 4096
    object_id = await executor.storage.write(payload)
    files = {"/workspace/input.bin": object_id}

    first = await executor.execute(
        "print(len(open('input.bin','rb').read()))",
        files=files,
        executor_id="xfer-sess",
    )
    assert first.exit_code == 0, first.stderr
    assert first.stdout.strip() == "4096"
    # Cold turn: everything moved, nothing skipped.
    assert first.phases["upload_bytes"] == float(len(payload))
    assert first.phases["upload_skipped_bytes"] == 0.0

    second = await executor.execute(
        "print(len(open('input.bin','rb').read()))",
        files=files,
        executor_id="xfer-sess",
    )
    assert second.exit_code == 0, second.stderr
    assert second.stdout.strip() == "4096"
    # Unchanged turn: the manifest delta moved nothing.
    assert second.phases["upload_bytes"] == 0.0
    assert second.phases["upload_skipped_bytes"] == float(len(payload))


async def test_download_negotiated_away_for_known_content(stack):
    executor = stack
    payload = b"round-trip me"
    object_id = await executor.storage.write(payload)
    result = await executor.execute(
        "open('copy.bin','wb').write(open('orig.bin','rb').read())",
        files={"/workspace/orig.bin": object_id},
        executor_id="xfer-dl",
    )
    assert result.exit_code == 0, result.stderr
    # The new file's bytes equal the input already in content-addressed
    # storage: the sha matched and no bytes came back over the wire.
    assert result.files["/workspace/copy.bin"] == object_id
    assert result.phases["download_bytes"] == 0.0
    assert result.phases["download_skipped_bytes"] == float(len(payload))


async def test_novel_output_still_downloads(stack):
    executor = stack
    result = await executor.execute(
        "open('novel.txt','w').write('fresh output')", executor_id="xfer-novel"
    )
    assert result.exit_code == 0, result.stderr
    object_id = result.files["/workspace/novel.txt"]
    assert await executor.storage.read(object_id) == b"fresh output"
    assert result.phases["download_bytes"] == float(len(b"fresh output"))
    assert result.phases["download_skipped_bytes"] == 0.0


async def test_transfer_metrics_move_on_skip(stack):
    executor = stack
    object_id = await executor.storage.write(b"metrics payload")
    files = {"/workspace/m.bin": object_id}
    await executor.execute("pass", files=files, executor_id="xfer-metrics")
    await executor.execute("pass", files=files, executor_id="xfer-metrics")
    rendered = executor.metrics.registry.render()
    assert (
        'code_interpreter_transfer_skipped_bytes_total{direction="upload"} 15'
        in rendered
    )


# ------------------------------------------------------------ legacy binary


async def test_legacy_binary_execute_and_session_roundtrip(legacy_stack):
    """The full execute/session flow against a manifest-less executor: the
    control plane detects the legacy host from its first response and runs
    the classic full-transfer path — correct results, zero skips."""
    executor = legacy_stack
    payload = b"legacy payload"
    object_id = await executor.storage.write(payload)
    files = {"/workspace/in.txt": object_id}

    first = await executor.execute(
        "open('out.txt','w').write(open('in.txt').read().upper())",
        files=files,
        executor_id="legacy-sess",
    )
    assert first.exit_code == 0, first.stderr
    out_id = first.files["/workspace/out.txt"]
    assert await executor.storage.read(out_id) == b"LEGACY PAYLOAD"

    second = await executor.execute(
        "print(open('in.txt').read())", files=files, executor_id="legacy-sess"
    )
    assert second.exit_code == 0, second.stderr
    assert second.stdout.strip() == "legacy payload"
    assert second.session_seq == 2
    # Fallback = full transfers: nothing is ever skipped.
    assert first.phases["upload_skipped_bytes"] == 0.0
    assert second.phases["upload_skipped_bytes"] == 0.0
    assert first.phases["download_skipped_bytes"] == 0.0


async def test_legacy_binary_stateless_roundtrip(legacy_stack):
    executor = legacy_stack
    result = await executor.execute("open('made.txt','w').write('plain')")
    assert result.exit_code == 0, result.stderr
    object_id = result.files["/workspace/made.txt"]
    assert await executor.storage.read(object_id) == b"plain"
    assert result.phases["download_skipped_bytes"] == 0.0
