"""End-to-end result-memo tests through the real local backend + C++
executor: the acceptance criterion verbatim — a repeated pure run serves
from the memo with ZERO sandbox HTTP and zero chip-seconds on the usage
ledger, byte-identical to its live execution (stdout, stderr, exit code,
output files) — plus the real executor's purity echo (the C++
`result_sha256` block verifying against the control plane's own
derivation), tenant isolation, kill-switch parity, and the X-Memo /
`pure` wire surface over the aiohttp server.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
aiohttp = pytest.importorskip(
    "aiohttp", reason="optional e2e dependency not installed"
)

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import (
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage


def _make_stack(tmp_path, **config_kwargs):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
        **config_kwargs,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


@pytest.fixture
async def stack(tmp_path):
    executor = _make_stack(tmp_path)
    yield executor
    await executor.close()


def _count_sandbox_http(executor):
    """Arm a request counter on the executor's live sandbox HTTP client —
    every wire round-trip to any sandbox host from now on increments it."""
    count = {"n": 0}

    async def tick(request):
        count["n"] += 1

    executor._http_client().event_hooks["request"].append(tick)
    return count


def _chip_seconds(executor, tenant="shared"):
    row = executor.usage.snapshot()["tenants"].get(tenant)
    return row["chip_seconds"] if row else 0.0


def _requests_billed(executor, tenant="shared"):
    row = executor.usage.snapshot()["tenants"].get(tenant)
    return row["requests"] if row else 0


async def test_repeat_pure_run_zero_sandbox_http_zero_chip_seconds(stack):
    """The BENCH_memo acceptance criterion, test flavor."""
    executor = stack
    source = "print(sum(range(100)))\nopen('out.txt','w').write('made')"

    live = await executor.execute(source, pure=True)
    assert live.exit_code == 0, live.stderr
    assert live.stdout.strip() == "4950"
    assert live.phases["memo"] == {"state": "miss", "recorded": "admitted"}
    # The real C++ executor echoed the purity block and its hash verified
    # (a record only admits through _verified_pure_echo).
    assert executor.result_memo.entry_count() == 1

    chip_before = _chip_seconds(executor)
    requests_before = _requests_billed(executor)
    wire = _count_sandbox_http(executor)

    cached = await executor.execute(source, pure=True)
    # Zero sandbox HTTP...
    assert wire["n"] == 0
    # ...zero chip-seconds on the ledger (but the request IS counted)...
    assert _chip_seconds(executor) == chip_before
    assert _requests_billed(executor) == requests_before + 1
    assert cached.phases["chip_seconds"] == 0.0
    assert cached.phases["device_op_seconds"] == 0.0
    # ...and byte-identical output, files included.
    assert cached.phases["memo"]["state"] == "hit"
    assert cached.stdout == live.stdout
    assert cached.stderr == live.stderr
    assert cached.exit_code == live.exit_code
    assert cached.files == live.files
    assert (
        await executor.storage.read(cached.files["/workspace/out.txt"])
        == b"made"
    )


async def test_stderr_and_nonzero_exit_memoize_too(stack):
    """A deterministic user error is as pure as a success: the memo serves
    the same failure without burning a sandbox on it again."""
    executor = stack
    source = "import sys\nsys.stderr.write('deterministic boom\\n')\nsys.exit(3)"
    live = await executor.execute(source, pure=True)
    assert live.exit_code == 3
    assert "deterministic boom" in live.stderr
    wire = _count_sandbox_http(executor)
    cached = await executor.execute(source, pure=True)
    assert wire["n"] == 0
    assert cached.exit_code == 3
    assert cached.stderr == live.stderr
    assert cached.phases["memo"]["state"] == "hit"


async def test_tenants_never_share_records_e2e(stack):
    executor = stack
    source = "print('isolated')"
    first = await executor.execute(source, pure=True, tenant="tenant-a")
    assert first.phases["memo"]["state"] == "miss"
    other = await executor.execute(source, pure=True, tenant="tenant-b")
    # Identical inputs, different tenant: a real re-execution.
    assert other.phases["memo"]["state"] == "miss"
    same = await executor.execute(source, pure=True, tenant="tenant-a")
    assert same.phases["memo"]["state"] == "hit"


async def test_input_files_key_the_record(stack):
    executor = stack
    a = await executor.storage.write(b"alpha")
    b = await executor.storage.write(b"bravo")
    source = "print(open('in.txt').read())"
    first = await executor.execute(
        source, files={"/workspace/in.txt": a}, pure=True
    )
    assert first.stdout.strip() == "alpha"
    changed = await executor.execute(
        source, files={"/workspace/in.txt": b}, pure=True
    )
    # Different input bytes -> different key -> a live run, not the record.
    assert changed.phases["memo"]["state"] == "miss"
    assert changed.stdout.strip() == "bravo"
    repeat = await executor.execute(
        source, files={"/workspace/in.txt": a}, pure=True
    )
    assert repeat.phases["memo"]["state"] == "hit"
    assert repeat.stdout.strip() == "alpha"


async def test_kill_switch_parity_e2e(tmp_path):
    executor = _make_stack(tmp_path, result_memo_enabled=False)
    try:
        for _ in range(2):
            result = await executor.execute("print('off')", pure=True)
            assert result.exit_code == 0, result.stderr
            assert "memo" not in result.phases
        assert executor.result_memo.entry_count() == 0
        assert not (tmp_path / "storage" / ".result-memo").exists()
    finally:
        await executor.close()


# ------------------------------------------------------------ HTTP surface


async def test_http_pure_field_and_x_memo_header(tmp_path):
    executor = _make_stack(tmp_path)
    app = create_http_app(
        executor, CustomToolExecutor(executor), executor.storage
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        body = {"source_code": "print('over http')", "pure": True}
        first = await client.post("/v1/execute", json=body)
        assert first.status == 200
        assert first.headers.get("X-Memo") == "miss"
        first_body = await first.json()

        second = await client.post("/v1/execute", json=body)
        assert second.status == 200
        assert second.headers.get("X-Memo") == "hit"
        second_body = await second.json()
        assert second_body["stdout"] == first_body["stdout"]
        assert second_body["exit_code"] == first_body["exit_code"]

        # Undeclared requests carry no memo surface at all.
        plain = await client.post(
            "/v1/execute", json={"source_code": "print('plain')"}
        )
        assert plain.status == 200
        assert "X-Memo" not in plain.headers
    finally:
        await client.close()
        await executor.close()
