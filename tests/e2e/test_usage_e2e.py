"""End-to-end usage metering against the real local backend + C++
executor — the acceptance criterion verbatim: two tenants run a mixed
workload (serial + batched + one violation + one session); `GET /usage`
chip-seconds per tenant agree with the executor-reported device-op time
within 5%; the batched jobs' total equals the fused dispatch's
chip-seconds (no double-billing, no loss); the violating request is billed
AND counted under its violation kind; and after a control-plane restart
the journal restores every counter to within one flush interval.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

import asyncio  # noqa: E402

from aiohttp.test_utils import TestClient, TestServer  # noqa: E402

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
    LimitExceededError,
)
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (  # noqa: E402
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.http_server import (  # noqa: E402
    create_http_app,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402
from bee_code_interpreter_fs_tpu.services.usage import UsageLedger  # noqa: E402

BATCH_LANE = 4
BATCH_JOBS = 4


def make_config(tmp_path, **overrides):
    defaults = dict(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        compile_cache_prewarm=False,
        default_execution_timeout=30.0,
        # Lane 4 stays single-host (the fused driver runs on one host's
        # runner), and a full 4-job batch fires immediately — the window
        # only bounds the wait for stragglers.
        tpu_chips_per_host=BATCH_LANE,
        batch_max_jobs=BATCH_JOBS,
        batch_window_ms=2000.0,
        usage_flush_interval=0.5,
    )
    defaults.update(overrides)
    return Config(**defaults)


@pytest.fixture
async def stack(tmp_path, monkeypatch):
    # Tight watchdog cadence so the violation leg resolves fast.
    monkeypatch.setenv("APP_LIMIT_POLL_INTERVAL", "0.05")
    config = make_config(tmp_path)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    app = create_http_app(executor, CustomToolExecutor(executor), storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    yield client, executor, config
    await client.close()
    await executor.close()


async def _settle(executor):
    for _ in range(400):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


async def _warm_lane(executor, lane):
    """One untimed run until the lane's recycled sandbox reports warm —
    /execute-batch requires a warm runner (409 otherwise), and a cold
    first dispatch falling back serially would fail the fused-path
    assertion on timing, not substance."""
    for _ in range(30):
        result = await executor.execute("print('warm-up')", chip_count=lane)
        assert result.exit_code == 0, result.stderr
        if result.warm:
            return
        await asyncio.sleep(0.2)
    pytest.fail("lane never produced a warm runner")


def _chip(executor, tenant):
    row = executor.usage.snapshot()["tenants"].get(tenant)
    return row["chip_seconds"] if row else 0.0


async def test_two_tenant_mixed_workload_accounting(stack):
    client, executor, config = stack
    reported = {"tenant-a": 0.0, "tenant-b": 0.0}

    def record(result, tenant):
        assert result.exit_code == 0, result.stderr
        # chip_seconds in phases IS the executor-reported device-op time
        # times the chip factor (for batched jobs: the apportioned share).
        reported[tenant] += result.phases["chip_seconds"]
        return result

    # --- tenant-a: serial ------------------------------------------------
    for i in range(2):
        record(
            await executor.execute(f"print({i})", tenant="tenant-a"),
            "tenant-a",
        )

    # --- tenant-a: one batched window of 4 jobs on the 4-chip lane -------
    await _warm_lane(executor, BATCH_LANE)
    await _settle(executor)
    chip_before_batch = _chip(executor, "tenant-a")
    reported_before_batch = reported["tenant-a"]
    results = await asyncio.gather(
        *(
            executor.execute(
                f"print('job', {i})",
                chip_count=BATCH_LANE,
                tenant="tenant-a",
            )
            for i in range(BATCH_JOBS)
        )
    )
    for result in results:
        record(result, "tenant-a")
        # Provably on the fused path — a silent serial fallback would make
        # the batch-equality assertion below vacuous.
        assert result.phases.get("batch_jobs") == float(BATCH_JOBS)
    await _settle(executor)
    # The batched jobs' apportioned total equals the fused dispatch's
    # chip-seconds the ledger billed: no double-billing, no loss. The
    # phases fields round each share to 6 decimals, so the summed shares
    # may differ from the (unrounded) ledger total by up to
    # BATCH_JOBS x 5e-7 — the tolerance covers exactly that, nothing more.
    batch_ledger_delta = _chip(executor, "tenant-a") - chip_before_batch
    batch_phase_total = reported["tenant-a"] - reported_before_batch
    assert batch_ledger_delta == pytest.approx(
        batch_phase_total, abs=BATCH_JOBS * 5e-7 + 1e-6
    )

    # --- tenant-a: a session (two turns, one sandbox) ---------------------
    record(
        await executor.execute(
            "open('state.txt', 'w').write('41')",
            executor_id="sess-a",
            tenant="tenant-a",
        ),
        "tenant-a",
    )
    second_turn = record(
        await executor.execute(
            "print(int(open('state.txt').read()) + 1)",
            executor_id="sess-a",
            tenant="tenant-a",
        ),
        "tenant-a",
    )
    assert second_turn.stdout.strip() == "42"  # the session really held
    await executor.close_session("sess-a")

    # --- tenant-a: one violation (billed AND counted) ---------------------
    chip_before_violation = _chip(executor, "tenant-a")
    with pytest.raises(LimitExceededError) as excinfo:
        await executor.execute(
            "while True: print('y' * 65536)\n",
            tenant="tenant-a",
            timeout=15,
            limits={"output_bytes": 1 << 20},
        )
    assert excinfo.value.kind == "output_cap"
    assert _chip(executor, "tenant-a") > chip_before_violation

    # --- tenant-b: serial only -------------------------------------------
    for i in range(2):
        record(
            await executor.execute(f"print('b', {i})", tenant="tenant-b"),
            "tenant-b",
        )
    await _settle(executor)

    # --- GET /usage agrees with executor-reported device-op time ----------
    resp = await client.get("/usage")
    assert resp.status == 200
    body = await resp.json()
    tenants = body["tenants"]
    # tenant-b ran only clean serial requests: ledger == sum of the
    # executor-reported attribution, within the acceptance 5%.
    assert tenants["tenant-b"]["chip_seconds"] == pytest.approx(
        reported["tenant-b"], rel=0.05
    )
    # tenant-a's ledger additionally holds the violating request's billed
    # device time (not client-visible in phases — the request 422'd).
    assert tenants["tenant-a"]["chip_seconds"] >= reported["tenant-a"]
    assert tenants["tenant-a"]["violations"] == {"output_cap": 1.0}
    assert tenants["tenant-a"]["outcomes"]["limit_violation"] == 1.0
    assert tenants["tenant-a"]["batch_jobs"] == BATCH_JOBS
    assert tenants["tenant-a"]["requests"] == 2 + BATCH_JOBS + 2 + 1
    # Isolation: tenant-b shows none of tenant-a's workload classes.
    assert tenants["tenant-b"]["batch_jobs"] == 0
    assert tenants["tenant-b"]["violations"] == {}
    assert tenants["tenant-b"]["requests"] == 2
    # Per-tenant route.
    resp = await client.get("/usage/tenant-a")
    assert resp.status == 200
    one = await resp.json()
    assert one["usage"] == tenants["tenant-a"]

    # --- restart: the journal restores every counter -----------------------
    assert executor.usage.flush() >= 0
    restored = UsageLedger(config)
    restored_tenants = restored.snapshot()["tenants"]
    # A clean flush means exact restoration (the one-flush-interval bound
    # is for crashes; the SIGKILL leg lives in test_usage_journal.py).
    assert restored_tenants == tenants


async def test_usage_kill_switch_end_to_end(tmp_path):
    config = make_config(tmp_path, usage_metering_enabled=False)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    app = create_http_app(executor, CustomToolExecutor(executor), storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        result = await executor.execute("print(1)", tenant="tenant-a")
        assert result.exit_code == 0
        # Pre-metering behavior byte-for-byte: no attribution fields, no
        # /usage surface, no journal on disk.
        assert "chip_seconds" not in result.phases
        resp = await client.get("/usage")
        assert resp.status == 404
        assert not (tmp_path / "storage" / ".usage").exists()
    finally:
        await client.close()
        await executor.close()
