"""End-to-end through the Kubernetes backend against a fake cluster.

The fake kubectl here doesn't just play back JSON — ``create`` actually
starts the real C++ executor server bound to a distinct loopback IP
(127.0.1.N:8000, standing in for the pod IP), ``get`` reports that IP as
``status.podIP``, and ``delete`` kills the process. So this exercises the
complete production path — orchestrator → KubernetesSandboxBackend →
kubectl → (fake) pod → real executor HTTP server → runner → result — with
zero mocks between the backend and the sandbox runtime.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")

import json
import stat
from pathlib import Path


from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.kubernetes import (
    KubernetesSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.kubectl import Kubectl
from bee_code_interpreter_fs_tpu.services.storage import Storage

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
EXECUTOR_BINARY = REPO_ROOT / "executor" / "build" / "executor-server"

FAKE_CLUSTER_KUBECTL = r"""#!/usr/bin/env python3
import json, os, signal, subprocess, sys
state = os.environ["FAKE_CLUSTER_DIR"]
stdin = sys.stdin.read() if not sys.stdin.isatty() else ""
args = sys.argv[1:]
verb = args[0] if args else ""

def pod_path(name):
    return os.path.join(state, name + ".json")

if verb == "create":
    manifest = json.loads(stdin)
    name = manifest["metadata"]["name"]
    counter_file = os.path.join(state, "counter")
    n = int(open(counter_file).read()) + 1 if os.path.exists(counter_file) else 2
    open(counter_file, "w").write(str(n))
    ip = "127.0.1.%d" % n
    env = dict(os.environ)
    for item in manifest["spec"]["containers"][0].get("env", []):
        env[item["name"]] = item["value"]
    env["APP_LISTEN_ADDR"] = ip + ":8000"
    env["APP_WORKSPACE"] = os.path.join(state, name, "workspace")
    env["APP_RUNTIME_PACKAGES"] = os.path.join(state, name, "runtime-packages")
    env["APP_PYTHON"] = sys.executable
    # A real pod's manifest wipes its CONTAINER-private /tmp and ~/.local at
    # generation reset; this fake pod is a host process, so point those at
    # per-pod directories — wiping the host's /tmp would destroy the test
    # harness itself (and anything else running on the machine).
    env["TMPDIR"] = os.path.join(state, name, "tmp")
    env["HOME"] = os.path.join(state, name, "home")
    env["APP_RESET_EXTRA_WIPE_DIRS"] = env["TMPDIR"] + ":~/.local"
    os.makedirs(env["APP_WORKSPACE"]); os.makedirs(env["APP_RUNTIME_PACKAGES"])
    os.makedirs(env["TMPDIR"]); os.makedirs(env["HOME"])
    proc = subprocess.Popen([os.environ["FAKE_EXECUTOR_BINARY"]], env=env,
                            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                            start_new_session=True)
    manifest["status"] = {"podIP": ip}
    manifest["metadata"]["uid"] = "uid-" + name
    manifest["pid"] = proc.pid
    with open(pod_path(name), "w") as f:
        json.dump(manifest, f)
    print(json.dumps(manifest))
elif verb == "get":
    name = args[2] if len(args) > 2 and not args[2].startswith("-") else None
    if name and os.path.exists(pod_path(name)):
        print(open(pod_path(name)).read())
    else:
        sys.stderr.write("NotFound\n"); sys.exit(1)
elif verb == "wait":
    # Real k8s Ready tracks the readinessProbe on /healthz; emulate by
    # polling until the executor actually listens.
    import time, urllib.request
    name = args[1].split("/", 1)[1]
    timeout = 60.0
    for a in args:
        if a.startswith("--timeout="):
            timeout = float(a.split("=", 1)[1].rstrip("s"))
    if not os.path.exists(pod_path(name)):
        sys.stderr.write("NotFound\n"); sys.exit(1)
    ip = json.load(open(pod_path(name)))["status"]["podIP"]
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            urllib.request.urlopen("http://%s:8000/healthz" % ip, timeout=2)
            print("condition met"); sys.exit(0)
        except Exception:
            time.sleep(0.3)
    sys.stderr.write("timed out waiting for the condition\n"); sys.exit(1)
elif verb == "delete":
    # Emulate kubelet: SIGTERM to the container's PID 1 (the server reaps its
    # runner session in its handler), escalate to SIGKILL after a grace.
    import time
    name = args[2]
    if os.path.exists(pod_path(name)):
        manifest = json.load(open(pod_path(name)))
        pid = manifest["pid"]
        try:
            os.kill(pid, signal.SIGTERM)
            for _ in range(40):
                time.sleep(0.05)
                os.kill(pid, 0)  # raises once the process is gone
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        os.unlink(pod_path(name))
    print("deleted")
else:
    sys.exit(2)
"""


@pytest.fixture
async def k8s_executor(tmp_path, monkeypatch):
    if not EXECUTOR_BINARY.exists():
        pytest.skip("executor binary not built; run `make -C executor`")
    state = tmp_path / "cluster"
    state.mkdir()
    binary = tmp_path / "kubectl"
    binary.write_text(FAKE_CLUSTER_KUBECTL)
    binary.chmod(binary.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("FAKE_CLUSTER_DIR", str(state))
    monkeypatch.setenv("FAKE_EXECUTOR_BINARY", str(EXECUTOR_BINARY))
    monkeypatch.delenv("HOSTNAME", raising=False)
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        executor_pod_queue_target_length=1,
        executor_pod_ready_timeout=90,
        jax_compilation_cache_dir="",
    )
    backend = KubernetesSandboxBackend(
        config, kubectl=Kubectl(binary=str(binary)), numpy_dispatch=False
    )
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    yield executor, state
    await executor.close()
    await backend.close()


async def test_execute_through_fake_cluster(k8s_executor):
    executor, state = k8s_executor
    result = await executor.execute(source_code="print(21 * 2)")
    assert result.exit_code == 0
    assert result.stdout == "42\n"


async def test_file_roundtrip_through_fake_cluster(k8s_executor):
    executor, state = k8s_executor
    result = await executor.execute(
        source_code="open('out.txt', 'w').write('hello from the pod')"
    )
    assert result.exit_code == 0
    assert "/workspace/out.txt" in result.files
    object_id = result.files["/workspace/out.txt"]
    second = await executor.execute(
        source_code="print(open('out.txt').read())",
        files={"/workspace/out.txt": object_id},
    )
    assert second.exit_code == 0
    assert second.stdout == "hello from the pod\n"


async def test_pods_are_single_use(k8s_executor):
    executor, state = k8s_executor
    import asyncio

    await executor.execute(source_code="x = 1")
    await executor.execute(source_code="print('second')")
    # Used pods get deleted off the hot path; drain the in-flight disposals
    # (and the refill they race) before counting what's actually left.
    await asyncio.gather(*executor._dispose_tasks, return_exceptions=True)
    await asyncio.gather(*executor._fill_tasks, return_exceptions=True)
    live = [p for p in state.glob("*.json")]
    assert len(live) <= executor.config.executor_pod_queue_target_length + 1
