"""End-to-end wedge-recovery acceptance (ISSUE 13): HTTP API → orchestrator
→ real C++ executors (local backend) with a seeded attach-hang wedging ONE
host, and the detect→act loop closed.

The acceptance criterion, verbatim: with seeded ``attach_hang`` wedging one
host under concurrent load, the probe's wedged verdict automatically drains
and disposes the host, a replacement spawns, the lane serves throughout
(other hosts unaffected), a stale-generation claim against the fenced chips
is rejected with the typed 409 and never wedges the successor, and the
recovering host re-admits only after the configured clean-probe streak —
all with zero manual intervention.
"""

import asyncio
import time

import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

import httpx
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.device_health import DeviceHealthProbe
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage

WEDGED_LANE = 2
READMIT_STREAK = 2


@pytest.fixture
async def stack(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
        # Wedge exactly ONE host of the doomed lane (rate 1.0 +
        # attach_hang_max:1): the dispose-and-replace successor comes up
        # clean, so re-admission is reachable in test time.
        executor_fault_spec=(
            f"attach_hang:1.0,attach_hang_lane:{WEDGED_LANE},"
            f"attach_hang_max:1,seed:7"
        ),
        device_probe_interval=0.05,
        device_probe_timeout=5.0,
        device_probe_attach_budget=0.3,
        device_probe_op_grace=5.0,
        device_probe_wedge_after=0.3,
        device_probe_readmit_streak=READMIT_STREAK,
    )
    backend = FaultInjectingBackend(
        LocalSandboxBackend(config, warm_import_jax=False),
        FaultSpec.parse(config.executor_fault_spec),
    )
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    probe = DeviceHealthProbe(executor)
    executor.device_health = probe
    app = create_http_app(executor, CustomToolExecutor(executor), storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    yield client, executor, probe
    await probe.stop()
    await client.close()
    await executor.close()


async def _execute_ok(client, lane: int, marker: str) -> dict:
    resp = await client.post(
        "/v1/execute",
        json={"source_code": f"print({marker!r})", "chip_count": lane},
    )
    assert resp.status == 200, await resp.text()
    body = await resp.json()
    assert body["stdout"] == f"{marker}\n"
    return body


def _counter(executor, metric) -> dict:
    return {
        tuple(labels.values()): value for labels, value in metric.samples()
    }


async def test_wedge_recovery_end_to_end(stack):
    client, executor, probe = stack
    # Light up both lanes with real executor hosts.
    await _execute_ok(client, 0, "healthy lane up")
    await _execute_ok(client, WEDGED_LANE, "doomed lane up")
    doomed = next(
        sandbox
        for lane, sandbox in executor.live_hosts()
        if lane == WEDGED_LANE
    )
    old_lease = doomed.meta["lease"]
    assert old_lease is not None and not old_lease.revoked

    # Concurrent load on the healthy lane for the WHOLE recovery window.
    stop_load = asyncio.Event()
    load_results: list[int] = []

    async def pump_load() -> None:
        i = 0
        while not stop_load.is_set():
            resp = await client.post(
                "/v1/execute",
                json={"source_code": f"print({i})", "chip_count": 0},
            )
            load_results.append(resp.status)
            i += 1
            await asyncio.sleep(0.02)

    load = asyncio.create_task(pump_load())

    # Run the probe daemon for real: detection -> fence -> drain ->
    # dispose -> respawn, zero manual intervention.
    probe.start()
    deadline = time.monotonic() + 30.0
    replacement = None
    while time.monotonic() < deadline:
        if executor.live_sandbox(doomed.id) is None:
            replacement = next(
                (
                    sandbox
                    for lane, sandbox in executor.live_hosts()
                    if lane == WEDGED_LANE
                ),
                None,
            )
            if replacement is not None:
                break
        await asyncio.sleep(0.05)
    assert replacement is not None, "wedged host was not replaced in time"
    assert old_lease.revoked, "the wedged host's lease was not fenced"
    fences = _counter(executor, executor.metrics.device_fences)
    assert fences.get((str(WEDGED_LANE), "fenced"), 0) >= 1
    new_lease = replacement.meta["lease"]
    assert new_lease.generation > old_lease.generation

    # A stale-generation claim against the fenced chips: dispatched
    # STRAIGHT at the successor's executor, it is rejected with the typed
    # 409 before taking any lock — it can never wedge the successor.
    async with httpx.AsyncClient() as raw:
        resp = await raw.post(
            f"{replacement.url}/execute",
            json={"source_code": "print('stale claim')", "timeout": 5},
            headers={"x-lease-token": old_lease.wire_token},
        )
    assert resp.status_code == 409
    body = resp.json()
    assert body["error"] == "stale_lease"
    # The successor's valid token is never echoed to a stale claimant
    # (log-only) — a junk claim must not harvest the live credential.
    assert "held" not in body

    # Re-admission is gated on the clean-probe streak: wait for the scope
    # to re-admit (host_readmitted_total fires), then the lane serves.
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        readmits = _counter(executor, executor.metrics.host_readmitted)
        if readmits.get((str(WEDGED_LANE),), 0) >= 1:
            break
        await asyncio.sleep(0.05)
    else:
        pytest.fail("fenced scope never re-admitted")
    assert not executor.leases.recovering(old_lease.scope)
    await _execute_ok(client, WEDGED_LANE, "lane recovered")

    # The healthy lane served throughout: every load request succeeded.
    stop_load.set()
    await load
    assert load_results, "load pump never ran"
    assert all(status == 200 for status in load_results)

    # The operator surfaces tell the story: /statusz recovery block and
    # /healthz lane census.
    resp = await client.get("/statusz")
    statusz = await resp.json()
    assert statusz["recovery"]["fences_total"] >= 1
    assert statusz["recovery"]["readmissions_total"] >= 1
    resp = await client.get("/healthz")
    healthz = await resp.json()
    assert str(WEDGED_LANE) in healthz["lanes"]
