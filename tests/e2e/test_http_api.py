"""End-to-end tests: HTTP API → orchestrator pool → real C++ executor.

These run the full Execute stack with the local subprocess backend — the
cluster-free e2e coverage the reference could not do (its tests required a
live k8s deployment, SURVEY.md §4). Scenario parity with the reference's
test/e2e/test_http.py and test_grpc.py: stdlib execution, file create →
returned id → feed back → read in a second execution, custom tool parse /
execute / error propagation, plus our additions (timeout, phases, probes).
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage


@pytest.fixture
async def client(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    tools = CustomToolExecutor(executor)
    app = create_http_app(executor, tools, storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    yield client
    await client.close()
    await executor.close()


async def test_execute_hello(client):
    resp = await client.post("/v1/execute", json={"source_code": "print(21 * 2)"})
    assert resp.status == 200
    body = await resp.json()
    assert body["stdout"] == "42\n"
    assert body["exit_code"] == 0
    assert set(body["phases"]) >= {"queue_wait", "upload", "exec", "download"}


async def test_execute_validation(client):
    resp = await client.post("/v1/execute", json={})
    assert resp.status == 400
    resp = await client.post(
        "/v1/execute", json={"source_code": "x", "source_file": "/workspace/y"}
    )
    assert resp.status == 400
    resp = await client.post("/v1/execute", data=b"not json")
    assert resp.status == 400


async def test_file_roundtrip_through_executions(client):
    # execution 1 creates a file
    resp = await client.post(
        "/v1/execute",
        json={"source_code": "open('result.txt', 'w').write('persisted state')"},
    )
    body = await resp.json()
    assert body["exit_code"] == 0
    assert "/workspace/result.txt" in body["files"]
    object_id = body["files"]["/workspace/result.txt"]

    # execution 2 (a different sandbox) reads it back via the files map
    resp = await client.post(
        "/v1/execute",
        json={
            "source_code": "print(open('result.txt').read())",
            "files": {"/workspace/result.txt": object_id},
        },
    )
    body = await resp.json()
    assert body["stdout"] == "persisted state\n"


async def test_execute_source_file_flow(client):
    # upload source as a file object, then execute by path (the fork's flow)
    resp = await client.put("/v1/files", data=b"print('ran from file')")
    object_id = (await resp.json())["hash"]
    resp = await client.post(
        "/v1/execute",
        json={
            "source_file": "/workspace/prog.py",
            "files": {"/workspace/prog.py": object_id},
        },
    )
    body = await resp.json()
    assert body["exit_code"] == 0
    assert body["stdout"] == "ran from file\n"


async def test_files_crud(client):
    resp = await client.put("/v1/files", data=b"file body")
    assert resp.status == 200
    object_id = (await resp.json())["hash"]
    assert len(object_id) == 64

    resp = await client.get(f"/v1/files/{object_id}")
    assert resp.status == 200
    assert await resp.read() == b"file body"

    # delete-on-read
    resp = await client.get(f"/v1/files/{object_id}?delete=true")
    assert await resp.read() == b"file body"
    resp = await client.get(f"/v1/files/{object_id}")
    assert resp.status == 404

    resp = await client.delete(f"/v1/files/{object_id}")
    assert resp.status == 200

    resp = await client.get("/v1/files/not%2Fvalid")
    assert resp.status in (400, 404)


async def test_multipart_upload(client):
    import aiohttp

    form = aiohttp.FormData()
    form.add_field("file", b"multipart content", filename="f.bin")
    resp = await client.put("/v1/files", data=form)
    assert resp.status == 200
    object_id = (await resp.json())["hash"]
    resp = await client.get(f"/v1/files/{object_id}")
    assert await resp.read() == b"multipart content"


async def test_execute_timeout(client):
    resp = await client.post(
        "/v1/execute",
        json={"source_code": "while True: pass", "timeout": 1.5},
    )
    body = await resp.json()
    assert body["exit_code"] == -1
    assert "timed out" in body["stderr"]


async def test_execute_nonzero_exit(client):
    resp = await client.post(
        "/v1/execute", json={"source_code": "import sys; sys.exit(7)"}
    )
    body = await resp.json()
    assert body["exit_code"] == 7


async def test_parse_custom_tool(client):
    source = '''
import typing

def find_items(query: str, limit: int = 10, tags: typing.Optional[list[str]] = None) -> dict:
    """Search the catalog.

    :param query: free-text search query
    :param limit: maximum number of results
    :param tags: restrict to these tags
    :return: matching items
    """
    return {}
'''
    resp = await client.post("/v1/parse-custom-tool", json={"tool_source_code": source})
    assert resp.status == 200
    body = await resp.json()
    assert body["tool_name"] == "find_items"
    assert body["tool_description"] == (
        "Search the catalog.\n\nReturns: dict -- matching items"
    )
    schema = json.loads(body["tool_input_schema_json"])
    assert schema["required"] == ["query"]
    assert schema["properties"]["query"] == {
        "type": "string",
        "description": "free-text search query",
    }
    assert schema["properties"]["limit"]["type"] == "integer"
    assert schema["properties"]["tags"]["anyOf"][0] == {
        "type": "array",
        "items": {"type": "string"},
    }


async def test_parse_custom_tool_errors(client):
    resp = await client.post(
        "/v1/parse-custom-tool",
        json={"tool_source_code": "def f(*args): pass"},
    )
    assert resp.status == 400
    body = await resp.json()
    assert any("*args" in m for m in body["error_messages"])


async def test_execute_custom_tool(client):
    source = "def add(a: int, b: int) -> int:\n    return a + b"
    resp = await client.post(
        "/v1/execute-custom-tool",
        json={"tool_source_code": source, "tool_input_json": '{"a": 2, "b": 40}'},
    )
    assert resp.status == 200
    body = await resp.json()
    assert json.loads(body["tool_output_json"]) == 42


async def test_execute_custom_tool_suppresses_prints(client):
    source = (
        "def noisy(x: int) -> int:\n"
        "    print('debug chatter')\n"
        "    return x * 2"
    )
    resp = await client.post(
        "/v1/execute-custom-tool",
        json={"tool_source_code": source, "tool_input_json": '{"x": 21}'},
    )
    body = await resp.json()
    assert json.loads(body["tool_output_json"]) == 42


async def test_execute_custom_tool_error_propagates(client):
    source = "def boom(x: int) -> int:\n    return x / 0"
    resp = await client.post(
        "/v1/execute-custom-tool",
        json={"tool_source_code": source, "tool_input_json": '{"x": 1}'},
    )
    assert resp.status == 400
    body = await resp.json()
    assert "division by zero" in body["stderr"]


async def test_concurrent_executes(client):
    async def one(i: int):
        resp = await client.post(
            "/v1/execute", json={"source_code": f"print({i} * 10)"}
        )
        return (await resp.json())["stdout"]

    results = await asyncio.gather(*(one(i) for i in range(4)))
    assert results == [f"{i * 10}\n" for i in range(4)]


async def test_execute_stream_over_http(client):
    """POST /v1/execute/stream through the whole stack: NDJSON chunks while
    the code runs, then the full execute response as the final line."""
    import time as _time

    src = (
        "import time\n"
        "for i in range(3):\n"
        "    print('beat', i, flush=True)\n"
        "    time.sleep(0.4)\n"
    )
    t0 = _time.monotonic()
    events = []
    resp = await client.post("/v1/execute/stream", json={"source_code": src})
    assert resp.status == 200
    assert resp.headers["Content-Type"].startswith("application/x-ndjson")
    buf = ""
    async for chunk, _ in resp.content.iter_chunks():
        buf += chunk.decode()
        while "\n" in buf:
            line, buf = buf.split("\n", 1)
            if line.strip():
                events.append((_time.monotonic() - t0, json.loads(line)))
    chunks = [e for _, e in events if "stream" in e]
    assert chunks, "no chunks streamed"
    assert events[0][0] < 1.0, f"first chunk too late: {events[0][0]:.2f}s"
    final = events[-1][1]
    assert final["exit_code"] == 0
    assert final["stdout"] == "beat 0\nbeat 1\nbeat 2\n"
    assert "".join(
        c["data"] for c in chunks if c["stream"] == "stdout"
    ) == final["stdout"]

    # Pre-flight validation still uses plain statuses.
    resp = await client.post("/v1/execute/stream", json={})
    assert resp.status == 400
    # A workspace-escaping source_file is a client error (the sandbox's 403
    # maps to 400 on the streamed surface too, not a 502 infra error).
    resp = await client.post(
        "/v1/execute/stream",
        json={"source_file": "/workspace/../../etc/passwd"},
    )
    assert resp.status == 400


async def test_execute_stream_in_session(client):
    """Streaming composes with executor_id sessions: chunks stream AND the
    workspace persists to the next (non-streamed) request."""
    resp = await client.post(
        "/v1/execute/stream",
        json={
            "source_code": "print('streamed'); open('s2.txt','w').write('x')",
            "executor_id": "stream-sess",
        },
    )
    assert resp.status == 200
    lines = [
        json.loads(l)
        for l in (await resp.text()).splitlines()
        if l.strip()
    ]
    final = lines[-1]
    assert final["exit_code"] == 0
    assert final["session_seq"] == 1
    resp = await client.post(
        "/v1/execute",
        json={
            "source_code": "print(open('s2.txt').read())",
            "executor_id": "stream-sess",
        },
    )
    body = await resp.json()
    assert body["exit_code"] == 0, body["stderr"]
    assert body["stdout"] == "x\n"
    assert body["session_seq"] == 2
    await client.delete("/v1/executors/stream-sess")


async def test_session_over_http(client):
    """executor_id session: workspace persists across Executes with no file
    round-trip; DELETE /v1/executors/{id} ends it."""
    resp = await client.post(
        "/v1/execute",
        json={
            "source_code": "open('s.txt','w').write('kept')",
            "executor_id": "http-sess",
        },
    )
    assert resp.status == 200
    body = await resp.json()
    assert body["exit_code"] == 0
    assert "/workspace/s.txt" in body["files"]

    resp = await client.post(
        "/v1/execute",
        json={
            "source_code": "print(open('s.txt').read())",
            "executor_id": "http-sess",
        },
    )
    body = await resp.json()
    assert body["exit_code"] == 0, body["stderr"]
    assert body["stdout"] == "kept\n"
    assert body["session_seq"] == 2
    assert body["session_ended"] is False

    resp = await client.delete("/v1/executors/http-sess")
    assert resp.status == 200
    assert (await resp.json())["closed"] == "http-sess"
    # Idempotence: the session is gone now.
    resp = await client.delete("/v1/executors/http-sess")
    assert resp.status == 404
    # Bad ids are client errors.
    resp = await client.delete("/v1/executors/bad%20id")
    assert resp.status == 400


async def test_custom_tool_in_session(client):
    """Custom tools compose with sessions: a tool's workspace files persist
    across an agent's calls sharing an executor_id."""
    tool = (
        "import os\n"
        "def count_calls() -> int:\n"
        '    """Counts invocations within this session.\n'
        "    :return: times called so far\n"
        '    """\n'
        "    n = int(open('calls.txt').read()) if os.path.exists('calls.txt') else 0\n"
        "    n += 1\n"
        "    open('calls.txt', 'w').write(str(n))\n"
        "    return n\n"
    )
    try:
        for want in (1, 2, 3):
            resp = await client.post(
                "/v1/execute-custom-tool",
                json={
                    "tool_source_code": tool,
                    "tool_input_json": "{}",
                    "executor_id": "tool-sess",
                },
            )
            assert resp.status == 200, await resp.text()
            body = await resp.json()
            assert json.loads(body["tool_output_json"]) == want
            # Continuity contract on the tool surface too.
            assert body["session_seq"] == want
            assert body["session_ended"] is False

        # The session is visible to the operator and closable.
        resp = await client.get("/v1/executors")
        sessions = (await resp.json())["sessions"]
        entry = next(s for s in sessions if s["executor_id"] == "tool-sess")
        assert entry["requests"] == 3 and entry["busy"] is False
        assert entry["status"] == "ready"
    finally:
        await client.delete("/v1/executors/tool-sess")
    resp = await client.get("/v1/executors")
    sessions = (await resp.json())["sessions"]
    assert not any(s["executor_id"] == "tool-sess" for s in sessions)


async def test_custom_tool_timeout_session_continuity(client):
    """Tool-call timeout continuity, both flavors. An INTERRUPTIBLE hang is
    cooperatively cancelled: the error body reports the session ALIVE
    (session_ended False) — the agent can keep using it. An uninterruptible
    hang kills the runner and the body must say the session died; a silent
    reset behind a 400 would strand the agent."""
    coop_tool = (
        "import time\n"
        "def hang() -> int:\n"
        "    time.sleep(30)\n"
        "    return 1\n"
    )
    try:
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={
                "tool_source_code": coop_tool,
                "tool_input_json": "{}",
                "executor_id": "tool-coop-sess",
                "timeout": 1,
            },
        )
        assert resp.status == 400
        body = await resp.json()
        assert "timed out" in body["stderr"].lower()
        assert body["session_ended"] is False
    finally:
        await client.delete("/v1/executors/tool-coop-sess")

    kill_tool = (
        "import signal\n"
        "def hang() -> int:\n"
        "    signal.signal(signal.SIGINT, signal.SIG_IGN)\n"
        "    while True:\n"
        "        pass\n"
        "    return 1\n"
    )
    try:
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={
                "tool_source_code": kill_tool,
                "tool_input_json": "{}",
                "executor_id": "tool-kill-sess",
                "timeout": 1,
            },
        )
        assert resp.status == 400
        body = await resp.json()
        assert "timed out" in body["stderr"].lower()
        assert body["session_ended"] is True
    finally:
        await client.delete("/v1/executors/tool-kill-sess")


async def test_healthz(client):
    resp = await client.get("/healthz")
    assert resp.status == 200


async def test_metrics_endpoint(client):
    resp = await client.post("/v1/execute", json={"source_code": "print('hi')"})
    assert resp.status == 200
    resp = await client.get("/metrics")
    assert resp.status == 200
    text = await resp.text()
    assert 'code_interpreter_executions_total{outcome="ok"} 1' in text
    assert "code_interpreter_phase_seconds_bucket" in text
    assert "code_interpreter_pool_depth" in text
    assert "code_interpreter_sandbox_spawn_seconds_count" in text

    # user errors are counted separately from infra errors
    await client.post("/v1/execute", json={"source_code": "raise SystemExit(3)"})
    text = await (await client.get("/metrics")).text()
    assert 'code_interpreter_executions_total{outcome="user_error"} 1' in text


async def test_profile_capture(client):
    source = (
        "import jax.numpy as jnp\n"
        "print(float(jnp.dot(jnp.ones(64), jnp.ones(64))))\n"
    )
    resp = await client.post(
        "/v1/execute", json={"source_code": source, "profile": True, "timeout": 120}
    )
    assert resp.status == 200
    body = await resp.json()
    assert body["exit_code"] == 0, body["stderr"]
    assert "/workspace/profile.zip" in body["files"], body
    # the trace zip is a real, non-empty zip
    object_id = body["files"]["/workspace/profile.zip"]
    resp = await client.get(f"/v1/files/{object_id}")
    data = await resp.read()
    assert data[:2] == b"PK"
