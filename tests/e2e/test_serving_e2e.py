"""The serving stack through the PRODUCT surface: a continuous-batching
engine built and driven INSIDE a sandbox via Execute — orchestrator →
pool → C++ executor server → warm JAX runner → ServingEngine — with the
outputs token-checked against the fused decoder in the same process.

This is config 5g's correctness backbone (benchmarks/run_configs.py runs
the throughput version on the chip); here the full feature surface rides
one Execute: prefix caching, per-request sampling with a seed, logprobs,
and a QLoRA adapter served beside base traffic.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")


from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage

SERVING_SNIPPET = """
import jax, jax.numpy as jnp, numpy as np
from bee_code_interpreter_fs_tpu.models import (
    LlamaConfig, ServingEngine, greedy_generate, init_params, init_lora,
    lora_wrap, quantize_params,
)

cfg = LlamaConfig.tiny(n_layers=2, dim=64, n_heads=4, n_kv_heads=2,
                       hidden_dim=128, vocab_size=97, max_seq_len=96,
                       dtype="float32")
base = quantize_params(init_params(jax.random.PRNGKey(0), cfg))
lora = jax.tree.map(lambda x: x + 0.02 * jnp.ones_like(x),
                    init_lora(jax.random.PRNGKey(1), cfg, rank=4))

eng = ServingEngine(base, cfg, n_slots=2, max_len=96, steps_per_sync=3,
                    adapters={"t": lora})
pid = eng.register_prefix([9, 4, 27])
r_pre = eng.submit([3, 5], 7, prefix_id=pid, logprobs=True)
r_ada = eng.submit([3, 5], 7, adapter="t")
r_smp = eng.submit([8], 6, temperature=1.1, seed=5)
res = eng.run()

ref_pre = np.asarray(greedy_generate(
    base, jnp.asarray([[9, 4, 27, 3, 5]], jnp.int32), cfg,
    max_new_tokens=7))[0, 5:]
assert np.array_equal(res[r_pre], ref_pre), (res[r_pre], ref_pre)
lps = eng.take_logprobs(r_pre)
assert lps is not None and lps.shape == (7,) and np.isfinite(lps).all()

ref_ada = np.asarray(greedy_generate(
    lora_wrap(base, lora), jnp.asarray([[3, 5]], jnp.int32), cfg,
    max_new_tokens=7))[0, 2:]
assert np.array_equal(res[r_ada], ref_ada), (res[r_ada], ref_ada)
assert len(res[r_smp]) == 6

print("serving_ok prefix+qlora+sampled")
"""


@pytest.fixture
async def stack(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        default_execution_timeout=240.0,
        jax_compilation_cache_dir=str(tmp_path / "jax-cache"),
    )
    backend = LocalSandboxBackend(config, warm_import_jax=True,
                                  numpy_dispatch=True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    yield executor
    await executor.close()


async def test_serving_engine_inside_sandbox(stack):
    executor = stack
    await executor.fill_pool()
    result = await executor.execute(SERVING_SNIPPET, timeout=240.0)
    assert result.exit_code == 0, result.stderr[-1200:]
    assert "serving_ok prefix+qlora+sampled" in result.stdout

SPEC_SNIPPET = """
import jax, jax.numpy as jnp, numpy as np
from bee_code_interpreter_fs_tpu.models import (
    LlamaConfig, SpeculativeServingEngine, greedy_generate, init_params,
)

cfg = LlamaConfig.tiny(n_layers=2, dim=64, n_heads=4, n_kv_heads=2,
                       hidden_dim=128, vocab_size=97, max_seq_len=64,
                       dtype="float32")
dcfg = LlamaConfig.tiny(n_layers=1, dim=32, n_heads=2, n_kv_heads=2,
                        hidden_dim=64, vocab_size=97, max_seq_len=64,
                        dtype="float32")
target = init_params(jax.random.PRNGKey(0), cfg)
draft = init_params(jax.random.PRNGKey(3), dcfg)

eng = SpeculativeServingEngine(target, cfg, draft_params=draft,
                               draft_cfg=dcfg, gamma=3, n_slots=2,
                               max_len=64, steps_per_sync=2)
r1 = eng.submit([3, 17, 55, 9], 8)
r2 = eng.submit([8], 6)
res = eng.run()
ref = np.asarray(greedy_generate(
    target, jnp.asarray([[3, 17, 55, 9]], jnp.int32), cfg,
    max_new_tokens=8))[0, 4:]
assert np.array_equal(res[r1], ref), (res[r1], ref)
assert len(res[r2]) == 6
print("spec_serving_ok draft+verify")
"""


async def test_speculative_engine_inside_sandbox(stack):
    executor = stack
    await executor.fill_pool()
    result = await executor.execute(SPEC_SNIPPET, timeout=240.0)
    assert result.exit_code == 0, result.stderr[-1200:]
    assert "spec_serving_ok draft+verify" in result.stdout
