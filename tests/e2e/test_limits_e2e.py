"""End-to-end resource governance: HTTP API → orchestrator → real C++
executor (local backend), pinning ISSUE 5's acceptance criterion — a
memory-hog, fork-bomb, and disk-filler snippet each return a typed limit
violation (correct kind, visible in metrics and the request trace) while the
SAME service successfully serves the immediately following request.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage

MB = 1 << 20


@pytest.fixture
async def stack(tmp_path, monkeypatch):
    # Tight watchdog cadence so kill-path cases resolve fast in CI.
    monkeypatch.setenv("APP_LIMIT_POLL_INTERVAL", "0.05")
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    tools = CustomToolExecutor(executor)
    app = create_http_app(executor, tools, storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    yield client, executor
    await client.close()
    await executor.close()


async def _assert_violation_then_serves(client, executor, code, limits, kind):
    resp = await client.post(
        "/v1/execute",
        json={"source_code": code, "timeout": 30, "limits": limits},
    )
    assert resp.status == 422
    body = await resp.json()
    assert body["violation"] == kind
    assert kind in body["error"]
    # Visible in the request trace: the 422 body carries the trace id and
    # the retained trace holds the limit.violation event.
    trace_id = body.get("trace_id")
    assert trace_id, "422 body should carry the trace id"
    spans = executor.tracer.ring.trace(trace_id)
    events = [
        event
        for span in spans
        for event in span.get("events", [])
        if event.get("name") == "limit.violation"
    ]
    assert events and events[0]["attributes"]["kind"] == kind
    # Visible in metrics.
    metrics_resp = await client.get("/metrics")
    text = await metrics_resp.text()
    assert f'code_interpreter_limit_violations_total{{chip_count="0",kind="{kind}"}}' in text
    # The immediately following request is served by the same service
    # (recycled or replacement host — the client cannot tell, nor should it).
    follow = await client.post(
        "/v1/execute", json={"source_code": "print('still serving')"}
    )
    assert follow.status == 200
    follow_body = await follow.json()
    assert follow_body["stdout"] == "still serving\n"
    assert follow_body["exit_code"] == 0


async def test_memory_hog_typed_violation_then_serves(stack):
    client, executor = stack
    await _assert_violation_then_serves(
        client,
        executor,
        "b = []\nimport time\n"
        "while True:\n"
        "    b.append(bytearray(8 << 20))\n"
        "    time.sleep(0.002)\n",
        {"memory_bytes": 64 * MB},
        "oom",
    )


async def test_fork_bomb_typed_violation_then_serves(stack):
    client, executor = stack
    await _assert_violation_then_serves(
        client,
        executor,
        "import subprocess, time\n"
        "procs = [subprocess.Popen(['sleep', '30']) for _ in range(20)]\n"
        "time.sleep(30)\n",
        {"nproc": 5},
        "nproc",
    )


async def test_disk_filler_typed_violation_then_serves(stack):
    client, executor = stack
    await _assert_violation_then_serves(
        client,
        executor,
        "import time\n"
        "with open('junk.bin', 'wb') as f:\n"
        "    for _ in range(200):\n"
        "        f.write(b'x' * 262144)\n"
        "        f.flush()\n"
        "        time.sleep(0.01)\n"
        "time.sleep(30)\n",
        {"disk_bytes": 1 * MB},
        "disk_quota",
    )
