"""End-to-end quota enforcement against the real local backend + C++
executor — the acceptance criterion verbatim: tenant A exhausts its
chip-second window and gets a 429 with a correct Retry-After and
``X-Quota-*`` headers, is re-admitted after the window refills; tenant B
is served normally throughout; a violation-storm tenant is quarantined AT
ADMISSION (zero scheduler grants consumed per rejected attempt) and
decays back in; and ``APP_QUOTAS_ENABLED=0`` reproduces today's behavior
byte-for-byte.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

import asyncio  # noqa: E402
import time  # noqa: E402

from aiohttp.test_utils import TestClient, TestServer  # noqa: E402

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (  # noqa: E402
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.http_server import (  # noqa: E402
    create_http_app,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402


def make_config(tmp_path, **overrides):
    defaults = dict(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        compile_cache_prewarm=False,
        default_execution_timeout=30.0,
        usage_flush_interval=0.5,
    )
    defaults.update(overrides)
    return Config(**defaults)


async def make_stack(tmp_path, **overrides):
    config = make_config(tmp_path, **overrides)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    app = create_http_app(executor, CustomToolExecutor(executor), storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, executor, config


async def _close(client, executor):
    await client.close()
    await executor.close()


async def _execute(client, code, tenant, **extra):
    return await client.post(
        "/v1/execute",
        json={"source_code": code, "tenant": tenant, **extra},
    )


def _grants_total(executor):
    return sum(
        value for _, value in executor.metrics.scheduler_grants.samples()
    )


async def test_two_tenant_budget_exhaustion_and_refill(tmp_path, monkeypatch):
    monkeypatch.setenv("APP_LIMIT_POLL_INTERVAL", "0.05")
    window = 10.0
    client, executor, config = await make_stack(
        tmp_path,
        quota_chip_seconds_per_window=0.25,
        quota_window_seconds=window,
    )
    try:
        # --- tenant A burns through its window with one slow-ish run ------
        resp = await _execute(
            client, "import time; time.sleep(0.4); print('a')", "tenant-a"
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["phases"]["chip_seconds"] >= 0.25
        quota_block = body["phases"]["quota"]
        assert quota_block["limit_chip_seconds"] == 0.25
        assert quota_block["remaining_chip_seconds"] == 0.0

        # --- over budget: 429 with the typed headers ----------------------
        denied_at = time.monotonic()
        resp = await _execute(client, "print('a2')", "tenant-a")
        assert resp.status == 429
        assert resp.headers["X-Quota-Reason"] == "chip_seconds"
        retry_after = int(resp.headers["Retry-After"])
        # Correct Retry-After: inside the window (the consumption ages out
        # within it), and honest — retrying EARLY is still denied.
        assert 1 <= retry_after <= window
        resp = await _execute(client, "print('early')", "tenant-a")
        assert resp.status == 429

        # --- tenant B is served normally THROUGHOUT -----------------------
        for i in range(3):
            resp = await _execute(client, f"print('b{i}')", "tenant-b")
            assert resp.status == 200

        # --- the window refills: tenant A is re-admitted ------------------
        elapsed = time.monotonic() - denied_at
        await asyncio.sleep(max(0.0, retry_after - elapsed) + 0.5)
        resp = await _execute(client, "print('a3')", "tenant-a")
        assert resp.status == 200, await resp.text()

        # The denials are on the quota surface and in metrics.
        resp = await client.get("/quotas/tenant-a")
        assert resp.status == 200
        row = (await resp.json())["quota"]
        assert row["denials"] >= 2
        metrics_text = await (await client.get("/metrics")).text()
        assert "code_interpreter_quota_denials_total" in metrics_text
        assert 'reason="chip_seconds"' in metrics_text
    finally:
        await _close(client, executor)


async def test_violation_storm_quarantine_and_decay(tmp_path, monkeypatch):
    monkeypatch.setenv("APP_LIMIT_POLL_INTERVAL", "0.05")
    client, executor, config = await make_stack(
        tmp_path,
        quota_violations_per_window=2,
        quota_window_seconds=60.0,
        quota_quarantine_base_seconds=2.0,
        quota_quarantine_decay_seconds=2.0,
    )
    try:
        # Two REAL typed violations (output-cap kills through the actual
        # executor watchdog) land in the abuser's ledger row.
        for _ in range(2):
            resp = await _execute(
                client,
                "while True: print('y' * 65536)\n",
                "abuser",
                timeout=15,
                limits={"output_bytes": 1 << 20},
            )
            assert resp.status == 422
            assert (await resp.json())["violation"] == "output_cap"

        # The storm crosses the threshold: quarantined AT ADMISSION — the
        # scheduler issues ZERO grants for the rejected attempts (no
        # sandbox is ever consumed, unlike the two violating runs above).
        grants_before = _grants_total(executor)
        for _ in range(3):
            resp = await _execute(client, "print('again')", "abuser")
            assert resp.status == 429
            assert resp.headers["X-Quota-Reason"] == "quarantined"
        assert _grants_total(executor) == grants_before

        # An innocent tenant keeps being served while the abuser is shed.
        resp = await _execute(client, "print('fine')", "innocent")
        assert resp.status == 200

        # The sentence decays: after the base quarantine, the abuser is
        # re-admitted (its spent violations do not re-quarantine).
        await asyncio.sleep(2.5)
        resp = await _execute(client, "print('reformed')", "abuser")
        assert resp.status == 200, await resp.text()
    finally:
        await _close(client, executor)


async def test_quota_kill_switch_reproduces_today(tmp_path):
    client, executor, config = await make_stack(
        tmp_path,
        quotas_enabled=False,
        quota_chip_seconds_per_window=0.0001,
        quota_violations_per_window=1,
    )
    try:
        # A budget that would deny everything enforces NOTHING, the
        # response body carries no quota block, and the surface is 404 —
        # pre-quota behavior byte-for-byte.
        for i in range(3):
            resp = await _execute(client, f"print({i})", "tenant-a")
            assert resp.status == 200
            body = await resp.json()
            assert "quota" not in body["phases"]
        assert (await client.get("/quotas")).status == 404
        metrics_text = await (await client.get("/metrics")).text()
        assert "quota_remaining_chip_seconds" not in metrics_text
    finally:
        await _close(client, executor)
