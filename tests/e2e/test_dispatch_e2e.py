"""End-to-end dispatch-shim tests: run the examples through the full Execute
stack with APP_NUMPY_DISPATCH enabled in the sandbox (CPU JAX backend here;
the same path hits the TPU in production/bench)."""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")

from pathlib import Path


from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage

EXAMPLES = Path(__file__).resolve().parent.parent.parent / "examples"


@pytest.fixture
async def executor(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=0,
        jax_compilation_cache_dir="",
        default_execution_timeout=120.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=True, numpy_dispatch=True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    yield executor
    await executor.close()


async def test_shim_active_in_sandbox(executor):
    result = await executor.execute(
        "import numpy as np\n"
        "a = np.random.rand(300000)\n"
        "print(type(a).__name__)\n"
        "print(type(np.zeros(3)).__name__)\n"
        "s = float((a * a).sum())\n"
        "print(0.28 < s / 300000 < 0.39)\n"
    )
    assert result.exit_code == 0, result.stderr
    lines = result.stdout.splitlines()
    assert lines[0] == "TpuArray"  # big arrays on device
    assert lines[1] == "ndarray"  # small arrays on host
    assert lines[2] == "True"


async def test_benchmark_fib_unaffected(executor):
    source = (EXAMPLES / "benchmark-fib.py").read_text()
    result = await executor.execute(source, timeout=120)
    assert result.exit_code == 0, result.stderr
    assert "fib(10000) x1000" in result.stdout


async def test_benchmark_attention_example(executor):
    """The long-context flash-attention bench runs via Execute; on the CPU
    test platform it self-shrinks and runs the kernel interpreted."""
    source = (EXAMPLES / "benchmark-attention.py").read_text()
    result = await executor.execute(source, timeout=120)
    assert result.exit_code == 0, result.stderr
    assert "ATTN_TFLOPS=" in result.stdout


async def test_benchmark_matmul_example(executor):
    """The compute-bound bench (chained bf16 matmuls) runs via Execute; on
    the CPU test platform it self-shrinks and still reports TFLOPS."""
    source = (EXAMPLES / "benchmark-matmul.py").read_text()
    result = await executor.execute(source, timeout=120)
    assert result.exit_code == 0, result.stderr
    assert "TFLOPS=" in result.stdout


async def test_using_imports_with_shim(executor):
    source = (EXAMPLES / "using_imports.py").read_text()
    result = await executor.execute(source, timeout=120)
    assert result.exit_code == 0, result.stderr
    assert result.stdout.strip().endswith("ok")


async def test_escaping_example(executor):
    source = (EXAMPLES / "escaping.py").read_text()
    result = await executor.execute(source)
    assert result.exit_code == 0
    assert "quotes: ' \"" in result.stdout


async def test_crash_example(executor):
    source = (EXAMPLES / "crash.py").read_text()
    result = await executor.execute(source)
    assert result.exit_code == 3
    assert "about to crash" in result.stdout
