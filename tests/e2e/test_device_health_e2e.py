"""End-to-end fleet-telemetry acceptance (ISSUE 8): HTTP API → orchestrator →
real C++ executors (local backend) with a seeded attach-hang fault on ONE
lane.

The acceptance criterion, verbatim: with the fault injected on one lane, the
probe daemon transitions that host healthy → suspect → wedged within the
configured budget, ``device_wedge_detected_total`` increments, the
transition appears as a trace event retrievable via ``/traces``, and
``/statusz`` shows the lane as wedged while the other lane keeps serving;
with a fake OTLP collector in-process, exported spans and metric points for
the same window arrive batched, and the kill switch (no endpoint) produces
zero export HTTP.
"""

import json
import time

import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

import httpx
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.device_health import (
    HEALTHY,
    SUSPECT,
    WEDGED,
    DeviceHealthProbe,
)
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage
from bee_code_interpreter_fs_tpu.utils.otlp import OtlpExporter

WEDGED_LANE = 2


@pytest.fixture
async def stack(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
        # The seeded attach hang, restricted to one lane (rate 1.0 makes
        # every host of that lane wedge deterministically).
        executor_fault_spec=(
            f"attach_hang:1.0,attach_hang_lane:{WEDGED_LANE},seed:7"
        ),
        # Tight budgets so the escalation lands in test time: attach is
        # over budget after 0.3s, wedged 0.3s past that.
        device_probe_interval=0.05,
        device_probe_timeout=5.0,
        device_probe_attach_budget=0.3,
        device_probe_op_grace=5.0,
        device_probe_wedge_after=0.3,
        # DETECTION-only posture (the PR 8 scope this e2e asserts): with
        # the PR 13 actuation default left on, the fencing layer disposes
        # the wedged host moments after the verdict and the wedged row
        # races out of the gauge/statusz census mid-assertion — a timing
        # flake under full-suite load. The detect→act loop has its own
        # e2e (test_recovery_e2e.py).
        device_fence_enabled=False,
    )
    backend = FaultInjectingBackend(
        LocalSandboxBackend(config, warm_import_jax=False),
        FaultSpec.parse(config.executor_fault_spec),
    )
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    probe = DeviceHealthProbe(executor)
    executor.device_health = probe
    app = create_http_app(executor, CustomToolExecutor(executor), storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    yield client, executor, probe
    await probe.stop()
    await client.close()
    await executor.close()


async def _execute_ok(client, lane: int, marker: str) -> dict:
    resp = await client.post(
        "/v1/execute",
        json={"source_code": f"print({marker!r})", "chip_count": lane},
    )
    assert resp.status == 200, await resp.text()
    body = await resp.json()
    assert body["stdout"] == f"{marker}\n"
    return body


async def test_wedge_detection_end_to_end(stack):
    client, executor, probe = stack
    # Light up both lanes: each execute spawns (and then pools) one real
    # executor host per lane.
    await _execute_ok(client, 0, "healthy lane up")
    await _execute_ok(client, WEDGED_LANE, "doomed lane up")
    lanes_by_url = {
        sandbox.url: lane for lane, sandbox in executor.live_hosts()
    }
    assert set(lanes_by_url.values()) == {0, WEDGED_LANE}
    wedged_url = next(
        url for url, lane in lanes_by_url.items() if lane == WEDGED_LANE
    )
    healthy_url = next(url for url, lane in lanes_by_url.items() if lane == 0)
    # Run the probe daemon for real and wait out the configured budget
    # (0.3s attach budget + 0.3s wedge threshold at a 0.05s cadence).
    probe.start()
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if probe.states().get(wedged_url) == WEDGED:
            break
        await __import__("asyncio").sleep(0.05)
    else:
        pytest.fail(f"host never wedged; states={probe.states()}")
    assert probe.states()[healthy_url] == HEALTHY

    # The escalation walked healthy -> ... -> suspect -> wedged. Routine
    # healthy<->busy flips (the synthesized attach inside its budget) are
    # deliberately NOT recorded, so the first retained transition comes
    # FROM a normal state INTO suspect, then suspect -> wedged.
    spans = [
        json.loads(line)
        for line in executor.tracer.ring.export_jsonl().splitlines()
        if "device_health.transition" in line
    ]
    hops = [
        (s["attributes"]["from"], s["attributes"]["to"])
        for s in spans
        if s["attributes"]["host"] == wedged_url
    ]
    assert hops, "no transition spans recorded for the wedged host"
    assert hops[0][0] in (HEALTHY, "busy")
    states_seen = [hop[1] for hop in hops]
    assert WEDGED in states_seen
    assert SUSPECT in states_seen
    assert states_seen.index(SUSPECT) < states_seen.index(WEDGED)

    # The counter moved, on the wedged lane only.
    metrics_resp = await client.get("/metrics")
    text = await metrics_resp.text()
    assert (
        f'device_wedge_detected_total{{chip_count="{WEDGED_LANE}"}} 1' in text
    )
    assert 'device_wedge_detected_total{chip_count="0"}' not in text
    # The gauge one-hots the verdicts.
    assert (
        f'device_health_state{{host="{wedged_url}",lane="{WEDGED_LANE}",'
        f'state="wedged"}} 1'
    ) in text

    # The transition is retrievable via the /traces debug surface.
    traces_resp = await client.get("/traces?limit=50")
    traces = (await traces_resp.json())["traces"]
    transition_rows = [
        t for t in traces if t["root"] == "device_health.transition"
    ]
    assert transition_rows, "transition trace not listed on /traces"
    detail_resp = await client.get(f"/traces/{transition_rows[0]['trace_id']}")
    detail = await detail_resp.json()
    assert detail["spans"][0]["name"] == "device_health.transition"

    # /statusz joins it all: the wedged host on its lane, the healthy lane
    # clean, and the lanes/compile-cache/batching blocks present.
    statusz = await (await client.get("/statusz")).json()
    health = statusz["device_health"]
    assert health["states"]["wedged"] == 1
    rows = {row["host"]: row for row in health["hosts"]}
    assert rows[wedged_url]["state"] == WEDGED
    assert rows[wedged_url]["lane"] == WEDGED_LANE
    assert rows[healthy_url]["state"] == HEALTHY
    assert str(WEDGED_LANE) in statusz["lanes"]
    text_resp = await client.get("/statusz?format=text")
    text_body = await text_resp.text()
    assert "wedged" in text_body

    # Detection only — and the OTHER lane keeps serving while the wedged
    # verdict stands.
    await _execute_ok(client, 0, "still serving")


async def test_otlp_export_and_kill_switch(stack):
    client, executor, probe = stack
    tracer = executor.tracer
    # Kill switch half: with no endpoint configured, no exporter exists and
    # the tracer has no extra sinks — export HTTP is structurally
    # impossible (the ApplicationContext never constructs OtlpExporter;
    # see test_otlp.py::test_application_context_kill_switch_creates_no_exporter).
    assert executor.otlp_exporter is None
    assert tracer.extra_exporters == []

    # Fake in-process collector.
    requests: list[tuple[str, dict]] = []

    def collect(request: httpx.Request) -> httpx.Response:
        requests.append((request.url.path, json.loads(request.content)))
        return httpx.Response(200)

    exporter = OtlpExporter(
        "http://collector:4318",
        registry=executor.metrics.registry,
        metrics=executor.metrics,
        transport=httpx.MockTransport(collect),
    )
    tracer.add_exporter(exporter)
    executor.otlp_exporter = exporter

    # One real traced window: an execute end to end.
    await _execute_ok(client, 0, "traced for export")
    await exporter.flush()

    paths = [path for path, _ in requests]
    assert paths == ["/v1/traces", "/v1/metrics"]
    # The window's spans arrived BATCHED in one trace POST: the HTTP root
    # and the pipeline stages it parented.
    span_names = {
        span["name"]
        for _, body in requests[:1]
        for rs in body["resourceSpans"]
        for ss in rs["scopeSpans"]
        for span in ss["spans"]
    }
    assert "http POST /v1/execute" in span_names
    assert any(name.startswith("executor.execute") for name in span_names)
    # Metric points for the same window rode the snapshot.
    metric_names = {
        metric["name"]
        for _, body in requests[1:2]
        for rm in body["resourceMetrics"]
        for sm in rm["scopeMetrics"]
        for metric in sm["metrics"]
    }
    assert "code_interpreter_executions_total" in metric_names
    assert "device_wedge_detected_total" in metric_names
    # /statusz reflects the exporter's own health.
    statusz = await (await client.get("/statusz")).json()
    assert statusz["otlp"]["enabled"] is True
    assert statusz["otlp"]["exported_spans"] > 0
    await exporter.close()
