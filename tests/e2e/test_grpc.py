"""gRPC e2e tests: real grpc.aio server + real executor binary, no cluster.

Scenario parity with the reference's test/e2e/test_grpc.py (preinstalled
imports, file create → id → feed back → read, custom tool parse/execute and
error propagation) plus the health service and TPU request fields.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("grpc", reason="optional e2e dependency not installed")

import json

import grpc

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.proto import (
    HEALTH_SERVICE_NAME,
    REFLECTION_SERVICE_NAME,
    SERVICE_NAME,
    code_interpreter_pb2 as pb2,
    health_pb2,
    reflection_pb2,
)
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_fs_tpu.services.grpc_server import GrpcServer
from bee_code_interpreter_fs_tpu.services.storage import Storage


class Client:
    def __init__(self, channel: grpc.aio.Channel):
        def u(method, req, resp, service=SERVICE_NAME):
            return channel.unary_unary(
                f"/{service}/{method}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )

        self.execute = u("Execute", pb2.ExecuteRequest, pb2.ExecuteResponse)
        self.parse_tool = u(
            "ParseCustomTool", pb2.ParseCustomToolRequest, pb2.ParseCustomToolResponse
        )
        self.execute_tool = u(
            "ExecuteCustomTool",
            pb2.ExecuteCustomToolRequest,
            pb2.ExecuteCustomToolResponse,
        )
        self.close_executor = u(
            "CloseExecutor", pb2.CloseExecutorRequest, pb2.CloseExecutorResponse
        )
        self.execute_stream = channel.unary_stream(
            f"/{SERVICE_NAME}/ExecuteStream",
            request_serializer=pb2.ExecuteRequest.SerializeToString,
            response_deserializer=pb2.ExecuteStreamEvent.FromString,
        )
        self.health_check = u(
            "Check",
            health_pb2.HealthCheckRequest,
            health_pb2.HealthCheckResponse,
            service=HEALTH_SERVICE_NAME,
        )
        self.reflect = channel.stream_stream(
            f"/{REFLECTION_SERVICE_NAME}/ServerReflectionInfo",
            request_serializer=(
                reflection_pb2.ServerReflectionRequest.SerializeToString
            ),
            response_deserializer=(
                reflection_pb2.ServerReflectionResponse.FromString
            ),
        )


@pytest.fixture
async def client(tmp_path):
    config = Config(
        grpc_listen_addr="127.0.0.1:0",
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    tools = CustomToolExecutor(executor)
    server = GrpcServer(config, executor, tools, storage)
    port = await server.start()
    channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
    yield Client(channel)
    await channel.close()
    await server.stop(grace=0.1)
    await executor.close()


async def test_execute(client):
    resp = await client.execute(pb2.ExecuteRequest(source_code="print(21 * 2)"))
    assert resp.stdout == "42\n"
    assert resp.exit_code == 0


async def test_execute_validation_abort(client):
    with pytest.raises(grpc.aio.AioRpcError) as e:
        await client.execute(pb2.ExecuteRequest())
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    with pytest.raises(grpc.aio.AioRpcError) as e:
        await client.execute(
            pb2.ExecuteRequest(source_code="x", files={"/workspace/a": "bad/id"})
        )
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    # Documented numeric constraints (proto/code_interpreter.proto)
    with pytest.raises(grpc.aio.AioRpcError) as e:
        await client.execute(pb2.ExecuteRequest(source_code="x", timeout=-5))
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    with pytest.raises(grpc.aio.AioRpcError) as e:
        await client.execute(pb2.ExecuteRequest(source_code="x", chip_count=-4))
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


async def test_execute_session_affinity(client):
    """executor_id pins requests to one live sandbox: the workspace persists
    across Executes (no files map round-trip needed). The reference carried
    this field but its single-use pods ignored it (only health_check.py:48
    ever set it); here it has the upstream persistent-executor semantics."""
    resp = await client.execute(
        pb2.ExecuteRequest(
            source_code="open('kept.txt','w').write('42')",
            executor_id="grpc-sess",
        )
    )
    assert resp.exit_code == 0
    resp = await client.execute(
        pb2.ExecuteRequest(
            source_code="print(open('kept.txt').read())",
            executor_id="grpc-sess",
        )
    )
    assert resp.exit_code == 0, resp.stderr
    assert resp.stdout == "42\n"
    assert resp.session_seq == 2
    assert resp.session_ended is False

    # gRPC clients can close their sessions without the HTTP surface.
    closed = await client.close_executor(
        pb2.CloseExecutorRequest(executor_id="grpc-sess")
    )
    assert closed.closed is True
    closed = await client.close_executor(
        pb2.CloseExecutorRequest(executor_id="grpc-sess")
    )
    assert closed.closed is False

    with pytest.raises(grpc.aio.AioRpcError) as e:
        await client.execute(
            pb2.ExecuteRequest(source_code="x", executor_id="bad id")
        )
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


async def test_execute_stream(client):
    """Server-streaming Execute: chunk events while the code runs, then one
    result event identical to Execute's response shape."""
    src = (
        "import time\n"
        "for i in range(3):\n"
        "    print('s', i, flush=True)\n"
        "    time.sleep(0.3)\n"
    )
    chunks, results = [], []
    async for event in client.execute_stream(
        pb2.ExecuteRequest(source_code=src)
    ):
        kind = event.WhichOneof("event")
        if kind == "chunk":
            chunks.append(event.chunk)
        else:
            results.append(event.result)
    assert len(results) == 1
    result = results[0]
    assert result.exit_code == 0
    assert result.stdout == "s 0\ns 1\ns 2\n"
    assert chunks, "no chunk events"
    assert "".join(
        c.data for c in chunks if c.stream == "stdout"
    ) == result.stdout

    # Validation aborts before the stream starts.
    with pytest.raises(grpc.aio.AioRpcError) as e:
        async for _ in client.execute_stream(pb2.ExecuteRequest()):
            pass
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


async def test_file_roundtrip(client):
    resp = await client.execute(
        pb2.ExecuteRequest(source_code="open('note.txt','w').write('hello from run 1')")
    )
    assert resp.exit_code == 0
    object_id = resp.files["/workspace/note.txt"]

    resp = await client.execute(
        pb2.ExecuteRequest(
            source_code="print(open('note.txt').read())",
            files={"/workspace/note.txt": object_id},
        )
    )
    assert resp.stdout == "hello from run 1\n"


async def test_parse_custom_tool(client):
    resp = await client.parse_tool(
        pb2.ParseCustomToolRequest(
            tool_source_code=(
                'def greet(name: str) -> str:\n'
                '    """Say hi.\n\n    :param name: who to greet\n    """\n'
                '    return f"hi {name}"'
            )
        )
    )
    assert resp.WhichOneof("response") == "success"
    assert resp.success.tool_name == "greet"
    schema = json.loads(resp.success.tool_input_schema_json)
    assert schema["properties"]["name"]["description"] == "who to greet"


async def test_parse_custom_tool_error(client):
    resp = await client.parse_tool(
        pb2.ParseCustomToolRequest(tool_source_code="def f(**kw): pass")
    )
    assert resp.WhichOneof("response") == "error"
    assert any("**kwargs" in m for m in resp.error.error_messages)


async def test_execute_custom_tool(client):
    resp = await client.execute_tool(
        pb2.ExecuteCustomToolRequest(
            tool_source_code="def add(a: int, b: int) -> int:\n    return a + b",
            tool_input_json='{"a": 40, "b": 2}',
        )
    )
    assert resp.WhichOneof("response") == "success"
    assert json.loads(resp.success.tool_output_json) == 42


async def test_execute_custom_tool_session(client):
    """Tool calls sharing an executor_id see each other's workspace files."""
    tool = (
        "import os\n"
        "def bump() -> int:\n"
        "    n = int(open('n.txt').read()) if os.path.exists('n.txt') else 0\n"
        "    open('n.txt', 'w').write(str(n + 1))\n"
        "    return n + 1\n"
    )
    try:
        for want in (1, 2):
            resp = await client.execute_tool(
                pb2.ExecuteCustomToolRequest(
                    tool_source_code=tool,
                    tool_input_json="{}",
                    executor_id="grpc-tool-sess",
                )
            )
            assert resp.WhichOneof("response") == "success", resp
            assert json.loads(resp.success.tool_output_json) == want
            assert resp.success.session_seq == want
            assert resp.success.session_ended is False
    finally:
        closed = await client.close_executor(
            pb2.CloseExecutorRequest(executor_id="grpc-tool-sess")
        )
    assert closed.closed is True


async def test_execute_custom_tool_session_death_visible_on_error(client):
    """gRPC mirror of the HTTP error-continuity test: a tool call whose
    timeout KILLS the session's runner (SIGINT ignored, so cooperative
    cancellation can't save it) returns the Error variant WITH
    session_ended=true — the agent must see its session died."""
    tool = (
        "import signal\n"
        "def hang() -> int:\n"
        "    signal.signal(signal.SIGINT, signal.SIG_IGN)\n"
        "    while True:\n"
        "        pass\n"
        "    return 1\n"
    )
    try:
        resp = await client.execute_tool(
            pb2.ExecuteCustomToolRequest(
                tool_source_code=tool,
                tool_input_json="{}",
                executor_id="grpc-tool-kill",
                timeout=1.0,
            )
        )
        assert resp.WhichOneof("response") == "error", resp
        assert "timed out" in resp.error.stderr.lower()
        assert resp.error.session_ended is True
    finally:
        await client.close_executor(
            pb2.CloseExecutorRequest(executor_id="grpc-tool-kill")
        )


async def test_execute_custom_tool_error(client):
    resp = await client.execute_tool(
        pb2.ExecuteCustomToolRequest(
            tool_source_code="def div(a: int) -> float:\n    return a / 0",
            tool_input_json='{"a": 1}',
        )
    )
    assert resp.WhichOneof("response") == "error"
    assert "division by zero" in resp.error.stderr


async def test_reflection_list_services(client):
    """The grpcurl `list` workflow (reference README.md:46): list_services
    must name every registered service."""
    call = client.reflect(
        iter([reflection_pb2.ServerReflectionRequest(list_services="*")])
    )
    responses = [r async for r in call]
    assert len(responses) == 1
    names = {s.name for s in responses[0].list_services_response.service}
    assert SERVICE_NAME in names
    assert HEALTH_SERVICE_NAME in names
    assert REFLECTION_SERVICE_NAME in names


async def test_reflection_file_containing_symbol(client):
    """The grpcurl `describe` workflow: fetching the file for a service
    symbol must return a descriptor closure that actually parses and
    contains the service definition."""
    from google.protobuf import descriptor_pb2

    call = client.reflect(
        iter(
            [
                reflection_pb2.ServerReflectionRequest(
                    file_containing_symbol=SERVICE_NAME
                ),
                reflection_pb2.ServerReflectionRequest(
                    file_containing_symbol="code_interpreter.v1.ExecuteRequest"
                ),
                reflection_pb2.ServerReflectionRequest(
                    file_containing_symbol="no.such.Symbol"
                ),
            ]
        )
    )
    responses = [r async for r in call]
    assert len(responses) == 3
    for resp in responses[:2]:
        assert resp.WhichOneof("message_response") == "file_descriptor_response"
        protos = [
            descriptor_pb2.FileDescriptorProto.FromString(raw)
            for raw in resp.file_descriptor_response.file_descriptor_proto
        ]
        assert any(
            svc.name == "CodeInterpreterService"
            for proto in protos
            for svc in proto.service
        )
    assert responses[2].WhichOneof("message_response") == "error_response"
    assert responses[2].error_response.error_code == int(
        grpc.StatusCode.NOT_FOUND.value[0]
    )


async def test_health_service(client):
    resp = await client.health_check(health_pb2.HealthCheckRequest())
    assert resp.status == health_pb2.HealthCheckResponse.SERVING
    resp = await client.health_check(health_pb2.HealthCheckRequest(service=SERVICE_NAME))
    assert resp.status == health_pb2.HealthCheckResponse.SERVING
    with pytest.raises(grpc.aio.AioRpcError) as e:
        await client.health_check(health_pb2.HealthCheckRequest(service="nope"))
    assert e.value.code() == grpc.StatusCode.NOT_FOUND
