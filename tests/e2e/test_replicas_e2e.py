"""Scale-out control-plane e2e: TWO in-process replicas over ONE shared
state store.

The ROADMAP acceptance criterion, verbatim: two control-plane replicas
serve one tenant's session stream with fair-share and breaker semantics
preserved — interleaved same-tenant sessions keep WFQ ordering, a breaker
tripped via replica A is observed open by replica B, and a host fenced by
A is never granted by B. Plus the failover satellite: kill one of two
replicas mid-session; its sessions rehash to the survivor, which serves
them after lease-fenced turnover instead of wedging on the dead owner's
grants.

Stack: CodeExecutor x2 over in-memory fake backends (distinct per
replica, as two k8s pods would have) sharing one InMemoryStateStore
(shared=True — the deterministic stand-in for the sqlite file store; the
store contract itself is covered in test_state_store.py), full aiohttp
apps with SessionRouter for the failover leg."""

import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

import asyncio

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.base import Sandbox
from bee_code_interpreter_fs_tpu.services.code_executor import (
    CircuitOpenError,
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.replicas import (
    ReplicaRing,
    SessionRouter,
)
from bee_code_interpreter_fs_tpu.services.state_store import InMemoryStateStore
from bee_code_interpreter_fs_tpu.services.storage import Storage


class ReplicaFakeBackend:
    """In-memory backend, one per replica (two pods own different
    sandboxes over the same physical substrate). `spawn_delay` keeps
    concurrent acquisitions inside one scheduler busy period so WFQ tags
    are comparable across replicas."""

    compile_cache_dir_scope = "private"
    supports_lease_push = False

    def __init__(self, name: str, spawn_delay: float = 0.0):
        self.name = name
        self.spawn_delay = spawn_delay
        self.spawns = 0
        self.live = set()

    async def spawn(self, chip_count: int = 0) -> Sandbox:
        if self.spawn_delay:
            await asyncio.sleep(self.spawn_delay)
        self.spawns += 1
        sid = f"{self.name}-sb-{self.spawns}"
        sandbox = Sandbox(
            id=sid, url=f"http://{sid}", chip_count=chip_count
        )
        self.live.add(sid)
        return sandbox

    def pool_capacity(self, chip_count: int):
        return None

    async def reset(self, sandbox: Sandbox):
        if sandbox.id not in self.live:
            return None
        return sandbox

    async def delete(self, sandbox: Sandbox) -> None:
        self.live.discard(sandbox.id)

    async def close(self) -> None:
        self.live.clear()


def patch_sandbox_wire(executor: CodeExecutor) -> list:
    """Replace the HTTP hop to the (fake) sandbox; returns the served-by
    log."""
    served = []

    async def fake_post_execute(client, base, payload, timeout, sandbox):
        served.append(sandbox.id)
        return {
            "stdout": "ok\n",
            "stderr": "",
            "exit_code": 0,
            "files": [],
            "warm": True,
        }

    executor._post_execute = fake_post_execute
    return served


def make_replica(name, store, tmp_path, spawn_delay=0.0, **config_kwargs):
    defaults = dict(
        file_storage_path=str(tmp_path / name / "storage"),
        usage_journal_path=str(tmp_path / name / "usage"),
        executor_pod_queue_target_length=0,
        compile_cache_enabled=False,
        replica_self=name,
    )
    defaults.update(config_kwargs)
    config = Config(**defaults)
    backend = ReplicaFakeBackend(name, spawn_delay=spawn_delay)
    executor = CodeExecutor(
        backend,
        Storage(config.file_storage_path),
        config,
        state_store=store,
    )
    served = patch_sandbox_wire(executor)
    return executor, backend, served


async def settle(executor):
    for _ in range(3):
        await asyncio.sleep(0)
    tasks = list(executor._dispose_tasks) + list(executor._fill_tasks)
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)


async def close_all(*executors):
    for executor in executors:
        await executor.close()


# ------------------------------------------------------------- acceptance


async def test_interleaved_sessions_keep_wfq_ordering(tmp_path):
    """One tenant's sessions, interleaved across both replicas, draw WFQ
    tags from ONE fleet-wide flow sequence: in submission order the tags
    are strictly increasing within the busy period — exactly what a
    single control plane would have assigned."""
    store = InMemoryStateStore(shared=True)
    exec_a, _, _ = make_replica("ra", store, tmp_path, spawn_delay=0.05)
    exec_b, _, _ = make_replica("rb", store, tmp_path, spawn_delay=0.05)
    tags = []
    for name, executor in (("ra", exec_a), ("rb", exec_b)):
        sched = executor.scheduler
        original = sched.submit

        def wrapped(lane, *, _orig=original, _name=name, **kwargs):
            ticket = _orig(lane, **kwargs)
            if kwargs.get("tenant") == "alice":
                tags.append((_name, ticket.start_tag, ticket.finish_tag))
            return ticket

        sched.submit = wrapped
    try:
        # 6 concurrent session creations, alternating replicas, one
        # tenant: the spawn delay holds them all inside one busy period.
        await asyncio.gather(
            *(
                (exec_a if i % 2 == 0 else exec_b).execute(
                    "print('hi')",
                    executor_id=f"sess-{i}",
                    tenant="alice",
                )
                for i in range(6)
            )
        )
        assert len(tags) == 6
        assert {name for name, _, _ in tags} == {"ra", "rb"}
        finishes = [finish for _, _, finish in tags]
        # One global flow sequence: tags never go backwards in submission
        # order (two private tag tables would restart per replica), and a
        # repeated tag can only be a fresh busy period's HEAD (the same
        # per-busy-period reset one scheduler performs when its lane
        # empties) — never two replicas handing one flow the same slot
        # mid-period.
        assert finishes == sorted(finishes)
        head = 1.0  # weight-1 flow: first tag of a fresh table
        duplicates = {f for f in finishes if finishes.count(f) > 1}
        assert duplicates <= {head}
        # Direct cross-replica continuation: some adjacent submissions on
        # DIFFERENT replicas chain start == previous finish — replica B
        # continued the flow exactly where replica A left it.
        assert any(
            name_b != name_a and start_b == pytest.approx(finish_a)
            for (name_a, _, finish_a), (name_b, start_b, _) in zip(
                tags, tags[1:]
            )
        )
    finally:
        await settle(exec_a)
        await settle(exec_b)
        await close_all(exec_a, exec_b)


async def test_breaker_tripped_on_a_open_on_b(tmp_path):
    store = InMemoryStateStore(shared=True)
    exec_a, _, _ = make_replica("ra", store, tmp_path)
    exec_b, _, _ = make_replica("rb", store, tmp_path)
    try:
        # Replica A trips its default-lane breaker (violation storm /
        # consecutive spawn failures); replica B observes it OPEN: its
        # health degrades and its executes fail fast — no burning the
        # acquire budget against the same dead backend.
        exec_a.breakers.lane(0).trip("storm on replica A")
        assert exec_b.degraded()
        assert exec_b.breakers.retry_after(0) > 0
        with pytest.raises(CircuitOpenError):
            await exec_b.execute("print('nope')")
    finally:
        await close_all(exec_a, exec_b)


async def test_host_fenced_by_a_never_granted_by_b(tmp_path):
    store = InMemoryStateStore(shared=True)
    exec_a, _, served_a = make_replica(
        "ra",
        store,
        tmp_path,
        device_probe_readmit_streak=1,
        executor_pod_queue_target_length=1,
        pool_autoscale_enabled=False,
    )
    exec_b, backend_b, served_b = make_replica(
        "rb",
        store,
        tmp_path,
        device_probe_readmit_streak=1,
        executor_pod_queue_target_length=1,
        pool_autoscale_enabled=False,
    )
    try:
        # B warms a sandbox on the shared hardware scope (lane-0)...
        await exec_b.execute("print('warm b')")
        await settle(exec_b)
        pool_b = exec_b._pool(0)
        assert pool_b  # recycled into B's pool
        stale_host = pool_b[0]
        gen_b = stale_host.meta["lease"].generation
        # ...then A mints a newer lease on the same scope and its host
        # wedges: A fences it.
        await exec_a.execute("print('warm a')")
        await settle(exec_a)
        sandbox_id_a = next(iter(exec_a._live_sandboxes))
        assert await exec_a.fence_host(sandbox_id_a, reason="wedged") == "fenced"
        # THE criterion: B's pooled host (an older generation on the
        # fenced scope) is never granted — the pop path drains it through
        # lease-fenced turnover instead.
        assert exec_b.leases.stale(stale_host.meta["lease"])
        assert exec_b._pop_pool_sandbox(pool_b) is None
        assert stale_host.meta["device_health"] == "draining"
        assert stale_host not in pool_b
        await settle(exec_b)
        assert stale_host.id not in backend_b.live  # disposed, not parked
        # The scope re-admits after the clean-probe streak (streak=1 here;
        # either replica's probes may complete it)...
        assert exec_b.leases.note_probe("lane-0", clean=True) is True
        assert not exec_a.leases.recovering("lane-0")
        # ...and B then serves on a FRESH generation above the fence floor.
        await exec_b.execute("print('post-fence')")
        assert served_b[-1] != stale_host.id
        floor = store.get("lease_fence", "lane-0")
        assert floor is None  # re-admitted
        assert gen_b < exec_b.leases.current_generation("lane-0")
    finally:
        await settle(exec_a)
        await settle(exec_b)
        await close_all(exec_a, exec_b)


# --------------------------------------------------------------- failover


class ManualClock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now


async def test_replica_failover_sessions_rehash_to_survivor(tmp_path):
    """Kill one of two replicas mid-session: the survivor detects the
    dead owner at proxy time, drops it from the ring, and serves the
    rehashed session itself (session_seq=1 reports the state loss) —
    instead of wedging on the dead owner's grants."""
    store = InMemoryStateStore(shared=True)
    exec_a, _, served_a = make_replica("ra", store, tmp_path)
    exec_b, _, served_b = make_replica("rb", store, tmp_path)
    clock = ManualClock()
    # Peer addresses are patched in once the test servers pick their
    # ephemeral ports (ring.peers is read live by url_of).
    peers = {"ra": "", "rb": ""}
    router_a = SessionRouter(
        ReplicaRing("ra", peers, store=store, heartbeat_ttl=30.0, clock=clock)
    )
    router_b = SessionRouter(
        ReplicaRing("rb", peers, store=store, heartbeat_ttl=30.0, clock=clock)
    )
    exec_a.session_router = router_a
    exec_b.session_router = router_b
    app_a = create_http_app(
        exec_a,
        CustomToolExecutor(exec_a),
        Storage(str(tmp_path / "ra" / "storage")),
        router=router_a,
    )
    app_b = create_http_app(
        exec_b,
        CustomToolExecutor(exec_b),
        Storage(str(tmp_path / "rb" / "storage")),
        router=router_b,
    )
    client_a = TestClient(TestServer(app_a))
    client_b = TestClient(TestServer(app_b))
    await client_a.start_server()
    await client_b.start_server()
    a_dead = False
    try:
        for ring in (router_a.ring, router_b.ring):
            ring.peers["ra"] = str(client_a.make_url("")).rstrip("/")
            ring.peers["rb"] = str(client_b.make_url("")).rstrip("/")
        router_a.ring.heartbeat()
        router_b.ring.heartbeat()
        # A session OWNED by replica A, created through replica B: the
        # edge transparently proxies it to the owner.
        session = next(
            f"sess-{i}"
            for i in range(256)
            if router_b.owner_of("alice", f"sess-{i}") == "ra"
        )
        resp = await client_b.post(
            "/v1/execute",
            json={
                "source_code": "print('turn 1')",
                "executor_id": session,
                "tenant": "alice",
            },
        )
        assert resp.status == 200
        assert resp.headers.get("X-Replica-Owner") == "ra"
        assert (await resp.json())["session_seq"] == 1
        assert served_a and not served_b  # A's sandbox served it
        # Turn 2 through B again: still proxied, session state lives on A.
        resp = await client_b.post(
            "/v1/execute",
            json={
                "source_code": "print('turn 2')",
                "executor_id": session,
                "tenant": "alice",
            },
        )
        assert (await resp.json())["session_seq"] == 2
        # KILL replica A mid-session (server down, executor gone).
        await client_a.close()
        await exec_a.close()
        a_dead = True
        # Turn 3 through B: the proxy fails, A drops off B's ring, the
        # session rehashes to B — which serves it FRESH (seq=1: the dead
        # owner's state is gone, reported honestly) on its own healthy
        # sandbox instead of wedging on the dead owner's grants.
        resp = await client_b.post(
            "/v1/execute",
            json={
                "source_code": "print('turn 3')",
                "executor_id": session,
                "tenant": "alice",
            },
        )
        assert resp.status == 200
        assert (await resp.json())["session_seq"] == 1
        assert served_b  # the survivor's own sandbox served it
        assert router_b.ring.live_ids() == ["rb"]
        assert router_b.owns("alice", session)
    finally:
        await router_a.close()
        await router_b.close()
        await client_b.close()
        await settle(exec_b)
        await exec_b.close()
        if not a_dead:
            await client_a.close()
            await exec_a.close()


# --------------------------------------------------------------- gRPC edge


class AbortRaised(Exception):
    def __init__(self, code, details):
        super().__init__(details)
        self.code = code
        self.details = details


class FakeGrpcContext:
    def __init__(self, metadata=()):
        self.metadata = tuple(metadata)
        self.trailing = ()

    def invocation_metadata(self):
        return self.metadata

    def set_trailing_metadata(self, trailing):
        self.trailing = tuple(trailing)

    async def abort(self, code, details=""):
        raise AbortRaised(code, details)


async def test_grpc_non_owner_aborts_with_owner_metadata(tmp_path):
    """The gRPC edge's half of affinity: a session RPC on a non-owner
    replica aborts UNAVAILABLE with the owner's identity (and address)
    in trailing metadata — the transport-level analogue of the HTTP
    307 + X-Replica-Owner contract."""
    grpc = pytest.importorskip("grpc")
    from bee_code_interpreter_fs_tpu.proto import code_interpreter_pb2 as pb2
    from bee_code_interpreter_fs_tpu.services.grpc_servicers.code_interpreter_servicer import (  # noqa: E501
        CodeInterpreterServicer,
    )

    store = InMemoryStateStore(shared=True)
    exec_b, _, served_b = make_replica("rb", store, tmp_path)
    router_b = SessionRouter(
        ReplicaRing("rb", {"ra": "http://replica-a:8000", "rb": ""})
    )
    exec_b.session_router = router_b
    servicer = CodeInterpreterServicer(exec_b, CustomToolExecutor(exec_b))
    try:
        ra_session = next(
            f"sess-{i}"
            for i in range(256)
            if router_b.owner_of("alice", f"sess-{i}") == "ra"
        )
        context = FakeGrpcContext(metadata=[("x-tenant", "alice")])
        with pytest.raises(AbortRaised) as exc:
            await servicer.Execute(
                pb2.ExecuteRequest(
                    source_code="print(1)", executor_id=ra_session
                ),
                context,
            )
        assert exc.value.code == grpc.StatusCode.UNAVAILABLE
        trailing = dict(context.trailing)
        assert trailing["x-replica-owner"] == "ra"
        assert trailing["x-replica-owner-url"] == "http://replica-a:8000"
        assert not served_b  # nothing ran locally
        # A session rb OWNS serves normally.
        rb_session = next(
            f"own-{i}"
            for i in range(256)
            if router_b.owner_of("alice", f"own-{i}") == "rb"
        )
        context = FakeGrpcContext(metadata=[("x-tenant", "alice")])
        response = await servicer.Execute(
            pb2.ExecuteRequest(
                source_code="print(1)", executor_id=rb_session
            ),
            context,
        )
        assert response.session_seq == 1
        assert served_b
    finally:
        await settle(exec_b)
        await exec_b.close()
