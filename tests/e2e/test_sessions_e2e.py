"""End-to-end executor_id sessions through the real local backend + C++
executor: workspace and process state persist across a session's Executes,
and closing the session scrubs everything for the next tenant.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")

import asyncio


from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage


@pytest.fixture
async def stack(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    yield executor, backend
    await executor.close()


async def _settle(executor):
    for _ in range(200):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


async def test_session_workspace_persists_across_executes(stack):
    executor, backend = stack

    first = await executor.execute(
        "open('notes.txt', 'w').write('hello from request 1')\n"
        "import os; print(os.getpid())\n",
        executor_id="sess-e2e",
    )
    assert first.exit_code == 0, first.stderr
    # The changed file is still captured per-request (stateless-files parity).
    assert "/workspace/notes.txt" in first.files

    # No upload round-trip: the session workspace still has the file.
    second = await executor.execute(
        "import os\n"
        "print(open('notes.txt').read())\n"
        "print(os.getpid())\n",
        executor_id="sess-e2e",
    )
    assert second.exit_code == 0, second.stderr
    lines = second.stdout.splitlines()
    assert lines[0] == "hello from request 1"
    # Same warm process served both (in-process execution: user pid = runner
    # pid), so imported modules stay hot within the session too.
    assert first.stdout.strip() == lines[1]

    # A STATELESS request meanwhile sees a pristine workspace.
    stateless = await executor.execute("import os; print(os.listdir('.'))")
    assert stateless.exit_code == 0, stateless.stderr
    assert "notes.txt" not in stateless.stdout

    # Close the session; the same id then starts from scratch.
    assert await executor.close_session("sess-e2e") is True
    await _settle(executor)
    fresh = await executor.execute(
        "import os; print(os.path.exists('notes.txt'))",
        executor_id="sess-e2e",
    )
    assert fresh.exit_code == 0, fresh.stderr
    assert fresh.stdout.strip() == "False"


async def test_session_survives_cooperative_timeout(stack):
    """An INTERRUPTIBLE runaway is cancelled via SIGINT: to the session the
    timeout is just a failed request — its in-process state and workspace
    legitimately survive (the runner was never killed)."""
    executor, backend = stack

    first = await executor.execute(
        "import os\nopen('state.txt', 'w').write('x')\nprint(os.getpid())",
        executor_id="sess-coop",
    )
    assert first.exit_code == 0, first.stderr
    pid = first.stdout.strip()

    hung = await executor.execute(
        "import time\ntime.sleep(30)", executor_id="sess-coop", timeout=1.0
    )
    assert hung.exit_code == -1
    assert "timed out" in hung.stderr.lower()
    assert "sess-coop" in executor._sessions

    # Same warm PROCESS (never killed) and same workspace afterwards.
    cont = await executor.execute(
        "import os\nprint(os.getpid(), os.path.exists('state.txt'))",
        executor_id="sess-coop",
    )
    assert cont.exit_code == 0, cont.stderr
    assert cont.stdout.strip() == f"{pid} True"
    await executor.close_session("sess-coop")
    await _settle(executor)


async def test_session_timeout_kill_ends_session(stack):
    executor, backend = stack

    first = await executor.execute(
        "open('state.txt', 'w').write('x')", executor_id="sess-kill"
    )
    assert first.exit_code == 0, first.stderr

    # An UNinterruptible runaway (ignores SIGINT) exhausts the cancellation
    # grace; the warm runner is killed -> runner_restarted -> the session
    # ends (its in-process state is gone, the contract is broken).
    hung = await executor.execute(
        "import signal\nsignal.signal(signal.SIGINT, signal.SIG_IGN)\n"
        "while True: pass",
        executor_id="sess-kill", timeout=1.0,
    )
    assert hung.exit_code == -1
    assert "timed out" in hung.stderr.lower()
    assert "sess-kill" not in executor._sessions
    await _settle(executor)

    # Same id afterwards = a fresh session with a clean workspace.
    fresh = await executor.execute(
        "import os; print(os.path.exists('state.txt'))",
        executor_id="sess-kill",
    )
    assert fresh.exit_code == 0, fresh.stderr
    assert fresh.stdout.strip() == "False"


async def test_session_hibernate_restore_round_trip(stack, tmp_path):
    """The durability plane end-to-end: a session that mutated interpreter
    state (env var) and its workspace is hibernated (sandbox disposed, chip
    released), then lazily restored onto a FRESH sandbox — env and file
    byte-exact, session_seq continuous, restore phase reported."""
    executor, backend = stack
    executor.config.session_hibernate_idle_seconds = 0.05

    first = await executor.execute(
        "import os\n"
        "os.environ['DURABLE_E2E'] = 'survives'\n"
        "open('notes.txt', 'w').write('hibernated bytes')\n"
        "print(os.getpid())\n",
        executor_id="sess-hib",
    )
    assert first.exit_code == 0, first.stderr

    await asyncio.sleep(0.2)
    assert await executor.sweep_sessions() == 1
    await _settle(executor)
    assert "sess-hib" not in executor._sessions
    assert sum(executor._session_held.values()) == 0
    assert executor.session_store.entry_count() == 1

    # The disposed sandbox went through /reset (env + workspace wiped)
    # before returning to the pool, so seeing the state back proves it
    # rode the checkpoint — whichever warm process serves the restore.
    back = await executor.execute(
        "import os\n"
        "print(os.environ.get('DURABLE_E2E'))\n"
        "print(open('notes.txt').read())\n",
        executor_id="sess-hib",
    )
    assert back.exit_code == 0, back.stderr
    lines = back.stdout.splitlines()
    assert lines[0] == "survives"
    assert lines[1] == "hibernated bytes"
    assert back.session_seq == 2
    assert "restore" in back.phases

    # Close wipes the live session AND the checkpoint: the id restarts
    # honestly from scratch.
    assert await executor.close_session("sess-hib") is True
    await _settle(executor)
    assert executor.session_store.entry_count() == 0
    fresh = await executor.execute(
        "import os; print(os.path.exists('notes.txt'))",
        executor_id="sess-hib",
    )
    assert fresh.session_seq == 1
    assert fresh.stdout.strip() == "False"
