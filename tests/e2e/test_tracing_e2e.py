"""End-to-end tracing (ISSUE 4 acceptance): one `/v1/execute` through the
HTTP API → scheduler → transfer → real C++ executor yields ONE connected
trace spanning both processes — API entry, scheduler wait, transfer upload,
executor call, the sandbox's install/exec/collect (grafted from its trace
block), and transfer download — retrievable via `GET /traces/{trace_id}`
and exported as JSONL.
"""

import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

import json

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage

TRACE_ID = "f" * 32
UPSTREAM_SPAN = "1" * 16
TRACEPARENT = f"00-{TRACE_ID}-{UPSTREAM_SPAN}-01"


async def make_client(tmp_path, **config_overrides):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
        **config_overrides,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    tools = CustomToolExecutor(executor)
    app = create_http_app(executor, tools, storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, executor


async def test_single_execute_yields_connected_cross_process_trace(tmp_path):
    jsonl_path = tmp_path / "spans.jsonl"
    client, executor = await make_client(
        tmp_path, tracing_jsonl_path=str(jsonl_path)
    )
    try:
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "print(6 * 7)"},
            headers={"traceparent": TRACEPARENT},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["stdout"] == "42\n"
        # The response correlates to its trace three ways: phases,
        # X-Trace-Id, and the echoed X-Request-Id.
        assert body["phases"]["trace_id"] == TRACE_ID
        assert resp.headers["X-Trace-Id"] == TRACE_ID
        assert resp.headers["X-Request-Id"]

        resp = await client.get(f"/traces/{TRACE_ID}")
        assert resp.status == 200
        spans = (await resp.json())["spans"]
        names = [s["name"] for s in spans]
        # ≥ 8 spans across BOTH processes (the sandbox.* three are measured
        # inside the C++ executor and grafted back).
        assert len(spans) >= 8
        assert set(names) >= {
            "http POST /v1/execute",
            "scheduler.queue_wait",
            "transfer.upload",
            "executor.execute",
            "sandbox.install",
            "sandbox.exec",
            "sandbox.collect",
            "transfer.download",
        }
        # One CONNECTED trace: a single root (parented to the upstream
        # context we sent), every other span reachable from it.
        by_id = {s["span_id"]: s for s in spans}
        roots = [s for s in spans if s["parent_id"] == UPSTREAM_SPAN]
        assert [s["name"] for s in roots] == ["http POST /v1/execute"]
        for span in spans:
            hops = 0
            node = span
            while node["parent_id"] != UPSTREAM_SPAN:
                node = by_id[node["parent_id"]]  # KeyError = orphan
                hops += 1
                assert hops < 10
        # Grafted sandbox spans nest inside their executor.execute parent.
        [host_span] = [s for s in spans if s["name"] == "executor.execute"]
        for span in spans:
            if span["name"].startswith("sandbox."):
                assert span["parent_id"] == host_span["span_id"]

        # Recent-traces debug surface lists it.
        resp = await client.get("/traces")
        assert resp.status == 200
        listing = await resp.json()
        assert listing["enabled"] is True
        assert any(t["trace_id"] == TRACE_ID for t in listing["traces"])

        # JSONL: both the file exporter and the on-demand endpoint.
        exported = [
            json.loads(line)
            for line in jsonl_path.read_text().splitlines()
        ]
        assert {s["trace_id"] for s in exported} == {TRACE_ID}
        assert len(exported) == len(spans)
        resp = await client.get(f"/traces/{TRACE_ID}?format=jsonl")
        assert resp.status == 200
        lines = (await resp.text()).splitlines()
        assert len(lines) == len(spans)

        # Per-stage histograms moved for every span name.
        rendered = executor.metrics.registry.render()
        for stage in ("scheduler.queue_wait", "sandbox.exec"):
            assert f'code_interpreter_span_seconds_count{{span="{stage}"}} 1' in rendered
    finally:
        await client.close()
        await executor.close()


async def test_tracing_disabled_kills_the_subsystem(tmp_path):
    """APP_TRACING_ENABLED=0: no spans, no trace ids anywhere — but request
    ids still correlate responses to logs."""
    client, executor = await make_client(tmp_path, tracing_enabled=False)
    try:
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "print('ok')"},
            headers={"traceparent": TRACEPARENT},
        )
        assert resp.status == 200
        body = await resp.json()
        assert "trace_id" not in body["phases"]
        assert "X-Trace-Id" not in resp.headers
        assert resp.headers["X-Request-Id"]
        assert len(executor.tracer.ring) == 0
        resp = await client.get(f"/traces/{TRACE_ID}")
        assert resp.status == 404
    finally:
        await client.close()
        await executor.close()


async def test_unsampled_trace_propagates_but_records_nothing(tmp_path):
    client, executor = await make_client(tmp_path, tracing_sample_ratio=0.0)
    try:
        resp = await client.post(
            "/v1/execute", json={"source_code": "print('ok')"}
        )
        assert resp.status == 200
        body = await resp.json()
        # Ids exist (downstream propagation) but nothing was recorded.
        trace_id = resp.headers.get("X-Trace-Id")
        assert trace_id
        assert body["phases"]["trace_id"] == trace_id
        assert len(executor.tracer.ring) == 0
        assert (await client.get(f"/traces/{trace_id}")).status == 404
    finally:
        await client.close()
        await executor.close()


async def test_bad_trace_id_rejected(tmp_path):
    client, executor = await make_client(tmp_path)
    try:
        resp = await client.get("/traces/not-hex")
        assert resp.status == 400
    finally:
        await client.close()
        await executor.close()
