"""Multi-host slice e2e through the local backend (SURVEY.md §7.6).

Two layers, both hardware-free:

1. Fan-out mechanics with the warm runner's JAX import disabled: uploads
   reach every host, /execute fires on every host, per-host output files are
   all captured, stdout comes from host 0, a non-zero exit on any host fails
   the Execute.
2. The real thing on the CPU platform: two executor processes bootstrap one
   jax.distributed cluster (gloo collectives), user code sees the global
   device view and runs a cross-host collective — exactly the flow a v5e-16
   slice uses with ICI instead of gloo.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")


from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage


def _config(tmp_path, **kwargs) -> Config:
    defaults = dict(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=0,
        tpu_chips_per_host=1,  # every "chip" is its own local host process
        jax_compilation_cache_dir="",
    )
    defaults.update(kwargs)
    return Config(**defaults)


@pytest.fixture
async def mechanics_executor(tmp_path):
    config = _config(tmp_path)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    yield executor
    await executor.close()


async def test_fanout_mechanics(mechanics_executor):
    executor = mechanics_executor
    # files uploaded once are visible on every host; each host writes its own
    # output; stdout is host 0's
    object_id = await executor.storage.write(b"shared input\n")
    result = await executor.execute(
        "import os\n"
        "host = os.environ.get('APP_HOST_ID', '?')\n"
        "assert open('shared.txt').read() == 'shared input\\n'\n"  # cwd=workspace
        "with open(f'host{host}.txt', 'w') as f:\n"
        "    f.write(f'from host {host}')\n"
        "print(f'hello from host {host}')\n",
        files={"/workspace/shared.txt": object_id},
        chip_count=2,
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "hello from host 0\n"
    assert set(result.files) >= {"/workspace/host0.txt", "/workspace/host1.txt"}
    data = await executor.storage.read(result.files["/workspace/host1.txt"])
    assert data == b"from host 1"


async def test_group_recycled_across_generations(tmp_path):
    """A multi-host slice group is reused whole across sandbox generations:
    both hosts reset, both keep the same processes (the jax.distributed
    membership — re-forming it would cost a full group respawn), and the
    second request sees pristine workspaces on every host."""
    import asyncio

    config = _config(tmp_path, executor_pod_queue_target_length=1)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        await executor.fill_pool(2)
        procs_before = {h: p.pid for h, (p, _) in backend._procs.items()}
        assert len(procs_before) == 2

        first = await executor.execute(
            "import os\nopen(f\"left{os.environ['APP_HOST_ID']}.txt\", 'w')"
            ".write('x')\nprint('gen1')\n",
            chip_count=2,
        )
        assert first.exit_code == 0, first.stderr
        for _ in range(200):
            pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
            if not pending:
                break
            await asyncio.gather(*pending, return_exceptions=True)

        second = await executor.execute(
            "import os\nprint(sorted(os.listdir('.')))\n", chip_count=2
        )
        assert second.exit_code == 0, second.stderr
        assert second.stdout.strip() == "[]"  # every host's workspace wiped
        procs_after = {h: p.pid for h, (p, _) in backend._procs.items()}
        assert procs_after == procs_before  # same group, no respawn
    finally:
        await executor.close()


async def test_fanout_streaming_host0(mechanics_executor):
    """Streaming on a multi-host sandbox: host 0 streams its chunks live,
    peers run normally, and the merged final result (host-0 stdout, all
    hosts' files) matches the non-streamed fan-out semantics."""
    executor = mechanics_executor
    chunks = []
    final = None
    async for event in executor.execute_stream(
        "import os\n"
        "print('from host', os.environ.get('APP_HOST_ID'), flush=True)\n"
        "open(f\"peer{os.environ.get('APP_HOST_ID')}.txt\", 'w').write('x')\n",
        chip_count=2,
    ):
        if "result" in event:
            final = event["result"]
        else:
            chunks.append(event)
    assert final is not None
    assert final.exit_code == 0, final.stderr
    assert final.stdout == "from host 0\n"  # host 0 is the streamed host
    joined = "".join(c["data"] for c in chunks if c["stream"] == "stdout")
    assert joined == "from host 0\n"
    # Peers' side effects still captured even though only host 0 streamed.
    assert set(final.files) >= {"/workspace/peer0.txt", "/workspace/peer1.txt"}


async def test_fanout_peer_failure_fails_execute(mechanics_executor):
    result = await mechanics_executor.execute(
        "import os, sys\n"
        "if os.environ.get('APP_HOST_ID') == '1':\n"
        "    print('boom on host 1', file=sys.stderr)\n"
        "    sys.exit(3)\n"
        "print('host 0 fine')\n",
        chip_count=2,
    )
    assert result.exit_code == 3
    assert result.stdout == "host 0 fine\n"
    assert "[host 1]" in result.stderr and "boom on host 1" in result.stderr


async def test_single_host_lane_unaffected(mechanics_executor):
    result = await mechanics_executor.execute("print(21 * 2)", chip_count=0)
    assert result.exit_code == 0
    assert result.stdout == "42\n"


async def test_jax_distributed_two_host_slice(tmp_path, monkeypatch):
    """Full coordinator bootstrap: 2 hosts × CPU, gloo collectives, global
    mesh visible to user code with zero user cooperation."""
    # 1 CPU device per host process (not the conftest's 8) → 2 gloo ranks,
    # much faster rendezvous; the sandbox env inherits this.
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    config = _config(tmp_path, executor_pod_ready_timeout=180.0)
    backend = LocalSandboxBackend(config, warm_import_jax=True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        result = await executor.execute(
            # The mesh is pre-established by the warm runner before this code
            # runs; user code just uses jax as if the slice were one machine.
            "import jax, jax.numpy as jnp, numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
            "assert jax.process_count() == 2, jax.process_count()\n"
            "mesh = Mesh(np.array(jax.devices()), ('d',))\n"
            "sharding = NamedSharding(mesh, P('d'))\n"
            "n = len(jax.devices())\n"
            "local = np.ones(n // 2, np.float32) * (jax.process_index() + 1)\n"
            "x = jax.make_array_from_process_local_data(sharding, local, (n,))\n"
            "total = jax.jit(lambda v: jnp.sum(v), out_shardings=NamedSharding(mesh, P()))(x)\n"
            "print('total:', float(total))\n"
            "with open(f'host{jax.process_index()}.ok', 'w') as f:\n"  # cwd=workspace
            "    f.write('ok')\n",
            chip_count=2,
            timeout=240.0,
        )
        assert result.exit_code == 0, result.stderr[-2000:]
        # devices split evenly: sum = n/2 * 1 + n/2 * 2 = 1.5n; n = 2 local
        # device counts — just check the line exists and both hosts ran
        assert "total:" in result.stdout
        assert set(result.files) >= {"/workspace/host0.ok", "/workspace/host1.ok"}
    finally:
        await executor.close()
