"""End-to-end sandbox reuse through the real local backend + C++ executor.

The TPU lease (warm executor process) must survive generation turnover while
each Execute still sees a pristine sandbox — fresh workspace, clean env, no
module shadows, no stray processes (VERDICT r2 #1).
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")


from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage


@pytest.fixture
async def stack(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    yield executor, backend
    await executor.close()


async def _settle(executor):
    import asyncio

    for _ in range(200):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


async def test_process_reused_and_workspace_isolated(stack):
    executor, backend = stack
    await executor.fill_pool()
    procs_before = {
        host_id: proc.pid for host_id, (proc, _) in backend._procs.items()
    }
    assert len(procs_before) == 1

    first = await executor.execute(
        "import os\n"
        "open('state.txt', 'w').write('gen1')\n"
        "os.environ['GEN'] = '1'\n"
        "print(os.getpid())\n"
    )
    assert first.exit_code == 0, first.stderr
    await _settle(executor)

    second = await executor.execute(
        "import os\n"
        "print(sorted(os.listdir('.')))\n"
        "print(os.environ.get('GEN'))\n"
        "print(os.getpid())\n"
    )
    assert second.exit_code == 0, second.stderr
    await _settle(executor)

    lines = second.stdout.splitlines()
    assert lines[0] == "[]"  # generation 1's files are gone
    assert lines[1] == "None"  # generation 1's env is gone
    # Same warm process served both generations (the lease survived): the
    # warm runner executes in-process, so the user-visible pid IS the
    # runner's pid.
    assert first.stdout.strip() == lines[2]
    procs_after = {
        host_id: proc.pid for host_id, (proc, _) in backend._procs.items()
    }
    assert procs_after == procs_before

    # Pool-pop latency, not respawn latency (VERDICT r2 #1 done-criterion).
    assert second.phases["queue_wait"] < max(first.phases["queue_wait"] * 10, 0.05)


async def test_timeout_poisons_sandbox_but_service_recovers(stack):
    executor, backend = stack
    await executor.fill_pool()
    result = await executor.execute("while True: pass", timeout=1)
    assert result.exit_code == -1
    assert "timed out" in result.stderr
    await _settle(executor)
    # The timed-out sandbox's runner was killed — /reset refuses, the
    # process is disposed, and the pool refills with a fresh spawn.
    result = await executor.execute("print('recovered')")
    assert result.exit_code == 0
    assert result.stdout == "recovered\n"


async def test_health_sweep_replaces_dead_pooled_sandbox(stack):
    """A pooled sandbox whose process dies silently is detected by the
    health sweep, disposed, and its lane refilled — the next request never
    sees it."""
    import os
    import signal

    executor, backend = stack
    await executor.fill_pool()
    (host_id, (proc, _)), = backend._procs.items()
    # Kill the sandbox's process group behind the backend's back (an
    # OOM-kill stand-in) — the pool still holds the dead sandbox.
    os.killpg(proc.pid, signal.SIGKILL)
    await proc.wait()
    assert len(executor._pool(0)) == 1

    removed = await executor.sweep_pool_health()
    assert removed == 1
    await _settle(executor)
    assert len(executor._pool(0)) == 1  # lane refilled with a live sandbox
    result = await executor.execute("print('alive')")
    assert result.exit_code == 0
    assert result.stdout == "alive\n"


async def test_file_outputs_per_generation(stack):
    """Changed-file capture works per generation: each request only sees its
    own writes even though the workspace directory object is shared."""
    executor, backend = stack
    await executor.fill_pool()
    first = await executor.execute("open('a.txt', 'w').write('A')")
    await _settle(executor)
    second = await executor.execute("open('b.txt', 'w').write('B')")
    assert set(first.files) == {"/workspace/a.txt"}
    assert set(second.files) == {"/workspace/b.txt"}
