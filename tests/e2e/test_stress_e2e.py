"""Concurrency stress over the full local stack: mixed success / user-error /
timeout / file-writing requests racing through the pool's recycle machinery.

The reference had nothing like this (SURVEY.md §5: no race detection); the
asyncio pool bookkeeping (in-use accounting, event wakeups, recycle-vs-
dispose races, slot lifecycle) is exactly the code a sequential test cannot
falsify, so this drives it with a burst of interleaved outcomes and then
audits the end state: correct per-request results, isolated workspaces,
bounded live processes, empty in-use/spawning counters.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")

import asyncio


from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage

REQUESTS = 32


@pytest.fixture
async def stack(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=3,
        jax_compilation_cache_dir="",
        default_execution_timeout=30.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    yield executor, backend
    await executor.close()


async def _settle(executor):
    for _ in range(400):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


async def test_mixed_burst_races_pool_machinery(stack):
    executor, backend = stack
    await executor.fill_pool()

    def source_for(i: int) -> tuple[str, int]:
        """(source, expected_exit_code) per request flavor."""
        flavor = i % 4
        if flavor == 0:  # plain success
            return f"print('req-{i}')", 0
        if flavor == 1:  # writes a uniquely-named file
            return (
                f"import os\nopen('out-{i}.txt', 'w').write('{i}')\n"
                f"print(len(os.listdir('.')))",
                0,
            )
        if flavor == 2:  # user error (sandbox stays healthy)
            return f"raise RuntimeError('req-{i} boom')", 1
        return f"import sys\nprint('req-{i}')\nsys.exit({i % 7})", i % 7

    expected = [source_for(i) for i in range(REQUESTS)]
    results = await asyncio.gather(
        *(executor.execute(src) for src, _ in expected)
    )

    for i, (result, (_, want_exit)) in enumerate(zip(results, expected)):
        assert result.exit_code == want_exit, (
            f"req {i}: exit {result.exit_code} != {want_exit}: "
            f"{result.stderr[-200:]}"
        )
        flavor = i % 4
        if flavor == 0:
            assert result.stdout == f"req-{i}\n"
        elif flavor == 1:
            # Workspace isolation under recycling: this request saw exactly
            # its own file, nothing from any other generation.
            assert result.stdout == "1\n", result.stdout
            assert set(result.files) == {f"/workspace/out-{i}.txt"}
        elif flavor == 2:
            assert f"req-{i} boom" in result.stderr

    await _settle(executor)
    # End-state audit: no runaway processes, consistent accounting. The
    # bound is the LANE TARGET — since the autoscaler, the burst itself
    # legitimately raises it (retained warm supply for the next wave, up
    # to APP_POOL_MAX_TARGET); runaway means exceeding even that.
    target = executor._lane_target(0)
    assert len(backend._procs) <= target
    assert sum(len(pool) for pool in executor._pools.values()) <= target
    assert all(v == 0 for v in executor._in_use.values())
    assert all(v == 0 for v in executor._spawning.values())
    assert all(
        executor.scheduler.queued(lane) == 0 for lane in executor._pools
    )


async def test_timeout_storm_recovers(stack):
    """A wave of timeouts poisons every runner at once; the service must
    dispose them all and still serve fresh requests afterwards."""
    executor, backend = stack
    await executor.fill_pool()
    storm = await asyncio.gather(
        *(executor.execute("while True: pass", timeout=1) for _ in range(4))
    )
    assert all(r.exit_code == -1 for r in storm)
    assert all("timed out" in r.stderr for r in storm)
    await _settle(executor)
    after = await asyncio.gather(
        *(executor.execute(f"print({i})") for i in range(4))
    )
    assert [r.stdout for r in after] == ["0\n", "1\n", "2\n", "3\n"]
