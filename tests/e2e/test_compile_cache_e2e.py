"""End-to-end fleet compile cache through the real local backend + C++
executor: a kernel compiled by one TRUSTED (pre-warm-style) sandbox run is
harvested into the fleet store at that sandbox's teardown and seeded into a
FRESH sandbox before its user code runs — with the first sandbox already
disposed. Per-sandbox cache dirs + reuse off reproduce the Kubernetes
pod-local reality where the fleet store is the ONLY cross-sandbox channel.

Harvest is provenance-gated: only control-plane-authored runs (driven here
via executor._execute_trusted, the pre-warm mechanism) are harvestable;
tenant executes taint their sandbox and nothing it holds ever enters the
fleet store — covered by its own leg below.

The fast legs use a synthetic cache entry (code writing into
$JAX_COMPILATION_CACHE_DIR stands in for XLA's cache writer — byte-for-byte
the same protocol surface). The slow leg compiles a real jitted kernel and
proves zero recompilation via the runner's jax.monitoring hit counter.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")

import asyncio  # noqa: E402

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

WRITE_ENTRY = """
import os
d = os.environ["JAX_COMPILATION_CACHE_DIR"]
path = os.path.join(d, "jit_popular_kernel-e2e-cache")
existed = os.path.exists(path)
if not existed:
    open(path, "wb").write(b"compiled-executable-bytes" * 10)
print("hit" if existed else "miss")
"""


def make_stack(tmp_path, *, warm_import_jax=False, **config_overrides):
    defaults = dict(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        # No warm pool: every execute spawns (and disposes) its own
        # sandbox, so seed-at-spawn and harvest-at-teardown interleave
        # deterministically (a pooled replacement would race the harvest).
        executor_pod_queue_target_length=0,
        jax_compilation_cache_dir=str(tmp_path / "unused-shared-cache"),
        compile_cache_per_sandbox=True,  # pod-local reality
        executor_reuse_sandboxes=False,  # every execute = a fresh sandbox
        default_execution_timeout=60.0,
    )
    defaults.update(config_overrides)
    config = Config(**defaults)
    backend = LocalSandboxBackend(config, warm_import_jax=warm_import_jax)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    return executor, backend


async def _settle(executor):
    for _ in range(200):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


async def test_disposed_sandboxs_kernel_reused_by_fresh_sandbox(tmp_path):
    executor, backend = make_stack(tmp_path)
    try:
        # The compiling run is control-plane-authored (the pre-warm
        # mechanism) — the only provenance harvest admits.
        first = await executor._execute_trusted(WRITE_ENTRY)
        assert first.exit_code == 0, first.stderr
        assert first.stdout.strip() == "miss"  # sandbox 1 had to "compile"
        await _settle(executor)
        # Sandbox 1 is gone (reuse off => disposed) and its kernel was
        # harvested into the fleet store at teardown.
        assert backend._procs == {}
        manifest = executor.compile_cache.manifest()
        assert "jit_popular_kernel-e2e-cache" in manifest

        second = await executor.execute(WRITE_ENTRY)
        assert second.exit_code == 0, second.stderr
        # THE acceptance criterion: the fresh TENANT sandbox found the
        # kernel already in its cache dir — seeded at spawn from the fleet
        # store, zero recompilation.
        assert second.stdout.strip() == "hit"
        assert second.phases["compile_cache_seeded_bytes"] > 0
        await _settle(executor)
    finally:
        await executor.close()


async def test_tenant_compiled_entry_never_reaches_other_sandboxes(tmp_path):
    """The cache-poisoning regression: a TENANT run that writes into its
    cache dir is never harvested — the fleet store stays empty and a fresh
    sandbox sees a cold cache (no cross-tenant executable channel)."""
    executor, backend = make_stack(tmp_path)
    try:
        first = await executor.execute(WRITE_ENTRY)
        assert first.exit_code == 0, first.stderr
        assert first.stdout.strip() == "miss"
        await _settle(executor)
        assert backend._procs == {}
        assert executor.compile_cache.manifest() == {}

        second = await executor.execute(WRITE_ENTRY)
        assert second.exit_code == 0, second.stderr
        # The next tenant's sandbox was NOT seeded with the first tenant's
        # planted entry.
        assert second.stdout.strip() == "miss"
        await _settle(executor)
    finally:
        await executor.close()


async def test_kill_switch_restores_pre_cache_behavior(tmp_path):
    executor, backend = make_stack(tmp_path, compile_cache_enabled=False)
    try:
        # Even a trusted run moves nothing with the switch off.
        first = await executor._execute_trusted(WRITE_ENTRY)
        assert first.exit_code == 0, first.stderr
        assert first.stdout.strip() == "miss"
        await _settle(executor)
        assert executor.compile_cache.manifest() == {}

        second = await executor.execute(WRITE_ENTRY)
        assert second.exit_code == 0, second.stderr
        # No fleet cache: the fresh sandbox recompiles, exactly as before.
        assert second.stdout.strip() == "miss"
        assert "compile_cache_seeded_bytes" not in second.phases
        await _settle(executor)
    finally:
        await executor.close()


async def test_harvest_and_seed_counters_move(tmp_path):
    executor, backend = make_stack(tmp_path)
    try:
        first = await executor._execute_trusted(WRITE_ENTRY)
        assert first.exit_code == 0
        # The executor reported the new cache entry on the execute itself.
        assert first.phases.get("compile_cache_new_bytes", 0) > 0
        await _settle(executor)
        render = executor.metrics.registry.render()
        assert (
            'code_interpreter_compile_cache_bytes_total{direction="harvest"}'
            in render
        )
        second = await executor.execute("print('warm')")
        await _settle(executor)
        assert (
            'code_interpreter_compile_cache_bytes_total{direction="seed"}'
            in render or second.phases.get("compile_cache_seeded_bytes", 0) > 0
        )
    finally:
        await executor.close()


@pytest.mark.slow
async def test_real_jit_kernel_zero_recompilation(tmp_path):
    """The full story with a real XLA compile: sandbox 1 jits a matmul
    (persistent cache write), dies; its local cache dir is wiped (modeling
    the next pod's empty emptyDir — sandbox 1 AND its cache are gone, the
    fleet store holds the only copy); sandbox 2 is seeded from the store
    and the runner's jax.monitoring listener reports persistent-cache HITS
    with no new cache entries — zero recompilation across disposed
    sandboxes.

    Shared-path mode on purpose: jax hashes the cache-dir PATH into its
    cache key, so fleet-wide hits require the fleet-constant cache path
    production has (every pod mounts the cache at the same mountPath);
    per-sandbox paths would change the keys themselves."""
    pytest.importorskip("jax")
    import shutil

    cache_dir = tmp_path / "pod-cache-path"
    # Warm jax import: the runner's jax.monitoring listener (which reports
    # the per-request hit/miss counts this test asserts on) registers
    # during the warm import.
    executor, backend = make_stack(
        tmp_path,
        warm_import_jax=True,
        compile_cache_per_sandbox=False,
        jax_compilation_cache_dir=str(cache_dir),
    )
    source = (
        "import jax, jax.numpy as jnp\n"
        "f = jax.jit(lambda a, b: a @ b)\n"
        "x = jnp.ones((128, 128), dtype=jnp.float32)\n"
        "f(x, x).block_until_ready()\n"
        "print('ran')\n"
    )
    try:
        # The compile happens on a trusted (pre-warm-style) run — harvest
        # only admits those.
        first = await executor._execute_trusted(source, timeout=300.0)
        assert first.exit_code == 0, first.stderr
        assert first.phases.get("compile_cache_new_bytes", 0) > 0
        await _settle(executor)
        assert backend._procs == {}  # sandbox 1 disposed
        assert executor.compile_cache.entry_count() > 0
        # The "pod" and its local cache are both gone; only the fleet
        # store survives.
        shutil.rmtree(cache_dir)

        second = await executor.execute(source, timeout=300.0)
        assert second.exit_code == 0, second.stderr
        assert second.phases.get("compile_cache_seeded_bytes", 0) > 0
        # Seeded kernels served the whole run: hits, no fresh misses that
        # produced new cache entries.
        assert second.phases.get("compile_cache_hits", 0) > 0
        assert second.phases.get("compile_cache_new_bytes", 1) == 0
        await _settle(executor)
    finally:
        await executor.close()


@pytest.mark.slow
async def test_new_prewarm_kernel_harvests_in_trusted_epoch(tmp_path):
    """The PREWARM_SOURCES growth contract (carried follow-up from PR 6:
    fleet coverage scales only with this set): the newly added
    small_matmul_chain kernel — the batch bench's hot small-array shape —
    compiles on a trusted (pre-warm) run, harvests into the fleet store in
    the trusted epoch, and a later TENANT run of the same shape hits the
    seeded cache with zero recompilation."""
    pytest.importorskip("jax")
    import shutil

    from bee_code_interpreter_fs_tpu.services.compile_cache import (
        PREWARM_SOURCES,
    )

    sources = dict(PREWARM_SOURCES)
    assert "small_matmul_chain" in sources  # the satellite's new entry
    cache_dir = tmp_path / "pod-cache-path"
    executor, backend = make_stack(
        tmp_path,
        warm_import_jax=True,
        compile_cache_per_sandbox=False,
        jax_compilation_cache_dir=str(cache_dir),
    )
    try:
        trusted = await executor._execute_trusted(
            sources["small_matmul_chain"], timeout=300.0
        )
        assert trusted.exit_code == 0, trusted.stderr
        assert "prewarm small_matmul_chain ok" in trusted.stdout
        # The trusted run COMPILED it (fresh store, fresh dir)...
        assert trusted.phases.get("compile_cache_new_bytes", 0) > 0
        await _settle(executor)
        # ...and teardown harvested it into the fleet store while the
        # epoch was still trusted (no tenant code has run).
        assert backend._procs == {}
        assert executor.compile_cache.entry_count() > 0

        # The sandbox and its local cache are both gone; only the fleet
        # store survives to seed the next spawn.
        shutil.rmtree(cache_dir)
        tenant = await executor.execute(
            sources["small_matmul_chain"], timeout=300.0
        )
        assert tenant.exit_code == 0, tenant.stderr
        assert tenant.phases.get("compile_cache_seeded_bytes", 0) > 0
        assert tenant.phases.get("compile_cache_hits", 0) > 0
        assert tenant.phases.get("compile_cache_new_bytes", 1) == 0
        await _settle(executor)
    finally:
        await executor.close()
