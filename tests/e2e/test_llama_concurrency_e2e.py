"""BASELINE config 5 scale-down: 16 concurrent Llama-class Executes.

The capstone concurrency story (SURVEY.md §7.6, BASELINE.md config 5:
"Llama-2-7B JAX inference via Execute, 16 concurrent requests") previously
existed only as an unexecuted benchmark script (VERDICT r1 #10). This drives
16 simultaneous Executes of the in-repo Llama model — each through the full
stack: orchestrator → pool → C++ executor server → warm JAX runner — on the
CPU-forced test platform, asserting every request succeeds and the pool
neither leaks sandboxes nor serializes the burst.
"""

# Optional-dep guard: a missing dependency must degrade this module to a
# SKIP at collection, not an ERROR that interrupts the whole run.
import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")

import asyncio
import re
import time


from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.local import LocalSandboxBackend
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.storage import Storage

CONCURRENCY = 16

# Tiny Llama-class forward, self-shrunk for CI: the same model family and
# code path as benchmarks/run_configs.py LLAMA_INFER, smaller shapes.
LLAMA_SNIPPET = """
import jax, jax.numpy as jnp
from bee_code_interpreter_fs_tpu.models.llama import LlamaConfig, init_params, forward

cfg = LlamaConfig.tiny(n_layers=2, dim=128, n_heads=4, n_kv_heads=4,
                       hidden_dim=352, vocab_size=512, max_seq_len=64)
params = init_params(jax.random.PRNGKey(0), cfg)
tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 64), 0, cfg.vocab_size)
fwd = jax.jit(lambda p, t: forward(p, t, cfg))
out = fwd(params, tokens)
out.block_until_ready()
print("llama_ok shape=%s" % (tuple(out.shape),))
"""


@pytest.fixture
async def llama_executor(tmp_path):
    config = Config(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=4,
        default_execution_timeout=240.0,
        jax_compilation_cache_dir=str(tmp_path / "jax-cache"),
    )
    backend = LocalSandboxBackend(config, warm_import_jax=True, numpy_dispatch=True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    yield executor, backend
    await executor.close()


async def test_16_concurrent_llama_executes(llama_executor):
    executor, backend = llama_executor
    await executor.fill_pool()
    t0 = time.perf_counter()
    results = await asyncio.gather(
        *(
            executor.execute(LLAMA_SNIPPET, timeout=240.0)
            for _ in range(CONCURRENCY)
        )
    )
    wall = time.perf_counter() - t0

    failures = [r for r in results if r.exit_code != 0]
    assert not failures, f"{len(failures)} failed; first stderr: " + (
        failures[0].stderr[-800:] if failures else ""
    )
    for r in results:
        assert re.search(r"llama_ok shape=\(1, 64, 512\)", r.stdout), r.stdout

    # The burst must actually run concurrently. Full serialization would put
    # wall at ~the sum of the exec phases; require clear overlap. (Bounding
    # against min-exec × N broke once reuse landed: a recycled warm sandbox
    # makes the fastest exec far faster than the burst's cold average, so
    # the old bound tightened for the wrong reason.)
    serialized_total = sum(r.phases["exec"] for r in results)
    assert wall < 0.75 * serialized_total, (
        f"wall {wall:.1f}s vs serialized total {serialized_total:.1f}s — "
        "the burst did not overlap"
    )

    # Pool hygiene: disposals drain; nothing leaks past close() (checked by
    # the fixture teardown), and live processes stay bounded by the LANE
    # TARGET — dynamic since the autoscaler (the burst legitimately raises
    # it to retain warm supply), so runaway means exceeding even that.
    await asyncio.gather(*executor._dispose_tasks, return_exceptions=True)
    await asyncio.gather(*executor._fill_tasks, return_exceptions=True)
    assert len(backend._procs) <= executor._lane_target(0)
