"""End-to-end performance-anomaly-plane acceptance (ISSUE 14): HTTP API →
orchestrator → real C++ executors (local backend) with a seeded
``slow_exec`` fault regressing ONE lane.

The acceptance criterion, verbatim: with a seeded slow_exec fault on one
lane, the drift detector flips that (lane, exec) series to ``regressed``
within one window while the healthy lane stays ``normal``;
``perf_regression_total`` fires and the ``perf.regression`` span is
retrievable via /traces at 0% head sampling; the next eligible request on
the flagged lane is auto-profiled, its artifact appears under
``GET /profiles`` cross-linked to its trace id, and the tenant's ledger
shows zero transfer bytes for the harvest; every request's Result.phases
carries ``peak_hbm_bytes``; the ``APP_PERF_OBSERVER_ENABLED=0`` run shows
zero perf surfaces and byte-identical serving behavior.
"""

import asyncio

import pytest

pytest.importorskip("httpx", reason="optional e2e dependency not installed")
pytest.importorskip("aiohttp", reason="optional e2e dependency not installed")

import httpx
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_fs_tpu.config import Config
from bee_code_interpreter_fs_tpu.services.backends.faults import (
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.backends.local import (
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_fs_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_fs_tpu.services.http_server import create_http_app
from bee_code_interpreter_fs_tpu.services.storage import Storage

SLOW_LANE = 2
HEALTHY_LANE = 0
TENANT = "perf-acct"
# The window must FIT a burst of sequential slow requests: at ~0.45s per
# slowed round-trip, five of them take ~2.3s — a shorter window would
# scatter them into sub-min_samples slivers the detector rightly ignores.
WINDOW_S = 2.5
SLOW_S = 0.4


def _config(tmp_path, **overrides) -> Config:
    defaults = dict(
        file_storage_path=str(tmp_path / "storage"),
        local_sandbox_root=str(tmp_path / "sandboxes"),
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        compile_cache_enabled=False,
        batching_enabled=False,
        default_execution_timeout=60.0,
        # 0% HEAD sampling: the perf.regression record_span must still be
        # retrievable (the device-health transition discipline).
        tracing_sample_ratio=0.0,
        tracing_tail_enabled=False,
        executor_fault_spec=(
            f"slow_exec:1.0,slow_exec_lane:{SLOW_LANE},"
            f"slow_exec_seconds:{SLOW_S},seed:7"
        ),
        perf_window_seconds=WINDOW_S,
        perf_min_window_samples=3,
        perf_min_band_seconds=0.05,
        perf_profile_min_interval_seconds=0.0,
    )
    defaults.update(overrides)
    return Config(**defaults)


async def _build_stack(config):
    backend = FaultInjectingBackend(
        LocalSandboxBackend(config, warm_import_jax=False),
        FaultSpec.parse(config.executor_fault_spec),
    )
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    # Hold the fault transport so the test can turn the regression ON at a
    # chosen moment (a fault active from the first request would BECOME
    # the baseline — the detector is right to call that normal).
    transport = backend.http_transport()
    transport.rate = 0.0
    executor._client = httpx.AsyncClient(transport=transport, timeout=90.0)
    app = create_http_app(executor, CustomToolExecutor(executor), storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, executor, transport


async def _execute(client, lane: int, tenant: str | None = None) -> dict:
    payload: dict = {"source_code": "print('tick')", "chip_count": lane}
    if tenant is not None:
        payload["tenant"] = tenant
    resp = await client.post("/v1/execute", json=payload)
    assert resp.status == 200, await resp.text()
    body = await resp.json()
    assert body["exit_code"] == 0, body
    return body


async def _window(client, lane: int, n: int = 5, tenant=None) -> list[dict]:
    bodies = [await _execute(client, lane, tenant) for _ in range(n)]
    await asyncio.sleep(WINDOW_S + 0.1)
    return bodies


def _perf_state(executor, lane: int) -> str:
    return executor.perf.lane_phase_states().get(f"{lane}/exec", "absent")


async def test_perf_anomaly_plane_end_to_end(tmp_path):
    config = _config(tmp_path)
    client, executor, transport = await _build_stack(config)
    try:
        # ---- baseline: both lanes healthy over two full windows.
        for _ in range(2):
            await _window(client, HEALTHY_LANE)
            await _window(client, SLOW_LANE, tenant=TENANT)
        body = await _execute(client, HEALTHY_LANE)
        # Every request's phases carries the device-memory attribution.
        assert "peak_hbm_bytes" in body["phases"], body["phases"]
        assert "live_buffer_bytes_delta" in body["phases"]
        await _execute(client, SLOW_LANE, tenant=TENANT)
        assert _perf_state(executor, HEALTHY_LANE) == "normal"
        assert _perf_state(executor, SLOW_LANE) == "normal"

        # ---- the regression: the seeded fault lands on the slow lane.
        transport.rate = 1.0
        await _window(client, SLOW_LANE, tenant=TENANT)
        await _window(client, HEALTHY_LANE)
        # The roll-triggering records: one per lane.
        await _execute(client, SLOW_LANE, tenant=TENANT)
        await _execute(client, HEALTHY_LANE)
        # Within ONE window the slowed lane flipped; the healthy one held.
        assert _perf_state(executor, SLOW_LANE) == "regressed"
        assert _perf_state(executor, HEALTHY_LANE) == "normal"
        # perf_regression_total{lane,phase} fired.
        samples = {
            (labels["lane"], labels["phase"]): value
            for labels, value in executor.metrics.perf_regressions.samples()
        }
        assert samples.get((str(SLOW_LANE), "exec"), 0) >= 1
        assert (str(HEALTHY_LANE), "exec") not in samples
        # The perf.regression span is retrievable via /traces at 0% head
        # sampling: find it in the ring, then fetch its trace over HTTP.
        spans = [
            s
            for s in list(executor.tracer.ring._spans)
            if s.get("name") == "perf.regression"
        ]
        assert spans, "perf.regression must bypass head sampling"
        resp = await client.get(f"/traces/{spans[-1]['trace_id']}")
        assert resp.status == 200
        trace_body = await resp.json()
        assert any(
            s["name"] == "perf.regression" for s in trace_body["spans"]
        )

        # ---- auto-profiling: the next eligible request on the flagged
        # lane runs with the JAX profiler armed and its artifact is
        # harvested (not returned to the tenant, not billed).
        ledger_before = executor.usage.tenant_snapshot(TENANT)
        profiled = await _execute(client, SLOW_LANE, tenant=TENANT)
        assert "/workspace/profile.zip" not in profiled["files"], (
            "the auto-captured artifact must be harvested, not returned"
        )
        resp = await client.get("/profiles")
        assert resp.status == 200
        listing = await resp.json()
        assert listing["total"] >= 1
        row = listing["profiles"][0]
        assert row["lane"] == SLOW_LANE
        assert row["tenant"] == TENANT
        assert row["reason"].startswith("regression:")
        # Cross-linked to the triggering request's trace id.
        assert row["trace_id"] == profiled["phases"]["trace_id"]
        resp = await client.get(f"/profiles/{row['id']}")
        assert resp.status == 200
        artifact = await resp.read()
        assert artifact[:2] == b"PK", "profile.zip must be a real zip"
        assert resp.headers["X-Trace-Id"] == row["trace_id"]
        # Zero transfer bytes billed for the harvest: the tenant's
        # download counter did not move (the profile.zip was this
        # workload's only changed file).
        ledger_after = executor.usage.tenant_snapshot(TENANT)
        assert (
            ledger_after["download_bytes"]
            == ledger_before["download_bytes"]
            == 0.0
        )
        # The statusz perf section shows the standing verdict.
        resp = await client.get("/statusz", params={"format": "text"})
        text = await resp.text()
        assert f"!!{SLOW_LANE}/exec: [regressed]" in text
    finally:
        await client.close()
        await executor.close()


async def test_kill_switch_restores_todays_behavior(tmp_path):
    config = _config(
        tmp_path, perf_observer_enabled=False, executor_fault_spec=""
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    storage = Storage(config.file_storage_path)
    executor = CodeExecutor(backend, storage, config)
    app = create_http_app(executor, CustomToolExecutor(executor), storage)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        body = await _execute(client, 0, tenant=TENANT)
        # Zero perf surface: no device-memory keys in phases, no series
        # recorded, 404 on both routes, no perf metric families.
        assert "peak_hbm_bytes" not in body["phases"]
        assert "live_buffer_bytes_delta" not in body["phases"]
        assert executor.perf._series == {}
        assert (await client.get("/perf")).status == 404
        assert (await client.get("/profiles")).status == 404
        metrics_text = (
            await (await client.get("/metrics")).text()
        )
        assert "perf_regression_total" not in metrics_text
        assert "code_interpreter_perf_state" not in metrics_text
        row = executor.usage.tenant_snapshot(TENANT)
        assert row["hbm_byte_seconds"] == 0.0
    finally:
        await client.close()
        await executor.close()
