#!/bin/bash
# DEPRECATED as a health-watching tool (PR 8): the service now has a
# first-party telemetry plane that covers what the probe loop below
# encoded — per-host device-health probing (GET /device-stats on every
# sandbox, classified healthy/busy/suspect/wedged with attach-budget and
# op-stall thresholds), `GET /statusz` (one consolidated operator view:
# `curl $CONTROL_PLANE/statusz?format=text` replaces the ssh-and-grep
# loop), the `device_wedge_detected_total` / `device_health_state`
# metrics, and OTLP export (APP_OTLP_ENDPOINT). See README "Telemetry".
# This script remains ONLY as the standalone bench-suite runner for a
# tunnel-attached chip with no control plane running.
#
# Patient TPU recovery watcher (round 5): probe until an attach succeeds,
# then fire the full on-chip measurement suite, writing results INTO the
# repo so the round-end auto-commit preserves them even if nobody is at
# the keyboard.
#
# Usage: nohup scripts/onchip_watch.sh & (from the repo root; safe to leave
# running — probe attempts end via SIGINT so the client unwinds cleanly;
# abrupt SIGKILLs mid-device-op are what wedge the tunneled device).
# WAIT_PID=<pid>: wait for that process (an older probe mid-attach) to exit
# before probing, so two clients never contend for the attach.
# Operator note from round 4: a persistent wedge (every attach blocking
# 25-75 min then UNAVAILABLE) cleared once at a HOST reboot; if attaches
# keep failing for hours, a reboot of the machine hosting the tunnel relay
# is the known remedy, after which this watcher (relaunched) captures
# everything automatically.
OUT=/root/repo/benchmarks/onchip_r05
LOG=/tmp/tpuprobe/probe.log
mkdir -p "$OUT" /tmp/tpuprobe
cd /root/repo || exit 1

if [ -n "$WAIT_PID" ]; then
  echo "$(date -u +%FT%TZ) waiting for old probe pid=$WAIT_PID" >> "$LOG"
  tail --pid="$WAIT_PID" -f /dev/null 2>/dev/null
fi

while true; do
  # 90 min per attempt (observed wedge blocks 25-76 min); on expiry the
  # probe gets SIGINT first (Python unwinds and says goodbye when it CAN —
  # an attach stuck inside an uninterruptible C call still eats the
  # +60s SIGKILL, so a >90-min attach can still be cut abruptly; the
  # budget is sized well past every observed block to keep that rare).
  timeout --signal=INT --kill-after=60 5400 python -c "
import time
t0=time.time()
import jax
d=jax.devices()
import jax.numpy as jnp
x=jnp.ones((1024,1024), dtype=jnp.bfloat16)
(x@x).block_until_ready()
print('attach+matmul ok in %.1fs' % (time.time()-t0), d, flush=True)
" >> "$LOG" 2>&1
  rc=$?
  echo "$(date -u +%FT%TZ) probe rc=$rc" >> "$LOG"
  if [ $rc -eq 0 ]; then echo ALIVE > /tmp/tpuprobe/status; break; fi
  echo DEAD > /tmp/tpuprobe/status
  sleep 30
done

echo "$(date -u +%FT%TZ) chip recovered; firing on-chip suite" >> "$LOG"
echo "recovered_at: $(date -u +%FT%TZ)" > "$OUT/STATUS.txt"

run_leg() {  # name, timeout, command...
  name=$1; tmo=$2; shift 2
  echo "$(date -u +%FT%TZ) leg $name starting" >> "$LOG"
  PYTHONPATH=/root/repo timeout --signal=INT --kill-after=120 "$tmo" \
    "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  echo "leg $name rc=$?" >> "$OUT/STATUS.txt"
  echo "$(date -u +%FT%TZ) leg $name done" >> "$LOG"
}

# 1. The driver-format bench (headline now ADAPTIVE-sampled: runs until
#    the steady state plateaus — VERDICT r4 #2 — plus matmul/flash/p50/int8).
run_leg bench 1800 python bench.py
# 2. The capstone: 7B-int8 continuous batching, 16 concurrent requests on
#    one resident model (VERDICT r4 #5). Standalone first so the number
#    lands even if the full config suite dies midway.
run_leg serving_7b 1800 python examples/benchmark-serving-7b.py
# 3. Speculative decoding composed into the serving engine (VERDICT r4
#    #8): draft/verify per slot, low- and mid-occupancy speedup rows.
run_leg serving_spec 1200 python examples/benchmark-serving-spec.py
# 4. Full config suite (1-4, 5a-5h incl. int8 ratio, true-7B, speculative,
#    serving engine, the 5h capstone through Execute).
run_leg run_configs 9000 python benchmarks/run_configs.py
# 5. Flash-attention tile sweep at t=16k (VERDICT r4 #3).
for bq in 256 512 1024; do
  for bk in 512 1024 2048; do
    BENCH_BLOCK_Q=$bq BENCH_BLOCK_K=$bk \
      run_leg "flash_q${bq}_k${bk}" 900 python examples/benchmark-attention.py
  done
done
BENCH_SEQ_LEN=32768 run_leg flash_32k 900 python examples/benchmark-attention.py
# 6. True-13B int4 on one chip.
BENCH_MODEL=llama2_13b BENCH_PRECISION=int4 \
  run_leg llama2_13b_int4 1800 python examples/benchmark-7b.py
echo "suite_complete: $(date -u +%FT%TZ)" >> "$OUT/STATUS.txt"
