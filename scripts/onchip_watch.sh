#!/bin/bash
# Patient TPU recovery watcher: probe until an attach succeeds, then fire
# the full on-chip measurement suite, writing results INTO the repo so the
# round-end auto-commit preserves them even if nobody is at the keyboard.
#
# Usage: nohup scripts/onchip_watch.sh & (from the repo root; safe to leave
# running — probe attempts end via SIGINT so the client unwinds cleanly;
# abrupt SIGKILLs mid-device-op are what wedge the tunneled device). Operator note from round 4: a persistent wedge (every
# attach blocking 25-75 min then UNAVAILABLE) cleared once at a HOST
# reboot; if attaches keep failing for hours, a reboot of the machine
# hosting the tunnel relay is the known remedy, after which this watcher
# (relaunched) captures everything automatically.
OUT=/root/repo/benchmarks/onchip_r04
LOG=/tmp/tpuprobe/probe.log
mkdir -p "$OUT" /tmp/tpuprobe
cd /root/repo || exit 1
while true; do
  # 90 min per attempt (observed wedge blocks 25-76 min); on expiry the
  # probe gets SIGINT first (Python unwinds and says goodbye when it CAN —
  # an attach stuck inside an uninterruptible C call still eats the
  # +60s SIGKILL, so a >90-min attach can still be cut abruptly; the
  # budget is sized well past every observed block to keep that rare).
  timeout --signal=INT --kill-after=60 5400 python -c "
import time
t0=time.time()
import jax
d=jax.devices()
import jax.numpy as jnp
x=jnp.ones((1024,1024), dtype=jnp.bfloat16)
(x@x).block_until_ready()
print('attach+matmul ok in %.1fs' % (time.time()-t0), d, flush=True)
" >> "$LOG" 2>&1
  rc=$?
  echo "$(date -u +%FT%TZ) probe rc=$rc" >> "$LOG"
  if [ $rc -eq 0 ]; then echo ALIVE > /tmp/tpuprobe/status; break; fi
  echo DEAD > /tmp/tpuprobe/status
  sleep 30
done

echo "$(date -u +%FT%TZ) chip recovered; firing on-chip suite" >> "$LOG"
echo "recovered_at: $(date -u +%FT%TZ)" > "$OUT/STATUS.txt"

run_leg() {  # name, timeout, command...
  name=$1; tmo=$2; shift 2
  echo "$(date -u +%FT%TZ) leg $name starting" >> "$LOG"
  PYTHONPATH=/root/repo timeout "$tmo" "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  echo "leg $name rc=$?" >> "$OUT/STATUS.txt"
  echo "$(date -u +%FT%TZ) leg $name done" >> "$LOG"
}

# 1. The driver-format bench (headline/matmul/flash/p50/int8).
run_leg bench 1800 python bench.py
# 2. Full config suite (1-4, 5a-5g incl. int8 ratio, true-7B, speculative,
#    serving engine).
run_leg run_configs 7200 python benchmarks/run_configs.py
# 3. Flash-attention tile sweep at t=16k (VERDICT next-4).
for bq in 256 512 1024; do
  for bk in 512 1024 2048; do
    BENCH_BLOCK_Q=$bq BENCH_BLOCK_K=$bk \
      run_leg "flash_q${bq}_k${bk}" 900 python examples/benchmark-attention.py
  done
done
BENCH_SEQ_LEN=32768 run_leg flash_32k 900 python examples/benchmark-attention.py
# 4. True-13B int4 on one chip.
BENCH_MODEL=llama2_13b BENCH_PRECISION=int4 \
  run_leg llama2_13b_int4 1800 python examples/benchmark-7b.py
echo "suite_complete: $(date -u +%FT%TZ)" >> "$OUT/STATUS.txt"
