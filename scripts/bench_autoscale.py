#!/usr/bin/env python3
"""Warm-pool autoscaling microbench: step-load burst waves against the real
local backend + C++ executor, demand-adaptive lane targets vs the static
pool knob.

Workload: WAVES bursts of JOBS concurrent trivial Executes, one wave per
GAP seconds — the step-load shape that made the static pool's weakness
visible in production traces (a burst queues behind one warm sandbox while
spawns catch up one acquire at a time, then the extra sandboxes are thrown
away and the NEXT wave pays the spawns again).

- ``static``     — APP_POOL_AUTOSCALE_ENABLED=0 with the historic target
  of 1: every wave beyond the warm sandbox pays spawn-scale acquire waits,
  and released surplus is disposed back down to 1 between waves.
- ``autoscaled`` — the demand model raises the lane target with the first
  wave, so its sandboxes are RETAINED at release; later waves pop warm.
  After the burst, hysteresis decays the target and the idle reaper
  disposes the excess — the scale-down half of the gate.

Acceptance (ISSUE verbatim, recorded in ``BENCH_autoscale.json``):
- autoscaled p50 acquire wait over the steady waves (wave 2+) <= 0.5x the
  static pool's (wave 1 is identical cold-start in both legs by design);
- idle-chip reaping observable in metrics within the configured window;
- the kill switch reproduces static-pool behavior exactly (target pinned
  at the constant, surplus disposed, zero scale events).

Usage:
    python scripts/bench_autoscale.py [--waves 4] [--jobs 6]
        [--out BENCH_autoscale.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# Never fight a TPU plugin for the chip in a bench by default.
os.environ.setdefault("JAX_PLATFORMS", os.environ.get("BENCH_PLATFORM", "cpu"))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

GAP_S = 1.0  # seconds between waves (the step-load cadence)
SOURCE = "print('ok')"

# Autoscale dynamics knobs for the bench: a short sweep so the bench's
# scale-down window is seconds, with the hysteresis LONGER than the whole
# burst so no decay interferes mid-measurement.
SWEEP_INTERVAL = 0.5
SCALE_DOWN_AFTER = 8.0
IDLE_REAP = 2.0


def make_executor(tmp: Path, *, autoscale: bool, max_target: int) -> CodeExecutor:
    config = Config(
        file_storage_path=str(tmp / "storage"),
        local_sandbox_root=str(tmp / "sandboxes"),
        jax_compilation_cache_dir=str(tmp / "jax-cache"),
        executor_pod_queue_target_length=1,
        pool_autoscale_enabled=autoscale,
        pool_min_target=1,
        pool_max_target=max_target,
        pool_autoscale_interval=SWEEP_INTERVAL,
        pool_scale_down_after=SCALE_DOWN_AFTER,
        pool_idle_reap_seconds=IDLE_REAP,
        compile_cache_prewarm=False,
        default_execution_timeout=120.0,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=True)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


async def settle(executor: CodeExecutor, skip: set | None = None) -> None:
    """Wait out release/refill tasks. `skip` holds long-running sweeper
    tasks (the autoscaler loop lives in _fill_tasks until close()) that
    must not be awaited — they only finish at shutdown."""
    skip = skip or set()
    for _ in range(400):
        pending = [
            t
            for t in list(executor._dispose_tasks) + list(executor._fill_tasks)
            if t not in skip
        ]
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


def scale_events(executor: CodeExecutor) -> dict[str, float]:
    return {
        labels["direction"]: value
        for labels, value in executor.metrics.pool_scale_events.samples()
    }


async def run_waves(
    executor: CodeExecutor, waves: int, jobs: int
) -> list[list[float]]:
    """The step load: per wave, JOBS concurrent Executes; returns each
    wave's per-job acquire waits (the queue_wait phase: scheduler wait +
    any spawn the request had to ride)."""
    per_wave: list[list[float]] = []
    for wave in range(waves):
        results = await asyncio.gather(
            *(executor.execute(SOURCE) for _ in range(jobs))
        )
        for r in results:
            if r.exit_code != 0:
                raise RuntimeError(f"job failed: {r.stderr[:300]}")
        per_wave.append(
            [float(r.phases.get("queue_wait", 0.0)) for r in results]
        )
        if wave < waves - 1:
            await asyncio.sleep(GAP_S)
    return per_wave


def p50(values: list[float]) -> float:
    return round(statistics.median(values), 4)


async def run_bench(waves: int, jobs: int) -> dict:
    tmp = Path(tempfile.mkdtemp(prefix="bench-autoscale-"))
    max_target = jobs + 2

    # ---- static leg (the kill switch IS this leg) -----------------------
    executor = make_executor(tmp / "static", autoscale=False, max_target=max_target)
    kill_switch_ok = True
    try:
        static_waves = await run_waves(executor, waves, jobs)
        await settle(executor)
        # Static behavior reproduced exactly: the target never moved off
        # the constant, surplus warm sandboxes were disposed back down to
        # it, and the autoscaler emitted nothing.
        kill_switch_ok = (
            executor.autoscaler.target(0) == 1
            and executor._lane_target(0) == 1
            and len(executor._pool(0)) <= 1
            and not scale_events(executor)
            and executor.start_autoscaler() is None
        )
        static_pool_depth = len(executor._pool(0))
    finally:
        await executor.close()

    # ---- autoscaled leg -------------------------------------------------
    executor = make_executor(tmp / "auto", autoscale=True, max_target=max_target)
    try:
        sweeper = {executor.start_autoscaler()}
        auto_waves = await run_waves(executor, waves, jobs)
        burst_end = time.perf_counter()
        peak_target = executor._lane_target(0)
        await settle(executor, skip=sweeper)
        retained = len(executor._pool(0))

        # Scale-down: wait out hysteresis + stepped decay + idle age, and
        # watch the reaper reclaim the excess down to the floor.
        reap_window = (
            SCALE_DOWN_AFTER
            + (max_target - 1) * SWEEP_INTERVAL
            + IDLE_REAP
            + 5.0  # scheduling margin on a loaded host
        )
        reclaimed_in = None
        while time.perf_counter() - burst_end < reap_window:
            events = scale_events(executor)
            if len(executor._pool(0)) <= 1 and events.get("reap", 0) > 0:
                reclaimed_in = round(time.perf_counter() - burst_end, 3)
                break
            await asyncio.sleep(0.25)
        await settle(executor, skip=sweeper)
        auto_events = scale_events(executor)
        floor_depth = len(executor._pool(0))
    finally:
        await executor.close()

    # Collect subprocess transports while the loop is alive (spurious
    # "Event loop is closed" __del__ tracebacks otherwise).
    import gc

    gc.collect()
    await asyncio.sleep(0)

    # Wave 1 is identical cold-start work in both legs; the step-load
    # comparison is the steady waves behind it.
    static_steady = [w for wave in static_waves[1:] for w in wave]
    auto_steady = [w for wave in auto_waves[1:] for w in wave]
    static_p50 = p50(static_steady)
    auto_p50 = p50(auto_steady)
    checks = {
        # THE gate: autoscaled p50 acquire wait <= 0.5x static under the
        # step-load burst.
        "autoscaled_p50_halved": auto_p50 <= 0.5 * static_p50,
        # Scale-up actually happened and retained the wave's supply.
        "burst_retained_warm_supply": peak_target > 1 and retained > 1,
        # Idle chips reclaimed, observably (reap events in metrics),
        # within the configured window.
        "reaped_within_window": reclaimed_in is not None and floor_depth <= 1,
        # APP_POOL_AUTOSCALE_ENABLED=0 reproduced the static pool exactly.
        "kill_switch_static": kill_switch_ok,
    }
    return {
        "metric": (
            "p50 acquire wait (queue_wait phase) across steady step-load "
            "burst waves (wave 2+), autoscaled vs static warm pool; plus "
            "idle-chip reclamation and kill-switch equivalence"
        ),
        "config": {
            "waves": waves,
            "jobs_per_wave": jobs,
            "wave_gap_s": GAP_S,
            "platform": os.environ.get("JAX_PLATFORMS", ""),
            "static_target": 1,
            "pool_max_target": max_target,
            "sweep_interval_s": SWEEP_INTERVAL,
            "scale_down_after_s": SCALE_DOWN_AFTER,
            "idle_reap_s": IDLE_REAP,
        },
        "static": {
            "p50_wait_s": static_p50,
            "wave_p50s": [p50(w) for w in static_waves],
            "end_pool_depth": static_pool_depth,
        },
        "autoscaled": {
            "p50_wait_s": auto_p50,
            "wave_p50s": [p50(w) for w in auto_waves],
            "peak_target": peak_target,
            "retained_after_burst": retained,
            "reclaimed_to_floor_in_s": reclaimed_in,
            "reap_window_s": round(
                SCALE_DOWN_AFTER + (max_target - 1) * SWEEP_INTERVAL + IDLE_REAP + 5.0,
                3,
            ),
            "floor_pool_depth": floor_depth,
            "scale_events": auto_events,
        },
        "speedup": round(static_p50 / auto_p50, 2) if auto_p50 else None,
        "checks": checks,
        "ok": all(checks.values()),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--waves", type=int, default=4)
    parser.add_argument("--jobs", type=int, default=6)
    parser.add_argument("--out", default=str(REPO_ROOT / "BENCH_autoscale.json"))
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="smaller step load + hard-fail on gate breakage (CI leg)",
    )
    args = parser.parse_args()
    if args.smoke:
        args.waves = min(args.waves, 3)
        args.jobs = min(args.jobs, 4)
    blob = asyncio.run(run_bench(max(2, args.waves), max(2, args.jobs)))
    Path(args.out).write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob))
    if not blob["ok"]:
        print("AUTOSCALE BENCH GATE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
