#!/usr/bin/env bash
# Regenerates the checked-in protobuf modules from /proto.
#
# Prefers real protoc when present; otherwise falls back to the in-repo
# descriptor compiler (scripts/genproto_fallback.py), which covers the
# proto3 subset the vendored contract uses and emits identical descriptors
# (tests/unit/test_proto_pin.py holds the pin either way).
set -euo pipefail
cd "$(dirname "$0")/.."
if command -v protoc >/dev/null 2>&1; then
  protoc --python_out=bee_code_interpreter_fs_tpu/proto -I proto \
    proto/code_interpreter.proto proto/health.proto proto/reflection.proto
  echo "regenerated bee_code_interpreter_fs_tpu/proto/*_pb2.py (protoc)"
else
  python scripts/genproto_fallback.py
fi
python scripts/genproto_fallback.py --check
