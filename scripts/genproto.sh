#!/usr/bin/env bash
# Regenerates the checked-in protobuf modules from /proto.
set -euo pipefail
cd "$(dirname "$0")/.."
protoc --python_out=bee_code_interpreter_fs_tpu/proto -I proto \
  proto/code_interpreter.proto proto/health.proto proto/reflection.proto
echo "regenerated bee_code_interpreter_fs_tpu/proto/*_pb2.py"
