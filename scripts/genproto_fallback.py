#!/usr/bin/env python3
"""protoc-free regeneration of the checked-in ``*_pb2.py`` modules.

The proto contract is vendored in-repo (/proto) and the generated modules
are checked in (bee_code_interpreter_fs_tpu/proto). Regenerating them
needs protoc — which the runtime image does not ship (the PR 5 follow-up
that kept the proto frozen). This script closes that gap: it compiles the
repo's protos with the ``google.protobuf`` runtime that IS in the image —
a small proto3 front-end producing a ``FileDescriptorProto`` and emitting
the same ``AddSerializedFile``-style module protoc's python plugin writes.

Scope is deliberately the subset the vendored contract uses: proto3,
messages (nested), enums, oneofs, map fields, repeated/scalar/message
fields, and services with unary/streaming methods. No imports, no
extensions, no custom options — adding any of those to /proto means
extending this script (or regenerating with real protoc; the emitted
descriptors are identical, byte-escaping style aside).

Usage:
    python scripts/genproto_fallback.py           # regenerate all modules
    python scripts/genproto_fallback.py --check   # drift gate (CI/test):
        fail when a .proto and its checked-in _pb2.py descriptor disagree
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

from google.protobuf import descriptor_pb2

REPO_ROOT = Path(__file__).resolve().parent.parent
PROTO_DIR = REPO_ROOT / "proto"
OUT_DIR = REPO_ROOT / "bee_code_interpreter_fs_tpu" / "proto"

SCALARS = {
    "double": descriptor_pb2.FieldDescriptorProto.TYPE_DOUBLE,
    "float": descriptor_pb2.FieldDescriptorProto.TYPE_FLOAT,
    "int64": descriptor_pb2.FieldDescriptorProto.TYPE_INT64,
    "uint64": descriptor_pb2.FieldDescriptorProto.TYPE_UINT64,
    "int32": descriptor_pb2.FieldDescriptorProto.TYPE_INT32,
    "fixed64": descriptor_pb2.FieldDescriptorProto.TYPE_FIXED64,
    "fixed32": descriptor_pb2.FieldDescriptorProto.TYPE_FIXED32,
    "bool": descriptor_pb2.FieldDescriptorProto.TYPE_BOOL,
    "string": descriptor_pb2.FieldDescriptorProto.TYPE_STRING,
    "bytes": descriptor_pb2.FieldDescriptorProto.TYPE_BYTES,
    "uint32": descriptor_pb2.FieldDescriptorProto.TYPE_UINT32,
    "sfixed32": descriptor_pb2.FieldDescriptorProto.TYPE_SFIXED32,
    "sfixed64": descriptor_pb2.FieldDescriptorProto.TYPE_SFIXED64,
    "sint32": descriptor_pb2.FieldDescriptorProto.TYPE_SINT32,
    "sint64": descriptor_pb2.FieldDescriptorProto.TYPE_SINT64,
}

_TOKEN = re.compile(
    r'"(?:[^"\\]|\\.)*"'  # string literal
    r"|[A-Za-z_][A-Za-z0-9_.]*"  # identifier (possibly dotted)
    r"|\d+"  # integer
    r"|[{}();=<>,]"  # punctuation
)


def tokenize(text: str) -> list[str]:
    text = re.sub(r"//[^\n]*", "", text)
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    return _TOKEN.findall(text)


def camel_entry(field_name: str) -> str:
    """protoc's map-entry message naming: snake_case -> CamelCase + Entry."""
    return "".join(p.capitalize() for p in field_name.split("_")) + "Entry"


class Parser:
    def __init__(self, tokens: list[str], filename: str):
        self.tokens = tokens
        self.pos = 0
        self.fd = descriptor_pb2.FileDescriptorProto(name=filename)
        # full name -> True when enum (drives TYPE_ENUM vs TYPE_MESSAGE).
        self.declared: dict[str, bool] = {}

    # ------------------------------------------------------------- tokens
    def next(self) -> str:
        tok = self.tokens[self.pos]
        self.pos += 1
        return tok

    def peek(self) -> str:
        return self.tokens[self.pos]

    def expect(self, tok: str) -> None:
        got = self.next()
        if got != tok:
            raise SyntaxError(f"expected {tok!r}, got {got!r} at {self.pos}")

    # -------------------------------------------------------------- parse
    def parse(self) -> descriptor_pb2.FileDescriptorProto:
        while self.pos < len(self.tokens):
            kw = self.next()
            if kw == "syntax":
                self.expect("=")
                syntax = self.next().strip('"')
                self.expect(";")
                self.fd.syntax = syntax
            elif kw == "package":
                self.fd.package = self.next()
                self.expect(";")
            elif kw == "message":
                self.fd.message_type.append(self.parse_message([]))
            elif kw == "enum":
                self.fd.enum_type.append(self.parse_enum([]))
            elif kw == "service":
                self.fd.service.append(self.parse_service())
            else:
                raise SyntaxError(f"unsupported top-level {kw!r}")
        self.resolve()
        return self.fd

    def parse_message(self, scope: list[str]) -> descriptor_pb2.DescriptorProto:
        name = self.next()
        msg = descriptor_pb2.DescriptorProto(name=name)
        inner_scope = scope + [name]
        self.declared[self.full_name(inner_scope)] = False
        self.expect("{")
        # Map-entry messages are appended AFTER declared nested types, in
        # field order — protoc's layout.
        map_entries: list[descriptor_pb2.DescriptorProto] = []
        while self.peek() != "}":
            kw = self.next()
            if kw == "message":
                msg.nested_type.append(self.parse_message(inner_scope))
            elif kw == "enum":
                msg.enum_type.append(self.parse_enum(inner_scope))
            elif kw == "oneof":
                oneof_name = self.next()
                oneof_index = len(msg.oneof_decl)
                msg.oneof_decl.add(name=oneof_name)
                self.expect("{")
                while self.peek() != "}":
                    field = self.parse_field(self.next(), inner_scope)
                    field.oneof_index = oneof_index
                    msg.field.append(field)
                self.expect("}")
            elif kw == "map":
                field, entry = self.parse_map_field(inner_scope)
                msg.field.append(field)
                map_entries.append(entry)
            else:
                msg.field.append(self.parse_field(kw, inner_scope))
        self.expect("}")
        msg.nested_type.extend(map_entries)
        return msg

    def parse_field(
        self, first: str, scope: list[str]
    ) -> descriptor_pb2.FieldDescriptorProto:
        field = descriptor_pb2.FieldDescriptorProto(
            label=descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
        )
        if first == "repeated":
            field.label = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
            first = self.next()
        self.set_type(field, first, scope)
        field.name = self.next()
        self.expect("=")
        field.number = int(self.next())
        self.expect(";")
        return field

    def parse_map_field(self, scope: list[str]):
        self.expect("<")
        key_type = self.next()
        self.expect(",")
        value_type = self.next()
        self.expect(">")
        name = self.next()
        self.expect("=")
        number = int(self.next())
        self.expect(";")
        entry = descriptor_pb2.DescriptorProto(name=camel_entry(name))
        entry.options.map_entry = True
        key = entry.field.add(
            name="key",
            number=1,
            label=descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL,
        )
        self.set_type(key, key_type, scope)
        value = entry.field.add(
            name="value",
            number=2,
            label=descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL,
        )
        self.set_type(value, value_type, scope)
        field = descriptor_pb2.FieldDescriptorProto(
            name=name,
            number=number,
            label=descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED,
            type=descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE,
            type_name="." + self.full_name(scope + [entry.name]),
        )
        return field, entry

    def parse_enum(self, scope: list[str]) -> descriptor_pb2.EnumDescriptorProto:
        name = self.next()
        enum = descriptor_pb2.EnumDescriptorProto(name=name)
        self.declared[self.full_name(scope + [name])] = True
        self.expect("{")
        while self.peek() != "}":
            value_name = self.next()
            self.expect("=")
            enum.value.add(name=value_name, number=int(self.next()))
            self.expect(";")
        self.expect("}")
        return enum

    def parse_service(self) -> descriptor_pb2.ServiceDescriptorProto:
        service = descriptor_pb2.ServiceDescriptorProto(name=self.next())
        self.expect("{")
        while self.peek() != "}":
            self.expect("rpc")
            method = service.method.add(name=self.next())
            self.expect("(")
            if self.peek() == "stream":
                self.next()
                method.client_streaming = True
            method.input_type = self.qualify(self.next())
            self.expect(")")
            self.expect("returns")
            self.expect("(")
            if self.peek() == "stream":
                self.next()
                method.server_streaming = True
            method.output_type = self.qualify(self.next())
            self.expect(")")
            self.expect(";")
        self.expect("}")
        return service

    # ------------------------------------------------------------ resolve
    def full_name(self, path: list[str]) -> str:
        return ".".join(([self.fd.package] if self.fd.package else []) + path)

    def qualify(self, name: str) -> str:
        """Service method types: all local to this file's package."""
        return "." + self.full_name([name])

    def set_type(self, field, type_name: str, scope: list[str]) -> None:
        if type_name in SCALARS:
            field.type = SCALARS[type_name]
        else:
            # Proto containers copy on append, so a deferred fixup can't
            # hold a reference to the field — stash the raw name (no
            # leading dot = unresolved marker) and the scope in json_name
            # for the resolve pass, which walks the finished tree.
            field.type_name = type_name
            field.json_name = "/".join(scope)

    def resolve(self) -> None:
        def fix(msg) -> None:
            for field in msg.field:
                if field.type_name and not field.type_name.startswith("."):
                    raw, scope = field.type_name, field.json_name.split("/")
                    # Innermost scope outward — the subset of protobuf
                    # scoping the vendored contract needs (file-local).
                    for depth in range(len(scope), -1, -1):
                        candidate = self.full_name(
                            scope[:depth] + raw.split(".")
                        )
                        if candidate in self.declared:
                            field.type_name = "." + candidate
                            field.type = (
                                descriptor_pb2.FieldDescriptorProto.TYPE_ENUM
                                if self.declared[candidate]
                                else descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
                            )
                            field.ClearField("json_name")
                            break
                    else:
                        raise SyntaxError(f"unresolved type {raw!r} in {scope}")
            for nested in msg.nested_type:
                fix(nested)

        for msg in self.fd.message_type:
            fix(msg)


def compile_proto(path: Path) -> descriptor_pb2.FileDescriptorProto:
    return Parser(tokenize(path.read_text()), path.name).parse()


# ------------------------------------------------------------------- emit

HEADER = '''# -*- coding: utf-8 -*-
# Generated by the protocol buffer compiler.  DO NOT EDIT!
# source: {source}
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, {module!r}, globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
'''


def mangle(path: list[str]) -> str:
    return "_" + "_".join(p.upper() for p in path)


def walk_messages(fd):
    def rec(msg, path):
        path = path + [msg.name]
        yield path, msg
        for nested in msg.nested_type:
            yield from rec(nested, path)

    for msg in fd.message_type:
        yield from rec(msg, [])


def emit_pb2(fd: descriptor_pb2.FileDescriptorProto, module: str) -> str:
    blob = fd.SerializeToString()
    options_lines: list[str] = []
    offset_lines: list[str] = []

    def offsets(path: list[str], sub) -> None:
        serialized = sub.SerializeToString()
        start = blob.find(serialized)
        name = mangle(path)
        offset_lines.append(f"  {name}._serialized_start={start}")
        offset_lines.append(f"  {name}._serialized_end={start + len(serialized)}")

    for path, msg in walk_messages(fd):
        if msg.options.map_entry:
            name = mangle(path)
            options_lines.append(f"  {name}._options = None")
            options_lines.append(
                f"  {name}._serialized_options = b'8\\001'"
            )
    for path, msg in walk_messages(fd):
        offsets(path, msg)
        for enum in msg.enum_type:
            offsets(path + [enum.name], enum)
    for enum in fd.enum_type:
        offsets([enum.name], enum)
    for service in fd.service:
        offsets([service.name], service)

    return (
        HEADER.format(source=fd.name, blob=blob, module=module)
        + "\n".join(options_lines + offset_lines)
        + "\n# @@protoc_insertion_point(module_scope)\n"
    )


# ------------------------------------------------------------------- main


def checked_in_descriptor(stem: str) -> descriptor_pb2.FileDescriptorProto:
    """Pull the serialized descriptor out of the checked-in module without
    importing it (imports would collide in the default descriptor pool)."""
    text = (OUT_DIR / f"{stem}_pb2.py").read_text()
    match = re.search(r"AddSerializedFile\((b'(?:[^'\\]|\\.)*')\)", text)
    if match is None:
        raise RuntimeError(f"no AddSerializedFile literal in {stem}_pb2.py")
    fd = descriptor_pb2.FileDescriptorProto()
    fd.MergeFromString(eval(match.group(1)))  # noqa: S307 — repo-owned literal
    return fd


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--check",
        action="store_true",
        help="verify the checked-in modules match /proto (drift gate)",
    )
    args = parser.parse_args()
    drift = False
    for proto in sorted(PROTO_DIR.glob("*.proto")):
        stem = proto.stem
        fd = compile_proto(proto)
        if args.check:
            pinned = checked_in_descriptor(stem)
            if fd != pinned:
                drift = True
                print(f"DRIFT: {proto.name} != {stem}_pb2.py", file=sys.stderr)
            else:
                print(f"ok: {proto.name}")
        else:
            out = OUT_DIR / f"{stem}_pb2.py"
            out.write_text(emit_pb2(fd, f"{stem}_pb2"))
            print(f"wrote {out.relative_to(REPO_ROOT)}")
    return 1 if drift else 0


if __name__ == "__main__":
    sys.exit(main())
