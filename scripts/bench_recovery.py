#!/usr/bin/env python3
"""Wedge-recovery microbench: a seeded attach-hang wedges one lane's host
through the real local backend + C++ executor, and the detect→act loop
must restore the lane to serving — detection, lease fence, drain, dispose,
respawn, clean-streak re-admission — inside a bounded wall-clock, with
zero manual intervention.

This is the ISSUE 13 acceptance gate made executable: the repo's own bench
history (BENCH_r03-r05) shows the unactuated version of this incident
costing 50-76 MINUTES of manual recovery (host reboot + watcher script).
The gate here asserts the automated loop closes in seconds:

- the probe detects the wedge (``device_wedge_detected_total``);
- the actuator fences it (``device_fence_total{outcome="fenced"}``), the
  host is disposed and a replacement spawns with a NEWER lease generation;
- a stale-generation claim against the successor is refused with the typed
  409 (the re-wedge vector is closed);
- the replacement re-admits only after the configured clean-probe streak
  (``host_readmitted_total``), and an Execute on the lane then succeeds;
- total time-to-restore (first probe -> serving execute) is under the
  bound.

Usage:
    python scripts/bench_recovery.py [--out BENCH_recovery.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# Never fight a TPU plugin for the chip in a bench by default.
os.environ.setdefault("JAX_PLATFORMS", os.environ.get("BENCH_PLATFORM", "cpu"))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import httpx  # noqa: E402

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.faults import (  # noqa: E402
    FaultInjectingBackend,
    FaultSpec,
)
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.device_health import (  # noqa: E402
    DeviceHealthProbe,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

LANE = 0
SEED = 7
READMIT_STREAK = 2
# Probe dynamics for the bench: tight budgets so detection is sub-second;
# production budgets are minutes by design (legitimate TPU init is slow).
PROBE_INTERVAL = 0.1
ATTACH_BUDGET = 0.5
WEDGE_AFTER = 0.5
# The smoke gate's time-to-restore bound (detection + drain + respawn +
# re-admission streak on the cadence above, plus CI scheduling slack).
RESTORE_BOUND_S = 20.0


def counter(metric) -> dict:
    return {tuple(l.values()): v for l, v in metric.samples()}


async def run_bench() -> dict:
    tmp = Path(tempfile.mkdtemp(prefix="bench-recovery-"))
    spec = (
        f"attach_hang:1.0,attach_hang_lane:{LANE},attach_hang_max:1,"
        f"seed:{SEED}"
    )
    config = Config(
        file_storage_path=str(tmp / "storage"),
        local_sandbox_root=str(tmp / "sandboxes"),
        jax_compilation_cache_dir="",
        executor_pod_queue_target_length=1,
        compile_cache_prewarm=False,
        executor_fault_spec=spec,
        device_probe_interval=PROBE_INTERVAL,
        device_probe_timeout=5.0,
        device_probe_attach_budget=ATTACH_BUDGET,
        device_probe_op_grace=5.0,
        device_probe_wedge_after=WEDGE_AFTER,
        device_probe_readmit_streak=READMIT_STREAK,
        default_execution_timeout=30.0,
    )
    backend = FaultInjectingBackend(
        LocalSandboxBackend(config, warm_import_jax=False),
        FaultSpec.parse(spec),
    )
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    probe = DeviceHealthProbe(executor)
    executor.device_health = probe
    timeline: dict[str, float] = {}
    checks: dict[str, bool] = {}
    try:
        # Lane up: one real executor host, which the seeded fault will
        # report as a wedged attach from its first probe.
        result = await executor.execute("print('up')", chip_count=LANE)
        assert result.exit_code == 0
        doomed = next(
            s for lane, s in executor.live_hosts() if lane == LANE
        )
        old_lease = doomed.meta["lease"]

        start = time.perf_counter()
        probe.start()
        deadline = start + RESTORE_BOUND_S

        def since_start() -> float:
            return round(time.perf_counter() - start, 3)

        # Detection.
        while time.perf_counter() < deadline:
            if counter(executor.metrics.device_wedges).get((str(LANE),), 0):
                timeline["detected_s"] = since_start()
                break
            await asyncio.sleep(0.02)
        checks["wedge_detected"] = "detected_s" in timeline

        # Fence + dispose + respawn.
        replacement = None
        while time.perf_counter() < deadline:
            fenced = counter(executor.metrics.device_fences).get(
                (str(LANE), "fenced"), 0
            )
            if fenced and executor.live_sandbox(doomed.id) is None:
                replacement = next(
                    (
                        s
                        for lane, s in executor.live_hosts()
                        if lane == LANE
                    ),
                    None,
                )
                if replacement is not None:
                    timeline.setdefault("replaced_s", since_start())
                    break
            await asyncio.sleep(0.02)
        checks["fenced_and_replaced"] = replacement is not None
        checks["lease_revoked"] = bool(old_lease.revoked)
        checks["generation_advanced"] = bool(
            replacement is not None
            and replacement.meta["lease"].generation > old_lease.generation
        )

        # The stale-generation claim dies typed at the successor.
        stale_refused = False
        if replacement is not None:
            async with httpx.AsyncClient() as raw:
                resp = await raw.post(
                    f"{replacement.url}/execute",
                    json={"source_code": "print('stale')", "timeout": 5},
                    headers={"x-lease-token": old_lease.wire_token},
                )
            stale_refused = (
                resp.status_code == 409
                and resp.json().get("error") == "stale_lease"
            )
        checks["stale_claim_409"] = stale_refused

        # Gated re-admission, then the lane serves again.
        while time.perf_counter() < deadline:
            if counter(executor.metrics.host_readmitted).get((str(LANE),), 0):
                timeline["readmitted_s"] = since_start()
                break
            await asyncio.sleep(0.02)
        checks["readmitted_after_streak"] = "readmitted_s" in timeline
        restored = False
        if checks["readmitted_after_streak"]:
            result = await executor.execute(
                "print('restored')", chip_count=LANE
            )
            restored = result.exit_code == 0
            timeline["restored_s"] = since_start()
        checks["lane_serves_again"] = restored
        checks["restored_within_bound"] = (
            restored and timeline["restored_s"] <= RESTORE_BOUND_S
        )
    finally:
        await probe.stop()
        await executor.close()
    # Collect subprocess transports while the loop is alive.
    import gc

    gc.collect()
    await asyncio.sleep(0)
    return {
        "metric": (
            "wall-clock from probe start to the wedged lane serving again "
            "(detect -> fence -> drain -> dispose -> respawn -> "
            "clean-streak re-admission), seeded attach_hang on the real "
            "local backend + C++ executor"
        ),
        "config": {
            "fault_spec": spec,
            "probe_interval_s": PROBE_INTERVAL,
            "attach_budget_s": ATTACH_BUDGET,
            "wedge_after_s": WEDGE_AFTER,
            "readmit_streak": READMIT_STREAK,
            "restore_bound_s": RESTORE_BOUND_S,
            "platform": os.environ.get("JAX_PLATFORMS", ""),
        },
        "timeline_s": timeline,
        "baseline": {
            "manual_recovery": "50-76 minutes (BENCH_r03-r05: host reboot "
            "+ watcher script)",
        },
        "checks": checks,
        "ok": all(checks.values()),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="BENCH_recovery.json")
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="CI gate mode: exit nonzero when any check fails",
    )
    args = parser.parse_args()
    body = asyncio.run(run_bench())
    Path(args.out).write_text(json.dumps(body, indent=2) + "\n")
    print(json.dumps(body, indent=2))
    if args.smoke and not body["ok"]:
        print("RECOVERY BENCH GATE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
