"""Scale-out control-plane bench + CI smoke gate (ISSUE 15 tentpole).

Two measurements, two gates:

1. **Shared-store overhead** (single stack, interleaved A/B): the PR 3/8
   unchanged session turn — the most overhead-sensitive real turn the
   service has — through ONE executor whose state-store wiring is toggled
   per turn between the DEFAULT private in-memory store (APP_STATE_STORE
   unset: every cross-replica path is skipped, the exact pre-PR code
   path) and a SHARED SQLite store (every cross-replica path live:
   shared WFQ tags, breaker reads, occupancy publishes). Gate, the
   established overhead discipline:

       shared-store p50 <= default p50 * 1.05 + 5ms

   The default leg IS the pre-PR path (store never consulted), so the
   "single replica with APP_STATE_STORE unset stays within 5%+5ms of
   pre-PR p50" claim is gated by construction — the stricter statement
   (even the SHARED path fits the budget) is what this gate measures.

2. **Two-replica aggregate throughput**: a saturating small-exec
   workload (8 concurrent clients, latency-bound execs) against ONE
   replica whose backend grants it a fixed sandbox budget, then against
   TWO in-process replicas (each with the same per-replica budget,
   replica-local sandbox roots) cooperating over one shared SQLite
   store. Each replica's budget models the per-pod management capacity a
   real deployment scales out BY; the gate proves the shared
   scheduler/lease/occupancy coordination does not serialize the second
   replica away:

       two-replica aggregate throughput >= 1.6x single-replica

3. **Store-loss drill** (ISSUE 20): THREE replicas over one RESP store
   (the in-repo stdlib stub, run as a subprocess so it can be SIGKILLed
   like a real store node). Mid-load the store process is killed and the
   drive keeps going; the store is then restarted and the wrappers heal.
   Gates — the degraded-mode invariants, checked end to end:

       - every turn completes (requests keep serving degraded),
       - zero duplicate (scope, generation) lease grants across the
         fleet, and ZERO mints at all while the store is unreachable
         (fail-closed fencing),
       - quota accrual journaled during the outage reconciles into the
         fleet windows within one window of reconnect.

Usage:
    python scripts/bench_replicas.py [--repeats 30] [--turns 10]
        [--out BENCH_replicas.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import secrets
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.errors import (  # noqa: E402
    StateStoreDegradedError,
)
from bee_code_interpreter_fs_tpu.services.quotas import (  # noqa: E402
    _FleetWindows,
)
from bee_code_interpreter_fs_tpu.services.state_store import (  # noqa: E402
    InMemoryStateStore,
    RespStateStore,
    ResilientStateStore,
    SQLiteStateStore,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402


def _trimmed_p50(samples: list[float]) -> float:
    """Median of the fastest two-thirds (the transfer bench's estimator)."""
    fast = sorted(samples)[: max(1, (2 * len(samples) + 2) // 3)]
    return statistics.median(fast)


class ReplicaCappedBackend(LocalSandboxBackend):
    """Local backend with a per-REPLICA warm-sandbox budget: each control
    plane may manage at most `cap` concurrent sandboxes — the per-pod
    management capacity scale-out multiplies. The budget names
    replica-local processes (each replica has its own sandbox root), so
    peers' holds do not contend for it."""

    capacity_shared_across_replicas = False

    def __init__(self, config, cap: int):
        super().__init__(config, warm_import_jax=False)
        self._cap = cap

    def pool_capacity(self, chip_count: int):
        return self._cap


def _config(tmp: str, name: str, **overrides) -> Config:
    defaults = dict(
        file_storage_path=f"{tmp}/{name}/storage",
        local_sandbox_root=f"{tmp}/{name}/sandboxes",
        usage_journal_path=f"{tmp}/{name}/usage",
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        compile_cache_prewarm=False,
        compile_cache_enabled=False,
        default_execution_timeout=120.0,
        replica_self=name,
    )
    defaults.update(overrides)
    return Config(**defaults)


def _swap_store(executor: CodeExecutor, store) -> None:
    """Re-point the executor's state-store seam (scheduler WFQ tags,
    breaker verdicts, lease generations, occupancy gauges) at `store`.
    None/private restores the exact default path (no component consults
    any store)."""
    shared = store is not None and store.shared
    executor.state_store = store or InMemoryStateStore()
    executor._store_shared = shared
    live = store if shared else None
    executor.scheduler._store = live
    executor.leases._store = live
    executor.breakers._store = live
    for breaker in executor.breakers._lanes.values():
        breaker._store = live
        breaker._remote_cache = (0.0, None)


async def bench_overhead(tmp: str, repeats: int) -> dict:
    """Leg 1: default-vs-shared-store unchanged-turn p50, one stack."""
    config = _config(tmp, "overhead")
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    sqlite_store = SQLiteStateStore(f"{tmp}/overhead-state.db")
    files = {}
    for i in range(8):
        object_id = await executor.storage.write(secrets.token_bytes(4096))
        files[f"/workspace/input-{i:03d}.bin"] = object_id
    default_samples: list[float] = []
    shared_samples: list[float] = []
    try:
        async def turn() -> float:
            start = time.perf_counter()
            result = await executor.execute(
                "import glob; print(len(glob.glob('input-*.bin')))",
                files=files,
                executor_id="bench-replicas",
                tenant="bench-tenant",
            )
            if result.exit_code != 0:
                raise RuntimeError(f"turn failed: {result.stderr[:400]}")
            return time.perf_counter() - start

        for _ in range(3):  # settle: spawn + cold sync
            await turn()
        for _ in range(repeats):
            _swap_store(executor, None)
            default_samples.append(await turn())
            _swap_store(executor, sqlite_store)
            shared_samples.append(await turn())
    finally:
        _swap_store(executor, None)
        await executor.close()
        sqlite_store.close()
    default_p50 = _trimmed_p50(default_samples)
    shared_p50 = _trimmed_p50(shared_samples)
    budget = default_p50 * 1.05 + 0.005
    return {
        "default_store_p50_s": round(default_p50, 6),
        "shared_sqlite_p50_s": round(shared_p50, 6),
        "overhead_s": round(shared_p50 - default_p50, 6),
        "gate": {
            "rule": "shared_sqlite_p50 <= default_p50 * 1.05 + 5ms "
                    "(default leg IS the pre-PR path: store never consulted)",
            "budget_s": round(budget, 6),
            "pass": bool(shared_p50 <= budget),
        },
    }


# Latency-bound small exec: saturates each replica's sandbox budget
# without pinning CI cores, so aggregate throughput tracks how many
# sandboxes the CONTROL PLANES can keep in flight — the quantity replicas
# multiply.
EXEC_SOURCE = "import time; time.sleep(0.2); print('ok')"
WORKERS = 8
PER_REPLICA_CAP = 2


async def _drive(executors: list[CodeExecutor], turns_per_worker: int) -> dict:
    """8 concurrent clients, round-robin across the replica set; returns
    aggregate throughput."""
    completed = 0

    async def worker(index: int) -> None:
        nonlocal completed
        executor = executors[index % len(executors)]
        for _ in range(turns_per_worker):
            result = await executor.execute(
                EXEC_SOURCE, tenant=f"client-{index % 2}"
            )
            if result.exit_code != 0:
                raise RuntimeError(f"exec failed: {result.stderr[:400]}")
            completed += 1

    start = time.perf_counter()
    await asyncio.gather(*(worker(i) for i in range(WORKERS)))
    wall = time.perf_counter() - start
    return {
        "turns": completed,
        "wall_s": round(wall, 3),
        "throughput_rps": round(completed / wall, 3),
    }


async def bench_throughput(tmp: str, turns_per_worker: int) -> dict:
    """Leg 2: single replica vs two replicas over one shared store."""

    def make_replica(name: str, store) -> CodeExecutor:
        # Static pool target == the sandbox budget (autoscale off): both
        # of a replica's sandboxes recycle into its pool between turns —
        # the measured quantity is steady-state serving, not spawn churn.
        config = _config(
            tmp,
            name,
            executor_pod_queue_target_length=PER_REPLICA_CAP,
            pool_autoscale_enabled=False,
        )
        backend = ReplicaCappedBackend(config, PER_REPLICA_CAP)
        return CodeExecutor(
            backend,
            Storage(config.file_storage_path),
            config,
            state_store=store,
        )

    async def settle(replicas: list[CodeExecutor]) -> None:
        # Warm every replica's FULL budget before measuring.
        await asyncio.gather(
            *(
                replica.execute(EXEC_SOURCE)
                for replica in replicas
                for _ in range(PER_REPLICA_CAP)
            )
        )

    # Single replica, default private store — the one-process baseline.
    single = make_replica("single", None)
    try:
        await settle([single])
        single_result = await _drive([single], turns_per_worker)
    finally:
        await single.close()

    # Two replicas sharing one SQLite store: shared WFQ tags, breaker
    # verdicts, lease generations, occupancy gauges — all live.
    store = SQLiteStateStore(f"{tmp}/fleet-state.db")
    replica_a = make_replica("replica-a", store)
    replica_b = make_replica("replica-b", store)
    try:
        await settle([replica_a, replica_b])
        pair_result = await _drive([replica_a, replica_b], turns_per_worker)
    finally:
        await replica_a.close()
        await replica_b.close()
        store.close()

    speedup = (
        pair_result["throughput_rps"] / single_result["throughput_rps"]
        if single_result["throughput_rps"] > 0
        else 0.0
    )
    return {
        "workload": {
            "exec": EXEC_SOURCE,
            "workers": WORKERS,
            "turns_per_worker": turns_per_worker,
            "per_replica_sandbox_budget": PER_REPLICA_CAP,
        },
        "single_replica": single_result,
        "two_replicas_shared_store": pair_result,
        "speedup": round(speedup, 3),
        "gate": {
            "rule": "two-replica aggregate throughput >= 1.6x single-replica",
            "pass": bool(speedup >= 1.6),
        },
    }


def _spawn_stub(port: int = 0) -> tuple[subprocess.Popen, int]:
    """Start the RESP stub as a real subprocess (so the bench can SIGKILL
    it like a store node dying) and block on its READY line."""
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "bee_code_interpreter_fs_tpu.services.resp_stub",
            "--port",
            str(port),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        cwd=str(REPO_ROOT),
    )
    line = (proc.stdout.readline() or "").strip()
    if not line.startswith("READY "):
        proc.kill()
        raise RuntimeError(f"resp stub failed to start: {line!r}")
    return proc, int(line.split()[1])


# The store-loss drill's fleet-window horizon: long enough that buckets
# accrued during the outage are still live when the post-reconnect read
# checks them (granularity = window/8 = 7.5s >> kill-to-heal time).
STORE_LOSS_QUOTA_WINDOW = 60.0
STORE_LOSS_REPLICAS = 3


async def bench_store_loss(tmp: str, turns_per_worker: int) -> dict:
    """Leg 3: three replicas over one RESP store; SIGKILL the store
    mid-load, keep driving, restart it, verify the degraded-mode
    invariants (serving, fencing, quota reconciliation) end to end."""
    stub, port = _spawn_stub()
    url = f"redis://127.0.0.1:{port}"
    # Short breaker cooldown so the post-restart heal lands within the
    # drill instead of the production-tuned probe cadence.
    stores = [
        ResilientStateStore(
            RespStateStore(url, op_timeout=1.0),
            failure_threshold=2,
            cooldown=0.75,
        )
        for _ in range(STORE_LOSS_REPLICAS)
    ]

    minted: list[tuple[str, int]] = []

    def make_replica(index: int) -> CodeExecutor:
        config = _config(
            tmp,
            f"loss-{index}",
            executor_pod_queue_target_length=PER_REPLICA_CAP,
            pool_autoscale_enabled=False,
        )
        backend = ReplicaCappedBackend(config, PER_REPLICA_CAP)
        executor = CodeExecutor(
            backend,
            Storage(config.file_storage_path),
            config,
            state_store=stores[index],
        )
        # Record every fleet lease grant: the zero-double-grant gate is
        # "no (scope, generation) pair is ever minted twice".
        registry = executor.leases
        inner_mint = registry.mint

        def mint(scope, sandbox_id=""):
            lease = inner_mint(scope, sandbox_id)
            minted.append((lease.scope, lease.generation))
            return lease

        registry.mint = mint
        return executor

    replicas = [make_replica(i) for i in range(STORE_LOSS_REPLICAS)]
    fleets = [
        _FleetWindows(store) for store in stores
    ]  # one per replica, as the quota enforcer holds

    served_after_kill = 0
    try:
        # Warm every replica's full sandbox budget while the store is up.
        await asyncio.gather(
            *(
                replica.execute(EXEC_SOURCE)
                for replica in replicas
                for _ in range(PER_REPLICA_CAP)
            )
        )
        # Healthy cross-replica fencing proof: three replicas minting on
        # ONE scope draw from the fleet counter — generations unique.
        for replica in replicas:
            replica.leases.mint("bench-shared-scope")

        # Drive with a mid-load SIGKILL of the store process.
        total_turns = WORKERS * turns_per_worker
        kill_after = max(1, total_turns // 2)
        completed = 0
        killed = False

        async def worker(index: int) -> None:
            nonlocal completed, served_after_kill, killed
            executor = replicas[index % len(replicas)]
            for _ in range(turns_per_worker):
                result = await executor.execute(
                    EXEC_SOURCE, tenant=f"client-{index % 2}"
                )
                if result.exit_code != 0:
                    raise RuntimeError(f"exec failed: {result.stderr[:400]}")
                completed += 1
                if killed:
                    served_after_kill += 1
                elif completed >= kill_after:
                    killed = True
                    stub.kill()  # SIGKILL: no shutdown handshake

        start = time.perf_counter()
        await asyncio.gather(*(worker(i) for i in range(WORKERS)))
        wall = time.perf_counter() - start

        # Store is dead. Mints must fail CLOSED — a partitioned replica
        # granting off a stale counter is the one forbidden behavior.
        refused = 0
        for replica in replicas:
            try:
                replica.leases.mint("bench-shared-scope")
            except StateStoreDegradedError:
                refused += 1
        # Quota accrual while the store is down: fails open locally and
        # journals — publish_errors would mean the enforcer saw the
        # outage instead of the wrapper absorbing it.
        per_replica_adds, delta = 5, 1.0
        for fleet in fleets:
            for _ in range(per_replica_adds):
                fleet.add(
                    "bench-tenant", "chip_s", delta, STORE_LOSS_QUOTA_WINDOW
                )
        expected_accrual = STORE_LOSS_REPLICAS * per_replica_adds * delta
        outage_health = [store.health() for store in stores]

        # Restart the store on the same port and let every wrapper heal
        # (breaker cooldown, then one good probe replays the journal).
        stub, _ = _spawn_stub(port)
        heal_deadline = time.monotonic() + 20.0
        healed = False
        while time.monotonic() < heal_deadline:
            if all(store.probe() for store in stores):
                healed = True
                break
            await asyncio.sleep(0.1)

        # Reconciliation: a FRESH handle (no replica-local state) must see
        # the full outage accrual in the fleet windows — within one
        # window of reconnect by construction, since the buckets the
        # journal replayed into are still the live ones.
        raw = RespStateStore(url, op_timeout=1.0)
        try:
            fleet_used = _FleetWindows(raw).used(
                "bench-tenant", "chip_s", STORE_LOSS_QUOTA_WINDOW
            )
        finally:
            raw.close()
        # Post-heal mints flow again. A fresh scope: the stub is
        # memoryless, so the restart is also a counter wipe — production
        # points the fleet counter at persistent storage (README), and
        # the invariant gated here is no-mints-during-outage plus no
        # duplicate grant ever observed.
        for replica in replicas:
            replica.leases.mint("bench-shared-scope-epoch2")
    finally:
        for replica in replicas:
            await replica.close()
        for store in stores:
            with contextlib.suppress(Exception):
                store.close()
        with contextlib.suppress(Exception):
            stub.kill()

    no_duplicate_grants = len(minted) == len(set(minted))
    mints_fail_closed = refused == STORE_LOSS_REPLICAS
    reconciled = (
        healed
        and abs(fleet_used - expected_accrual) < 1e-6
        and all(f.publish_errors == 0 for f in fleets)
        and all(s.health()["journal_depth"] == 0 for s in stores)
    )
    return {
        "replicas": STORE_LOSS_REPLICAS,
        "turns": completed,
        "wall_s": round(wall, 3),
        "served_after_store_kill": served_after_kill,
        "degraded_mint_refusals": refused,
        "lease_grants": len(minted),
        "store_outages_seen": [h["outages"] for h in outage_health],
        "quota_accrual_expected": expected_accrual,
        "quota_accrual_fleet_view": round(fleet_used, 6),
        "journal_replays": [s.health()["journal_replays"] for s in stores],
        "gate": {
            "rule": "all turns serve through the store SIGKILL; zero "
            "duplicate (scope, generation) grants and zero mints while "
            "the store is down; journaled quota accrual reconciles "
            "within one window of reconnect",
            "served_degraded": bool(served_after_kill > 0),
            "no_duplicate_grants": no_duplicate_grants,
            "mints_fail_closed": mints_fail_closed,
            "quota_reconciled": bool(reconciled),
            "pass": bool(
                served_after_kill > 0
                and completed == total_turns
                and no_duplicate_grants
                and mints_fail_closed
                and reconciled
            ),
        },
    }


async def run_bench(repeats: int, turns_per_worker: int) -> dict:
    tmp = tempfile.mkdtemp(prefix="bench-replicas-")
    overhead = await bench_overhead(tmp, repeats)
    throughput = await bench_throughput(tmp, turns_per_worker)
    store_loss = await bench_store_loss(tmp, turns_per_worker)
    return {
        "overhead": overhead,
        "throughput": throughput,
        "store_loss": store_loss,
        "gates_pass": bool(
            overhead["gate"]["pass"]
            and throughput["gate"]["pass"]
            and store_loss["gate"]["pass"]
        ),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repeats", type=int, default=30)
    parser.add_argument("--turns", type=int, default=10)
    parser.add_argument("--out", default="BENCH_replicas.json")
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="CI profile: fewer repeats/turns, same gates",
    )
    args = parser.parse_args()
    repeats = 12 if args.smoke else args.repeats
    turns = 6 if args.smoke else args.turns
    result = asyncio.run(run_bench(repeats, turns))
    Path(args.out).write_text(json.dumps(result, indent=2) + "\n")
    print(json.dumps(result, indent=2))
    if not result["gates_pass"]:
        print(
            "GATE FAILED: replica scale-out "
            "(overhead, throughput, or store-loss drill)",
            file=sys.stderr,
        )
        return 1
    print("gates MET")
    return 0


if __name__ == "__main__":
    sys.exit(main())
