#!/usr/bin/env bash
# Tear down the local deployment: stop the port-forward, delete the service
# pod (executor pods cascade via ownerReferences) and the RBAC objects.
# Reference parity: scripts/teardown.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ -f .port-forward.pid ]]; then
  kill "$(cat .port-forward.pid)" 2>/dev/null || true
  rm -f .port-forward.pid
fi

kubectl delete -f k8s/local.yaml --ignore-not-found --wait=false
# Belt & braces: reap any executor pods that lost their owner.
kubectl delete pods -l app=code-executor --ignore-not-found --wait=false
