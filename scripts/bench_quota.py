"""Quota-enforcement overhead bench + CI smoke gate (ISSUE 12 satellite).

The quota layer sits on EVERY request's admission path, so it buys its
abuse-control value only if the well-behaved-tenant path stays free. This
bench drives the PR 3/PR 4/PR 8 unchanged-turn workload (a session turn
whose input files are already synced — the fastest real turn the service
has, i.e. the most overhead-sensitive) through ONE executor stack,
interleaving turns with the enforcer toggled off and on (every budget
check armed with room to spare, so the FULL enforcement path runs and
admits). The gate, the established overhead discipline:

    enabled unchanged-turn p50 <= disabled p50 * 1.05 + 5ms

Interleaved single-stack turns + trimmed medians, like the tracing and
probe overhead benches: same process, same sandbox, only the quota gate
varies — CI load spikes hit both sides symmetrically.

Also recorded (informational, no gate): the denial fast path — how
quickly an over-budget tenant is turned away. Shedding is only cheaper
than serving if the denial itself costs microseconds, not a sandbox.

Usage:
    python scripts/bench_quota.py [--repeats 40] [--files 8]
        [--file-bytes 4096] [--out BENCH_quota.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import secrets
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
    QuotaExceededError,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

TENANT = "bench-tenant"


def _trimmed_p50(samples: list[float]) -> float:
    """Median of the fastest two-thirds (the transfer bench's estimator):
    symmetric across both sides of the comparison, so CI load bursts
    cannot bias the delta while real per-turn overhead still shifts the
    fast samples it would hide in."""
    fast = sorted(samples)[: max(1, (2 * len(samples) + 2) // 3)]
    return statistics.median(fast)


def _make_executor(tmp: str) -> CodeExecutor:
    config = Config(
        file_storage_path=f"{tmp}/storage",
        local_sandbox_root=f"{tmp}/sandboxes",
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        compile_cache_prewarm=False,
        default_execution_timeout=120.0,
        # EVERY quota check armed (the full enforcement path runs on each
        # admitted turn) with room the bench can never exhaust — this
        # measures the well-behaved-tenant tax, not denials.
        quota_chip_seconds_per_window=1e9,
        quota_window_seconds=3600.0,
        quota_requests_per_window=10_000_000,
        quota_max_concurrent=10_000,
        quota_violations_per_window=10_000_000,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


async def run_bench(num_files: int, file_bytes: int, repeats: int) -> dict:
    tmp = tempfile.mkdtemp(prefix="bench-quota-")
    executor = _make_executor(tmp)
    files: dict[str, str] = {}
    for i in range(num_files):
        object_id = await executor.storage.write(
            secrets.token_bytes(file_bytes)
        )
        files[f"/workspace/input-{i:03d}.bin"] = object_id
    off_samples: list[float] = []
    on_samples: list[float] = []
    try:
        async def turn() -> float:
            start = time.perf_counter()
            result = await executor.execute(
                "import glob; print(len(glob.glob('input-*.bin')))",
                files=files,
                executor_id="bench-quota",
                tenant=TENANT,
            )
            wall = time.perf_counter() - start
            if result.exit_code != 0:
                raise RuntimeError(
                    f"bench execute failed: {result.stderr[:500]}"
                )
            return wall

        # Settle: first turns pay spawn + cold sync; the comparison is the
        # steady unchanged turn.
        for _ in range(3):
            await turn()
        # Interleaved A/B: the enforcer's `enabled` flag is the exact
        # admission-gate toggle (admit()/release() return immediately when
        # off — the kill switch's serving-path behavior).
        for _ in range(repeats):
            executor.quotas.enabled = False
            off_samples.append(await turn())
            executor.quotas.enabled = True
            on_samples.append(await turn())

        # Denial fast path (informational): a tenant with a zero-room
        # budget is turned away in-process — time 1000 denials. One real
        # admitted run first seeds the window's baseline sample (the
        # production order: admission always precedes consumption), then
        # the billed burn puts the tenant decisively over.
        executor.quotas.default_policy = (
            executor.quotas.default_policy.__class__(
                chip_seconds_per_window=0.001,
                window_seconds=3600.0,
            )
        )
        await executor.execute("print(1)", tenant="denied-tenant")
        executor.usage.add("denied-tenant", chip_seconds=1.0)
        denial_start = time.perf_counter()
        denials = 0
        for _ in range(1000):
            try:
                await executor.execute("print(1)", tenant="denied-tenant")
            except QuotaExceededError:
                denials += 1
        denial_wall = time.perf_counter() - denial_start
        if denials != 1000:
            raise RuntimeError(f"expected 1000 denials, got {denials}")
    finally:
        await executor.close()

    off_p50 = _trimmed_p50(off_samples)
    on_p50 = _trimmed_p50(on_samples)
    budget = off_p50 * 1.05 + 0.005
    return {
        "workload": {
            "num_files": num_files,
            "file_bytes": file_bytes,
            "repeats": repeats,
        },
        "quotas_disabled_p50_s": round(off_p50, 6),
        "quotas_enabled_p50_s": round(on_p50, 6),
        "overhead_s": round(on_p50 - off_p50, 6),
        "overhead_frac": round((on_p50 - off_p50) / off_p50, 6)
        if off_p50 > 0
        else 0.0,
        "denial_p50_us": round(denial_wall / 1000 * 1e6, 1),
        "gate": {
            "rule": "enabled_p50 <= disabled_p50 * 1.05 + 5ms",
            "budget_s": round(budget, 6),
            "pass": bool(on_p50 <= budget),
        },
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repeats", type=int, default=40)
    parser.add_argument("--files", type=int, default=8)
    parser.add_argument("--file-bytes", type=int, default=4096)
    parser.add_argument("--out", default="BENCH_quota.json")
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="CI profile: fewer repeats, same gate",
    )
    args = parser.parse_args()
    repeats = 15 if args.smoke else args.repeats
    result = asyncio.run(run_bench(args.files, args.file_bytes, repeats))
    Path(args.out).write_text(json.dumps(result, indent=2) + "\n")
    print(json.dumps(result, indent=2))
    if not result["gate"]["pass"]:
        print("GATE FAILED: quota enforcement taxes the unchanged turn",
              file=sys.stderr)
        return 1
    print("gate MET")
    return 0


if __name__ == "__main__":
    sys.exit(main())
