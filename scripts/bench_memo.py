#!/usr/bin/env python3
"""Result-memo microbench: repeat pure-run Execute latency served from the
content-addressed memo vs the live sandbox path, plus the two overhead
gates the ISSUE demands.

Drives the real local backend + C++ executor (no jax import — the
workload is pure CPython so the numbers isolate the memo plane, not XLA).
Three legs:

- ``disabled``  — ``result_memo_enabled=False`` (the
  ``APP_RESULT_MEMO_ENABLED=0`` kill switch): the pre-this-PR wire path,
  every run live. Baseline for the overhead + parity gates.
- ``miss``      — memo ENABLED, every run a unique source: each run is a
  live execution that also derives keys, verifies the executor's purity
  echo, and records the result. The delta vs ``disabled`` is the memo's
  full uncached overhead.
- ``hit``       — memo ENABLED, one primed source repeated: every run is
  served from the record with no scheduler ticket, no sandbox HTTP, and
  zero chip-seconds.

Emits ``BENCH_memo.json``. Gates (the ISSUE acceptance criteria):

- ``hit_speedup_10x``      — hit wall p50 at least 10x faster than the
  uncached live p50.
- ``uncached_overhead``    — miss p50 within 5% + 5ms of the disabled
  baseline p50.
- ``kill_switch_parity``   — with the kill switch thrown, the same pure
  request byte-for-byte matches the live leg (stdout, stderr, exit code,
  output-file bytes), carries no memo surface, and writes no memo state.
- ``hits_cost_nothing``    — every hit reports state=hit, zero
  chip-seconds, and made zero sandbox HTTP round-trips.

``--smoke`` (CI) shrinks repeats and hard-fails on any gate breakage.

Usage:
    python scripts/bench_memo.py [--repeats 7]
        [--out BENCH_memo.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

# A deterministic, CPU-bound workload heavy enough (~100ms+ of CPython)
# that the 5%+5ms overhead gate measures the memo plane, not timer jitter,
# and with an output file so the hit leg proves files ride the record.
WORK = """
total = 0
for i in range(1_200_000):
    total += i * i
print(total)
open('out.bin', 'wb').write(total.to_bytes(16, 'big'))
"""


def make_executor(tmp: Path, **overrides) -> CodeExecutor:
    defaults = dict(
        file_storage_path=str(tmp / "storage"),
        local_sandbox_root=str(tmp / "sandboxes"),
        # One warm, recycled sandbox: the live path is dispatch + exec, not
        # spawn — the honest (hardest) baseline for the 10x hit gate.
        executor_pod_queue_target_length=1,
        executor_reuse_sandboxes=True,
        jax_compilation_cache_dir="",
        compile_cache_enabled=False,
        default_execution_timeout=120.0,
    )
    defaults.update(overrides)
    config = Config(**defaults)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


async def settle(executor: CodeExecutor) -> None:
    for _ in range(400):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


def count_sandbox_http(executor: CodeExecutor) -> dict:
    """Arm a request counter on the live sandbox HTTP client — every wire
    round-trip from now on increments it."""
    count = {"n": 0}

    async def tick(request):
        count["n"] += 1

    executor._http_client().event_hooks["request"].append(tick)
    return count


async def timed_run(executor: CodeExecutor, source: str, *, pure: bool):
    start = time.perf_counter()
    result = await executor.execute(source, pure=pure)
    wall = time.perf_counter() - start
    if result.exit_code != 0:
        raise RuntimeError(f"bench execute failed: {result.stderr[:500]}")
    return round(wall, 5), result


async def result_bytes(executor: CodeExecutor, result) -> dict:
    files = {}
    for path, sha in sorted(result.files.items()):
        files[path] = (await executor.storage.read(sha)).hex()
    return {
        "stdout": result.stdout,
        "stderr": result.stderr,
        "exit_code": result.exit_code,
        "files": files,
    }


def p50(walls: list[float]) -> float:
    return round(statistics.median(walls), 5)


async def run_bench(repeats: int) -> dict:
    tmp = Path(tempfile.mkdtemp(prefix="bench-memo-"))

    def unique(n: int) -> str:
        return WORK + f"# variant {n}\n"

    # --- disabled: the kill-switch wire path, every run live.
    disabled_walls: list[float] = []
    executor = make_executor(tmp / "disabled", result_memo_enabled=False)
    try:
        await timed_run(executor, "print('spin-up')", pure=False)
        await settle(executor)
        for n in range(repeats):
            wall, _ = await timed_run(executor, unique(n), pure=True)
            disabled_walls.append(wall)
            await settle(executor)
        _, parity_run = await timed_run(executor, WORK, pure=True)
        disabled_parity = await result_bytes(executor, parity_run)
        disabled_clean = (
            "memo" not in parity_run.phases
            and executor.result_memo.entry_count() == 0
            and not (tmp / "disabled" / "storage" / ".result-memo").exists()
        )
    finally:
        await executor.close()

    # --- enabled: miss leg (unique sources, live + record) then hit leg
    # (one primed source repeated, served from the record).
    executor = make_executor(tmp / "enabled")
    miss_walls: list[float] = []
    hit_walls: list[float] = []
    hit_runs: list[dict] = []
    try:
        await timed_run(executor, "print('spin-up')", pure=False)
        await settle(executor)
        for n in range(repeats):
            wall, result = await timed_run(executor, unique(n), pure=True)
            if result.phases.get("memo", {}).get("state") != "miss":
                raise RuntimeError("unique source unexpectedly hit the memo")
            miss_walls.append(wall)
            await settle(executor)

        _, prime = await timed_run(executor, WORK, pure=True)
        enabled_parity = await result_bytes(executor, prime)
        await settle(executor)
        wire = count_sandbox_http(executor)
        for _ in range(repeats):
            wall, result = await timed_run(executor, WORK, pure=True)
            hit_walls.append(wall)
            hit_runs.append(
                {
                    "wall_s": wall,
                    "state": result.phases.get("memo", {}).get("state"),
                    "chip_seconds": result.phases.get("chip_seconds"),
                }
            )
        hit_bytes = await result_bytes(executor, result)
        hit_sandbox_http = wire["n"]
    finally:
        await executor.close()

    import gc

    gc.collect()
    await asyncio.sleep(0)

    disabled_p50 = p50(disabled_walls)
    miss_p50 = p50(miss_walls)
    hit_p50 = p50(hit_walls)
    speedup = round(miss_p50 / hit_p50, 2) if hit_p50 else float("inf")
    overhead_gate_s = round(disabled_p50 * 1.05 + 0.005, 5)
    checks = {
        # THE acceptance criterion: a memo hit is at least 10x faster at
        # p50 than the uncached live path.
        "hit_speedup_10x": hit_p50 * 10 <= miss_p50,
        # Enabled-but-uncached stays within 5% + 5ms of the kill-switch
        # baseline.
        "uncached_overhead_within_5pct_5ms": miss_p50 <= overhead_gate_s,
        # Kill switch is byte-for-byte: same output bytes, no memo
        # surface, no memo state on disk.
        "kill_switch_parity": (
            disabled_parity == enabled_parity == hit_bytes and disabled_clean
        ),
        # Hits cost nothing: state=hit, zero chip-seconds, zero sandbox
        # HTTP round-trips across the whole hit leg.
        "hits_cost_nothing": (
            all(
                r["state"] == "hit" and r["chip_seconds"] == 0.0
                for r in hit_runs
            )
            and hit_sandbox_http == 0
        ),
    }
    return {
        "metric": (
            "pure-run Execute wall p50: memo hit vs uncached live vs "
            "kill-switch baseline"
        ),
        "config": {
            "repeats": repeats,
            "workload": "CPU-bound CPython sum-of-squares + output file",
        },
        "disabled": {"p50_wall_s": disabled_p50, "walls_s": disabled_walls},
        "miss": {"p50_wall_s": miss_p50, "walls_s": miss_walls},
        "hit": {
            "p50_wall_s": hit_p50,
            "walls_s": hit_walls,
            "runs": hit_runs,
            "sandbox_http_requests": hit_sandbox_http,
        },
        "hit_speedup_p50_x": speedup,
        "uncached_overhead_gate_s": overhead_gate_s,
        "checks": checks,
        "ok": all(checks.values()),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repeats", type=int, default=7)
    parser.add_argument("--out", default=str(REPO_ROOT / "BENCH_memo.json"))
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="three repeats per leg + hard-fail on gate breakage (CI leg)",
    )
    args = parser.parse_args()
    if args.smoke:
        args.repeats = min(args.repeats, 3)
    blob = asyncio.run(run_bench(max(1, args.repeats)))
    Path(args.out).write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob))
    if not blob["ok"]:
        print("RESULT-MEMO BENCH GATE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
