#!/usr/bin/env python3
"""Workspace-sync microbench: Execute latency and bytes moved for
(a) a cold first session turn, (b) a session turn with unchanged input
files, and (c) a turn with exactly one changed file.

Drives the real local backend + C++ executor (warm JAX import off — this
measures the transfer protocol, not TPU init) and reads the byte movement
straight out of ``Result.phases``, which the delta sync populates. Emits a
``BENCH_transfer.json`` blob::

    {"config": {...}, "cold": {...}, "unchanged": {...}, "one_changed": {...},
     "ok": true}

The headline invariant (the ISSUE acceptance criterion): the unchanged turn
moves ZERO upload bytes regardless of file count or size — O(1) wire cost,
not O(total bytes x hosts) — and its skipped-bytes counters are nonzero
while the cold turn's are zero. ``--smoke`` (CI) shrinks the file set and
exits nonzero when any invariant breaks.

A second gate (ISSUE 4) measures TRACING overhead on the same unchanged-turn
path: the p50 with tracing enabled at 0% sampling must stay within 5% of the
tracing-disabled p50 (the no-op fast path really is a no-op); the 100%
number is recorded for reference.

A third gate (ISSUE 8) measures the DEVICE-HEALTH PROBE daemon's overhead on
the same path: with the probe running at a 500ms cadence (30x the
production default) against the live sandbox host, the unchanged-turn p50
must stay
within 5% + 5ms of the probe-off p50, and one full probe cycle (real
/device-stats HTTP + classification) must finish under 250ms — background
telemetry must not tax the serving path, and the probe itself must stay
cheap enough that any cadence an operator picks stays negligible.

Usage:
    python scripts/bench_transfer.py [--files 16] [--bytes 65536]
        [--repeats 3] [--out BENCH_transfer.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import secrets
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# The transfer bench must not fight a TPU plugin for the chip; everything
# here is control-plane + wire mechanics.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402


def _phase_blob(result, wall_s: float) -> dict:
    phases = result.phases
    return {
        "wall_s": round(wall_s, 4),
        "upload_s": round(phases.get("upload", 0.0), 4),
        "download_s": round(phases.get("download", 0.0), 4),
        "upload_bytes": int(phases.get("upload_bytes", 0.0)),
        "upload_skipped_bytes": int(phases.get("upload_skipped_bytes", 0.0)),
        "download_bytes": int(phases.get("download_bytes", 0.0)),
        "download_skipped_bytes": int(
            phases.get("download_skipped_bytes", 0.0)
        ),
    }


async def _timed_execute(executor, source, files, session) -> dict:
    start = time.perf_counter()
    result = await executor.execute(source, files=files, executor_id=session)
    wall = time.perf_counter() - start
    if result.exit_code != 0:
        raise RuntimeError(f"bench execute failed: {result.stderr[:500]}")
    return _phase_blob(result, wall)


def _make_executor(tmp: str, **config_overrides) -> CodeExecutor:
    config = Config(
        file_storage_path=f"{tmp}/storage",
        local_sandbox_root=f"{tmp}/sandboxes",
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=120.0,
        **config_overrides,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


def _trimmed_p50(samples: list[float]) -> float:
    """Median of the fastest two-thirds of samples. Applied to BOTH sides
    of an overhead comparison (symmetric, so it cannot bias the delta): a
    CI machine's load bursts land multi-x spikes on a ~50ms path, and a
    plain small-sample median flakes when a burst covers one side's slow
    half. Real per-turn overhead shifts the FAST samples too, so the
    trimmed median still detects it."""
    fast = sorted(samples)[: max(1, (2 * len(samples) + 2) // 3)]
    return statistics.median(fast)


class _OverheadStack:
    """One executor stack for the overhead benches (tracing, device-health
    probe): its own session and input set, a `turn` that wraps every
    execute in a root span (without one, the pipeline's child spans no-op
    regardless of sampling and the comparison would measure nothing), and
    a recorded-sample list the A/B loops slice per mode."""

    def __init__(self, label: str, **config_overrides) -> None:
        self.label = label
        self.config_overrides = config_overrides
        self.samples: list[float] = []
        self.executor: CodeExecutor | None = None
        self.files: dict[str, str] = {}

    async def start(self, num_files: int, file_bytes: int) -> None:
        tmp = tempfile.mkdtemp(prefix=f"bench-overhead-{self.label}-")
        self.executor = _make_executor(tmp, **self.config_overrides)
        for i in range(num_files):
            object_id = await self.executor.storage.write(
                secrets.token_bytes(file_bytes)
            )
            self.files[f"/workspace/input-{i:03d}.bin"] = object_id

    async def close(self) -> None:
        if self.executor is not None:
            await self.executor.close()

    async def turn(self, record: bool) -> None:
        with self.executor.tracer.start_trace("bench unchanged-turn"):
            start = time.perf_counter()
            result = await self.executor.execute(
                "import glob; print(len(glob.glob('input-*.bin')))",
                files=self.files,
                executor_id="bench-tracing",
            )
            wall = time.perf_counter() - start
        if result.exit_code != 0:
            raise RuntimeError(f"bench execute failed: {result.stderr[:500]}")
        if record:
            self.samples.append(wall)


async def tracing_overhead_bench(
    num_files: int, file_bytes: int, repeats: int
) -> dict:
    """ISSUE 4 satellite: unchanged-turn p50 with tracing disabled vs
    enabled@0% vs enabled@100%. The gate: 0% sampling must be free — within
    5% of disabled (plus a 5ms epsilon so sub-ms scheduler jitter on a
    ~50ms path cannot flake CI).

    ONE stack, three tracer modes toggled turn by turn (`Tracer.enabled` /
    `sample_ratio` are plain attributes, and no span is live between
    turns): the original three-parallel-stacks design compared three
    separate executor/sandbox PROCESSES, whose scheduling placement on a
    loaded CI machine differs by more than the 5% being measured — the
    dominant flake source. Same process, same sandbox, interleaved turns,
    trimmed medians: only the tracer config varies.

    Tail sampling is off in the 0% mode: since PR 7 a head-REJECTED trace
    records tentatively anyway (the tail flight recorder) — a deliberate,
    separately kill-switched feature whose cost is ~that of 100% sampling.
    This gate measures the head-sampling no-op path, which is what "0%
    sampling is free" has always meant; the 100% leg stands in as the
    recording-cost reference."""
    stack = _OverheadStack("tracing-ab", tracing_sample_ratio=1.0)
    modes = {"off": [], "s0": [], "s100": []}
    try:
        await stack.start(num_files, file_bytes)
        await stack.turn(record=False)  # the cold upload turn
        tracer = stack.executor.tracer
        # Deep sampling: a loaded CI box jitters a ~50ms path by +/-50%,
        # and a 5% gate needs the trimmed median to converge through that.
        for _ in range(max(24, 8 * repeats)):
            for mode, samples in modes.items():
                tracer.enabled = mode != "off"
                tracer.sample_ratio = 1.0 if mode == "s100" else 0.0
                tracer.tail_enabled = mode == "s100"
                stack.samples = []
                await stack.turn(record=True)
                samples.extend(stack.samples)
    finally:
        await stack.close()

    # Trimmed medians for the GATE comparison: CI load bursts land multi-x
    # spikes on a ~50ms path, and a plain median flakes when a burst covers
    # one leg's slow half (the trim is symmetric, so it cannot bias the
    # delta; real overhead shifts the fast samples too).
    off, sampled_0, sampled_100 = (
        _trimmed_p50(modes["off"]),
        _trimmed_p50(modes["s0"]),
        _trimmed_p50(modes["s100"]),
    )
    gate = off * 1.05 + 0.005
    return {
        "metric": "tracing overhead on the unchanged-turn path (p50 seconds)",
        "disabled_p50_s": round(off, 4),
        "sampling_0_p50_s": round(sampled_0, 4),
        "sampling_100_p50_s": round(sampled_100, 4),
        "gate_p50_s": round(gate, 4),
        "checks": {"sampling_0_within_5pct_of_disabled": sampled_0 <= gate},
    }


async def probe_overhead_bench(
    num_files: int, file_bytes: int, repeats: int
) -> dict:
    """ISSUE 8 satellite: unchanged-turn p50 with the device-health probe
    daemon OFF vs ON at a 500ms cadence (30x the production default), with
    ON blocks long enough (~1s of turns) that daemon cycles genuinely land
    INSIDE the measured turns — not just at block boundaries — plus a
    direct bound on the probe cycle's own latency. The cadence is chosen
    against the gate's own arithmetic: expected per-turn overhead is
    cycle_cost/interval, and a contended CI box prices one cycle at up to
    ~25ms, so 500ms keeps even the contended expectation (~5%) inside the
    5% + 5ms budget while any *regression* in the probe (a blocking loop, a
    cycle that stops being async) still blows straight through it. Two
    gates:

    - p50 gate (the ISSUE criterion): probe-on stays within 5% + 5ms of
      probe-off. At any sane cadence the daemon's per-turn p50 impact is
      (cycle cost x cadence) — sub-millisecond — so this catches the
      failure mode that matters: a probe loop that starts blocking or
      hogging the shared event loop.
    - cycle gate: one full probe cycle (real /device-stats HTTP against
      the live host + classification) stays under 250ms. This is the
      regression detector for the probe itself — per-turn p50 at a
      realistic cadence cannot see a ~5ms cycle becoming seconds (a probe
      that blocks, serializes on a lock, or stops being async), this can.
      The bound is generous because a loaded CI box prices one local HTTP
      round-trip at tens of milliseconds.

    Single-stack A/B block design: the daemon starts and stops on ONE live
    executor (same process, same sandbox, same session), eliminating the
    per-process scheduling-placement bias that dominates a 5% gate on a
    loaded CI machine; alternating blocks handle load drift and trimmed
    medians handle burst noise."""
    interval = 0.5
    stack = _OverheadStack(
        "probe-ab",
        device_probe_interval=interval,
        device_probe_timeout=2.0,
    )
    off_samples: list[float] = []
    on_samples: list[float] = []
    cycle_samples: list[float] = []
    probe = None
    try:
        await stack.start(num_files, file_bytes)
        await stack.turn(record=False)  # the cold upload turn
        from bee_code_interpreter_fs_tpu.services.device_health import (
            DeviceHealthProbe,
        )

        probe = DeviceHealthProbe(stack.executor)
        blocks = max(6, 2 * repeats)
        turns_per_block = 24
        for _ in range(blocks):
            # One unrecorded settle turn after each toggle (symmetric on
            # both sides): start() fires its first probe cycle immediately,
            # and that one-off start transient is a bench artifact — the
            # production daemon starts once per process, so steady state is
            # what the gate must measure.
            await stack.turn(record=False)
            stack.samples = []
            for _ in range(turns_per_block):
                await stack.turn(record=True)
            off_samples.extend(stack.samples)
            probe.start()  # probes immediately, then every `interval`
            await stack.turn(record=False)
            stack.samples = []
            for _ in range(turns_per_block):
                await stack.turn(record=True)
            on_samples.extend(stack.samples)
            await probe.stop()  # restart-safe: next block start()s again
        # Snapshot BEFORE the direct cycle-latency section below: the
        # probe_actually_ran check must count only cycles the DAEMON ran
        # during the measured ON blocks — the standalone probe_once calls
        # would otherwise satisfy it even if start() never probed at all.
        # And it must exceed ONE PER BLOCK: each start() fires exactly one
        # immediate cycle during the unrecorded settle turn, so equality
        # with `blocks` would mean no cycle ever overlapped a RECORDED
        # turn and the p50 gate measured two probe-off legs.
        leg_cycles = probe._cycles
        # Direct cycle-latency samples (the probe-regression detector).
        await probe.probe_once()  # warm the client path
        for _ in range(10):
            t0 = time.perf_counter()
            await probe.probe_once()
            cycle_samples.append(time.perf_counter() - t0)
    finally:
        if probe is not None:
            await probe.stop()
        await stack.close()

    off, on = _trimmed_p50(off_samples), _trimmed_p50(on_samples)
    cycle = _trimmed_p50(cycle_samples)
    gate = off * 1.05 + 0.005
    return {
        "metric": (
            "device-health probe overhead on the unchanged-turn path "
            "(p50 seconds)"
        ),
        "probe_off_p50_s": round(off, 4),
        "probe_on_p50_s": round(on, 4),
        "probe_interval_s": interval,
        "probe_cycles_during_leg": leg_cycles,
        "probe_cycle_p50_s": round(cycle, 4),
        "gate_p50_s": round(gate, 4),
        "checks": {
            "probe_on_within_5pct_plus_5ms_of_off": on <= gate,
            "probe_cycle_under_250ms": cycle <= 0.25,
            # The DAEMON must have probed INSIDE the measured ON turns —
            # strictly more cycles than the one-per-block start transient
            # — or the p50 gate trivially measures two probe-off legs.
            "probe_actually_ran": leg_cycles > blocks,
        },
    }


async def run_bench(num_files: int, file_bytes: int, repeats: int) -> dict:
    tmp = tempfile.mkdtemp(prefix="bench-transfer-")
    executor = _make_executor(tmp)
    try:
        files = {}
        for i in range(num_files):
            # Distinct random content per file: dedup must come from the
            # manifest protocol, not accidentally-identical payloads.
            object_id = await executor.storage.write(
                secrets.token_bytes(file_bytes)
            )
            files[f"/workspace/input-{i:03d}.bin"] = object_id
        changed_id = await executor.storage.write(secrets.token_bytes(file_bytes))
        session = "bench-transfer"
        source = "import glob; print(len(glob.glob('input-*.bin')))"

        cold = await _timed_execute(executor, source, files, session)
        unchanged_runs = [
            await _timed_execute(executor, source, files, session)
            for _ in range(max(1, repeats))
        ]
        one_changed_files = dict(files)
        one_changed_files[f"/workspace/input-000.bin"] = changed_id
        one_changed = await _timed_execute(
            executor, source, one_changed_files, session
        )

        unchanged = min(unchanged_runs, key=lambda r: r["wall_s"])
        tracing = await tracing_overhead_bench(num_files, file_bytes, repeats)
        device_probe = await probe_overhead_bench(
            num_files, file_bytes, repeats
        )
        total_bytes = num_files * file_bytes
        checks = {
            "cold_moves_all_bytes": cold["upload_bytes"] == total_bytes,
            "cold_skips_nothing": cold["upload_skipped_bytes"] == 0,
            "unchanged_moves_zero_bytes": unchanged["upload_bytes"] == 0,
            "unchanged_skips_all_bytes": (
                unchanged["upload_skipped_bytes"] == total_bytes
            ),
            "one_changed_moves_one_file": (
                one_changed["upload_bytes"] == file_bytes
                and one_changed["upload_skipped_bytes"]
                == total_bytes - file_bytes
            ),
        }
        return {
            "metric": "workspace-sync bytes moved per session turn",
            "config": {
                "files": num_files,
                "file_bytes": file_bytes,
                "total_bytes": total_bytes,
                "repeats": repeats,
            },
            "cold": cold,
            "unchanged": unchanged,
            "one_changed": one_changed,
            "tracing": tracing,
            "device_probe": device_probe,
            "checks": checks,
            "ok": (
                all(checks.values())
                and all(tracing["checks"].values())
                and all(device_probe["checks"].values())
            ),
        }
    finally:
        await executor.close()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--files", type=int, default=16)
    parser.add_argument("--bytes", type=int, default=65536)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--out", default=str(REPO_ROOT / "BENCH_transfer.json"))
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="tiny file set + hard-fail on invariant breakage (CI leg)",
    )
    args = parser.parse_args()
    if args.smoke:
        args.files = min(args.files, 4)
        args.bytes = min(args.bytes, 8192)
        args.repeats = 1
    blob = asyncio.run(run_bench(args.files, args.bytes, args.repeats))
    Path(args.out).write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob))
    if not blob["ok"]:
        print("TRANSFER BENCH INVARIANT FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
