#!/usr/bin/env python3
"""Workspace-sync microbench: Execute latency and bytes moved for
(a) a cold first session turn, (b) a session turn with unchanged input
files, and (c) a turn with exactly one changed file.

Drives the real local backend + C++ executor (warm JAX import off — this
measures the transfer protocol, not TPU init) and reads the byte movement
straight out of ``Result.phases``, which the delta sync populates. Emits a
``BENCH_transfer.json`` blob::

    {"config": {...}, "cold": {...}, "unchanged": {...}, "one_changed": {...},
     "ok": true}

The headline invariant (the ISSUE acceptance criterion): the unchanged turn
moves ZERO upload bytes regardless of file count or size — O(1) wire cost,
not O(total bytes x hosts) — and its skipped-bytes counters are nonzero
while the cold turn's are zero. ``--smoke`` (CI) shrinks the file set and
exits nonzero when any invariant breaks.

A second gate (ISSUE 4) measures TRACING overhead on the same unchanged-turn
path: the p50 with tracing enabled at 0% sampling must stay within 5% of the
tracing-disabled p50 (the no-op fast path really is a no-op); the 100%
number is recorded for reference.

Usage:
    python scripts/bench_transfer.py [--files 16] [--bytes 65536]
        [--repeats 3] [--out BENCH_transfer.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import secrets
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# The transfer bench must not fight a TPU plugin for the chip; everything
# here is control-plane + wire mechanics.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402


def _phase_blob(result, wall_s: float) -> dict:
    phases = result.phases
    return {
        "wall_s": round(wall_s, 4),
        "upload_s": round(phases.get("upload", 0.0), 4),
        "download_s": round(phases.get("download", 0.0), 4),
        "upload_bytes": int(phases.get("upload_bytes", 0.0)),
        "upload_skipped_bytes": int(phases.get("upload_skipped_bytes", 0.0)),
        "download_bytes": int(phases.get("download_bytes", 0.0)),
        "download_skipped_bytes": int(
            phases.get("download_skipped_bytes", 0.0)
        ),
    }


async def _timed_execute(executor, source, files, session) -> dict:
    start = time.perf_counter()
    result = await executor.execute(source, files=files, executor_id=session)
    wall = time.perf_counter() - start
    if result.exit_code != 0:
        raise RuntimeError(f"bench execute failed: {result.stderr[:500]}")
    return _phase_blob(result, wall)


def _make_executor(tmp: str, **config_overrides) -> CodeExecutor:
    config = Config(
        file_storage_path=f"{tmp}/storage",
        local_sandbox_root=f"{tmp}/sandboxes",
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        default_execution_timeout=120.0,
        **config_overrides,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


class _OverheadStack:
    """One config leg of the tracing-overhead probe: a fresh executor stack
    plus its own session and input set. Traced legs wrap every execute in a
    root span, because without one the pipeline's child spans no-op
    regardless of sampling and the comparison would measure nothing."""

    def __init__(self, label: str, **config_overrides) -> None:
        self.label = label
        self.config_overrides = config_overrides
        self.samples: list[float] = []
        self.executor: CodeExecutor | None = None
        self.files: dict[str, str] = {}

    async def start(self, num_files: int, file_bytes: int) -> None:
        tmp = tempfile.mkdtemp(prefix=f"bench-tracing-{self.label}-")
        self.executor = _make_executor(tmp, **self.config_overrides)
        for i in range(num_files):
            object_id = await self.executor.storage.write(
                secrets.token_bytes(file_bytes)
            )
            self.files[f"/workspace/input-{i:03d}.bin"] = object_id

    async def turn(self, record: bool) -> None:
        with self.executor.tracer.start_trace("bench unchanged-turn"):
            start = time.perf_counter()
            result = await self.executor.execute(
                "import glob; print(len(glob.glob('input-*.bin')))",
                files=self.files,
                executor_id="bench-tracing",
            )
            wall = time.perf_counter() - start
        if result.exit_code != 0:
            raise RuntimeError(f"bench execute failed: {result.stderr[:500]}")
        if record:
            self.samples.append(wall)

    def p50(self) -> float:
        return statistics.median(self.samples)


async def tracing_overhead_bench(
    num_files: int, file_bytes: int, repeats: int
) -> dict:
    """ISSUE 4 satellite: unchanged-turn p50 with tracing disabled vs
    enabled@0% vs enabled@100%. The gate: 0% sampling must be free — within
    5% of disabled (plus a 5ms epsilon so sub-ms scheduler jitter on a
    ~50ms path cannot flake CI). The three legs are INTERLEAVED turn by
    turn, not run back to back: machine-load drift between sequential legs
    otherwise swamps the very overhead being measured."""
    stacks = [
        _OverheadStack("off", tracing_enabled=False),
        _OverheadStack("s0", tracing_sample_ratio=0.0),
        _OverheadStack("s100", tracing_sample_ratio=1.0),
    ]
    try:
        for stack in stacks:
            await stack.start(num_files, file_bytes)
            await stack.turn(record=False)  # the cold upload turn
        for _ in range(max(5, repeats)):
            for stack in stacks:
                await stack.turn(record=True)
    finally:
        for stack in stacks:
            if stack.executor is not None:
                await stack.executor.close()
    off, sampled_0, sampled_100 = (s.p50() for s in stacks)
    gate = off * 1.05 + 0.005
    return {
        "metric": "tracing overhead on the unchanged-turn path (p50 seconds)",
        "disabled_p50_s": round(off, 4),
        "sampling_0_p50_s": round(sampled_0, 4),
        "sampling_100_p50_s": round(sampled_100, 4),
        "gate_p50_s": round(gate, 4),
        "checks": {"sampling_0_within_5pct_of_disabled": sampled_0 <= gate},
    }


async def run_bench(num_files: int, file_bytes: int, repeats: int) -> dict:
    tmp = tempfile.mkdtemp(prefix="bench-transfer-")
    executor = _make_executor(tmp)
    try:
        files = {}
        for i in range(num_files):
            # Distinct random content per file: dedup must come from the
            # manifest protocol, not accidentally-identical payloads.
            object_id = await executor.storage.write(
                secrets.token_bytes(file_bytes)
            )
            files[f"/workspace/input-{i:03d}.bin"] = object_id
        changed_id = await executor.storage.write(secrets.token_bytes(file_bytes))
        session = "bench-transfer"
        source = "import glob; print(len(glob.glob('input-*.bin')))"

        cold = await _timed_execute(executor, source, files, session)
        unchanged_runs = [
            await _timed_execute(executor, source, files, session)
            for _ in range(max(1, repeats))
        ]
        one_changed_files = dict(files)
        one_changed_files[f"/workspace/input-000.bin"] = changed_id
        one_changed = await _timed_execute(
            executor, source, one_changed_files, session
        )

        unchanged = min(unchanged_runs, key=lambda r: r["wall_s"])
        tracing = await tracing_overhead_bench(num_files, file_bytes, repeats)
        total_bytes = num_files * file_bytes
        checks = {
            "cold_moves_all_bytes": cold["upload_bytes"] == total_bytes,
            "cold_skips_nothing": cold["upload_skipped_bytes"] == 0,
            "unchanged_moves_zero_bytes": unchanged["upload_bytes"] == 0,
            "unchanged_skips_all_bytes": (
                unchanged["upload_skipped_bytes"] == total_bytes
            ),
            "one_changed_moves_one_file": (
                one_changed["upload_bytes"] == file_bytes
                and one_changed["upload_skipped_bytes"]
                == total_bytes - file_bytes
            ),
        }
        return {
            "metric": "workspace-sync bytes moved per session turn",
            "config": {
                "files": num_files,
                "file_bytes": file_bytes,
                "total_bytes": total_bytes,
                "repeats": repeats,
            },
            "cold": cold,
            "unchanged": unchanged,
            "one_changed": one_changed,
            "tracing": tracing,
            "checks": checks,
            "ok": all(checks.values()) and all(tracing["checks"].values()),
        }
    finally:
        await executor.close()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--files", type=int, default=16)
    parser.add_argument("--bytes", type=int, default=65536)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--out", default=str(REPO_ROOT / "BENCH_transfer.json"))
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="tiny file set + hard-fail on invariant breakage (CI leg)",
    )
    args = parser.parse_args()
    if args.smoke:
        args.files = min(args.files, 4)
        args.bytes = min(args.bytes, 8192)
        args.repeats = 1
    blob = asyncio.run(run_bench(args.files, args.bytes, args.repeats))
    Path(args.out).write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob))
    if not blob["ok"]:
        print("TRANSFER BENCH INVARIANT FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
