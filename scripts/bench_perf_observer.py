"""Performance-observer overhead bench + CI smoke gate (ISSUE 14).

The perf plane touches EVERY request: the runner samples device memory
around each exec, the executor folds the wire block into phases +
ledger, and the observer's sketches record every phase latency. All of
that buys its drift-detection value only if the healthy path stays free.
This bench drives the established unchanged-turn workload (a session turn
whose input files are already synced — the fastest real turn the service
has, i.e. the most overhead-sensitive) through ONE executor stack,
interleaving turns with the observer toggled off and on. The gate, the
established overhead discipline (PR 8/11):

    enabled unchanged-turn p50 <= disabled p50 * 1.05 + 5ms

Interleaved single-stack turns + trimmed medians, like the tracing, probe,
and quota overhead benches: same process, same sandbox, only the perf
plane varies — CI load spikes hit both sides symmetrically.

Also recorded (informational, no gate): the pure record() cost — how many
latency samples per second one series absorbs.

Usage:
    python scripts/bench_perf_observer.py [--repeats 40] [--files 8]
        [--file-bytes 4096] [--out BENCH_perf.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import secrets
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

TENANT = "bench-tenant"


def _trimmed_p50(samples: list[float]) -> float:
    """Median of the fastest two-thirds (the transfer bench's estimator):
    symmetric across both sides of the comparison, so CI load bursts
    cannot bias the delta while real per-turn overhead still shifts the
    fast samples it would hide in."""
    fast = sorted(samples)[: max(1, (2 * len(samples) + 2) // 3)]
    return statistics.median(fast)


def _make_executor(tmp: str) -> CodeExecutor:
    config = Config(
        file_storage_path=f"{tmp}/storage",
        local_sandbox_root=f"{tmp}/sandboxes",
        executor_pod_queue_target_length=1,
        jax_compilation_cache_dir="",
        compile_cache_prewarm=False,
        default_execution_timeout=120.0,
        # Tight windows so every measured turn exercises the FULL path —
        # sketch records, window rolls, verdict classification — not just
        # the between-rolls fast case.
        perf_window_seconds=1.0,
        perf_min_window_samples=3,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


async def run_bench(num_files: int, file_bytes: int, repeats: int) -> dict:
    tmp = tempfile.mkdtemp(prefix="bench-perf-")
    executor = _make_executor(tmp)
    files: dict[str, str] = {}
    for i in range(num_files):
        object_id = await executor.storage.write(
            secrets.token_bytes(file_bytes)
        )
        files[f"/workspace/input-{i:03d}.bin"] = object_id
    off_samples: list[float] = []
    on_samples: list[float] = []
    try:
        async def turn() -> float:
            start = time.perf_counter()
            result = await executor.execute(
                "import glob; print(len(glob.glob('input-*.bin')))",
                files=files,
                executor_id="bench-perf",
                tenant=TENANT,
            )
            wall = time.perf_counter() - start
            if result.exit_code != 0:
                raise RuntimeError(
                    f"bench execute failed: {result.stderr[:500]}"
                )
            return wall

        # Settle: first turns pay spawn + cold sync; the comparison is the
        # steady unchanged turn.
        for _ in range(3):
            await turn()
        # Interleaved A/B: the observer's `enabled` flag is the exact
        # kill-switch serving-path toggle (record()/take_profile_arm()
        # return immediately and the wire payload drops the device_memory
        # flag when off).
        for _ in range(repeats):
            executor.perf.enabled = False
            off_samples.append(await turn())
            executor.perf.enabled = True
            on_samples.append(await turn())

        armed_turn = await turn()  # one extra armed sample for the record
        # Pure sketch-record cost (informational): samples/second one
        # series absorbs — the per-request recording is 4 of these.
        record_start = time.perf_counter()
        for i in range(100_000):
            executor.perf.record(0, "exec", 0.01 + (i % 7) * 0.001)
        record_wall = time.perf_counter() - record_start
    finally:
        await executor.close()

    off_p50 = _trimmed_p50(off_samples)
    on_p50 = _trimmed_p50(on_samples)
    budget = off_p50 * 1.05 + 0.005
    return {
        "workload": {
            "num_files": num_files,
            "file_bytes": file_bytes,
            "repeats": repeats,
        },
        "perf_disabled_p50_s": round(off_p50, 6),
        "perf_enabled_p50_s": round(on_p50, 6),
        "overhead_s": round(on_p50 - off_p50, 6),
        "overhead_frac": round((on_p50 - off_p50) / off_p50, 6)
        if off_p50 > 0
        else 0.0,
        "armed_turn_s": round(armed_turn, 6),
        "record_per_sample_us": round(record_wall / 100_000 * 1e6, 3),
        "gate": {
            "rule": "enabled_p50 <= disabled_p50 * 1.05 + 5ms",
            "budget_s": round(budget, 6),
            "pass": bool(on_p50 <= budget),
        },
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repeats", type=int, default=40)
    parser.add_argument("--files", type=int, default=8)
    parser.add_argument("--file-bytes", type=int, default=4096)
    parser.add_argument("--out", default="BENCH_perf.json")
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="CI profile: fewer repeats, same gate",
    )
    args = parser.parse_args()
    repeats = 15 if args.smoke else args.repeats
    result = asyncio.run(run_bench(args.files, args.file_bytes, repeats))
    Path(args.out).write_text(json.dumps(result, indent=2) + "\n")
    print(json.dumps(result, indent=2))
    if not result["gate"]["pass"]:
        print(
            "GATE FAILED: the perf observer taxes the unchanged turn",
            file=sys.stderr,
        )
        return 1
    print("gate MET")
    return 0


if __name__ == "__main__":
    sys.exit(main())
