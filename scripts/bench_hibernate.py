#!/usr/bin/env python3
"""Session-durability microbench: hibernate must actually release the chip,
and the lazy restore must bring the session back intact within a bounded
latency tax over a fresh-session turn.

Drives the real local backend + C++ executor (no jax import — the numbers
isolate the durability plane, not XLA). Three legs:

- ``fresh``    — first turn of a brand-new session: sandbox acquire +
  execute. The baseline the restore tax is gated against.
- ``restore``  — a session runs a turn that mutates interpreter state
  (env var) AND the workspace (marker file), idles past the hibernate
  threshold, is checkpointed and its sandbox disposed (chip released),
  then the next turn lazily restores onto a fresh sandbox. The turn must
  see the exact state back, continue ``session_seq`` at 2, and report the
  ``restore`` phase.
- ``disabled`` — ``session_durability_enabled=False`` (the
  ``APP_SESSION_DURABILITY_ENABLED=0`` kill switch): the sweep must
  hibernate NOTHING, the session stays pinned (pre-durability semantics
  byte-for-byte), and no store state touches disk.

Emits ``BENCH_hibernate.json``. Gates:

- ``chip_released_on_hibernate`` — after the hibernate sweep, the
  session's lane capacity is back (``_session_held`` drained) and the
  record is visible in the statusz durability block.
- ``restored_state_intact``      — the restore turn sees the env var and
  the workspace file byte-exact, seq continues at 2, phase reported.
- ``restore_within_budget``      — restore-turn p50 within 1.5x + 500ms
  of the fresh-session-turn p50 (the restore is a sandbox acquire plus a
  state upload; it must never cost a cold re-derivation).
- ``kill_switch_parity``         — with the switch thrown the sweep is a
  no-op, the chip stays held, the session keeps serving live, and no
  session-store directory exists.

``--smoke`` (CI) shrinks repeats and hard-fails on any gate breakage.

Usage:
    python scripts/bench_hibernate.py [--repeats 5]
        [--out BENCH_hibernate.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import os  # noqa: E402

os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

MUTATE = """
import os
os.environ['HIBERNATE_PROBE'] = '42'
open('marker.txt', 'w').write('durable bytes')
print('state planted')
"""

OBSERVE = """
import os
print(os.environ.get('HIBERNATE_PROBE'))
print(open('marker.txt').read())
"""

EXPECTED_OBSERVE = "42\ndurable bytes\n"

# The hibernate threshold for the bench: long enough that in-flight turns
# never trip it, short enough that one sleep ages the session past it.
IDLE_S = 0.05


def make_executor(tmp: Path, **overrides) -> CodeExecutor:
    defaults = dict(
        file_storage_path=str(tmp / "storage"),
        local_sandbox_root=str(tmp / "sandboxes"),
        executor_pod_queue_target_length=1,
        executor_reuse_sandboxes=True,
        jax_compilation_cache_dir="",
        compile_cache_enabled=False,
        default_execution_timeout=120.0,
        session_hibernate_idle_seconds=IDLE_S,
    )
    defaults.update(overrides)
    config = Config(**defaults)
    backend = LocalSandboxBackend(config, warm_import_jax=False)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


async def settle(executor: CodeExecutor) -> None:
    for _ in range(400):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


def held_chips(executor: CodeExecutor) -> int:
    return sum(executor._session_held.values())


async def timed_turn(executor: CodeExecutor, source: str, executor_id: str):
    start = time.perf_counter()
    result = await executor.execute(source, executor_id=executor_id)
    wall = time.perf_counter() - start
    if result.exit_code != 0:
        raise RuntimeError(f"bench execute failed: {result.stderr[:500]}")
    return round(wall, 5), result


def p50(walls: list[float]) -> float:
    return round(statistics.median(walls), 5)


async def run_bench(repeats: int) -> dict:
    tmp = Path(tempfile.mkdtemp(prefix="bench-hibernate-"))

    fresh_walls: list[float] = []
    restore_walls: list[float] = []
    restore_runs: list[dict] = []
    chip_cycle_ok = True

    executor = make_executor(tmp / "enabled")
    try:
        # Spin-up: pay the first sandbox spawn outside every timing window.
        await timed_turn(executor, "print('spin-up')", "warmup")
        await executor.close_session("warmup")
        await settle(executor)

        for n in range(repeats):
            sid = f"bench-{n}"
            wall, first = await timed_turn(executor, MUTATE, sid)
            fresh_walls.append(wall)
            if first.session_seq != 1:
                raise RuntimeError("fresh session did not start at seq 1")

            # Age past the hibernate threshold, sweep, and verify the chip
            # actually came back before the restore is timed.
            await asyncio.sleep(IDLE_S * 3)
            await executor.sweep_sessions()
            await settle(executor)
            status = executor.statusz()["session_durability"]
            chip_cycle_ok = chip_cycle_ok and (
                held_chips(executor) == 0
                and sid not in executor._sessions
                and status["hibernated"] >= 1
            )

            wall, back = await timed_turn(executor, OBSERVE, sid)
            restore_walls.append(wall)
            restore_runs.append(
                {
                    "wall_s": wall,
                    "seq": back.session_seq,
                    "stdout": back.stdout,
                    "restore_phase": "restore" in back.phases,
                }
            )
            await executor.close_session(sid)
            await settle(executor)
        enabled_status = executor.statusz()["session_durability"]
    finally:
        await executor.close()

    # --- kill switch: the sweep must be a no-op, the session stays live.
    executor = make_executor(
        tmp / "disabled", session_durability_enabled=False
    )
    try:
        await timed_turn(executor, MUTATE, "pinned")
        await asyncio.sleep(IDLE_S * 3)
        swept = await executor.sweep_sessions()
        await settle(executor)
        still_pinned = (
            swept == 0
            and "pinned" in executor._sessions
            and held_chips(executor) >= 1
        )
        _, live = await timed_turn(executor, OBSERVE, "pinned")
        disabled_clean = (
            still_pinned
            and live.session_seq == 2
            and live.stdout == EXPECTED_OBSERVE
            and "restore" not in live.phases
            and executor.statusz()["session_durability"]["enabled"] is False
            and not (tmp / "disabled" / "storage" / ".session-store").exists()
        )
    finally:
        await executor.close()

    fresh_p50 = p50(fresh_walls)
    restore_p50 = p50(restore_walls)
    budget_s = round(fresh_p50 * 1.5 + 0.5, 5)
    checks = {
        "chip_released_on_hibernate": chip_cycle_ok,
        "restored_state_intact": all(
            r["seq"] == 2
            and r["stdout"] == EXPECTED_OBSERVE
            and r["restore_phase"]
            for r in restore_runs
        ),
        "restore_within_budget": restore_p50 <= budget_s,
        "kill_switch_parity": disabled_clean,
    }
    return {
        "metric": (
            "session-turn wall p50: lazy restore after hibernate vs fresh "
            "session, chip release + kill-switch parity gates"
        ),
        "config": {
            "repeats": repeats,
            "hibernate_idle_s": IDLE_S,
            "workload": "env var + workspace marker file round trip",
        },
        "fresh": {"p50_wall_s": fresh_p50, "walls_s": fresh_walls},
        "restore": {
            "p50_wall_s": restore_p50,
            "walls_s": restore_walls,
            "runs": restore_runs,
        },
        "restore_budget_s": budget_s,
        "store": enabled_status,
        "checks": checks,
        "ok": all(checks.values()),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument(
        "--out", default=str(REPO_ROOT / "BENCH_hibernate.json")
    )
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="three repeats + hard-fail on gate breakage (CI leg)",
    )
    args = parser.parse_args()
    if args.smoke:
        args.repeats = min(args.repeats, 3)
    blob = asyncio.run(run_bench(max(1, args.repeats)))
    Path(args.out).write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob))
    if not blob["ok"]:
        print("HIBERNATE BENCH GATE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
