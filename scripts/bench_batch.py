#!/usr/bin/env python3
"""Batched-execution-lanes microbench: aggregate throughput for 8 concurrent
one-chip-sized jobs on an 8-chip lane, fused into ONE dispatch vs the serial
pre-batching reality of N sandbox round-trips.

Drives the real local backend + C++ executor with a warm jax runner (the
production shape: the fused /execute-batch staging, per-thread device
pinning, and stdout demux are all exercised end to end). Each job is the
same small matmul chain — known FLOPs, so aggregate GFLOPS is total work
over wall clock and the comparison is apples to apples:

- ``serial``  — APP_BATCHING_ENABLED=0: the 8 jobs run as 8 sequential
  Execute round-trips on one warm recycled sandbox — the pre-this-PR
  reality of the lane's single slice serving its queue one caller at a
  time, which includes the generation turnover (workspace reset) between
  consecutive callers' jobs. The turnover AFTER the last job is excluded
  (symmetric with the batched leg, whose one post-batch turnover is also
  outside the timed window).
- ``batched`` — batching ON, window sized so the 8 concurrent submissions
  always coalesce: one multi-job grant, one fused dispatch, one turnover,
  per-job results demuxed back.

Emits ``BENCH_batch.json``. The headline gate (ROADMAP verbatim, the ISSUE
acceptance criterion): batched aggregate GFLOPS >= 4x the serial baseline,
AND every batched run actually rode the fused path (``batch_jobs`` == 8 in
each job's phases — a silent fallback to serial would otherwise let wall-
clock noise decide the gate). ``--smoke`` (CI) shrinks repeats and
hard-fails on any invariant breakage.

Usage:
    python scripts/bench_batch.py [--repeats 3] [--out BENCH_batch.json]
        [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# The bench must not fight a TPU plugin for the chip by default; on a real
# TPU host run with BENCH_PLATFORM=tpu to measure the 8-chip ICI lane this
# subsystem exists for (there the fused dispatch also parallelizes compute;
# on CPU the win it proves is round-trip coalescing).
os.environ.setdefault("JAX_PLATFORMS", os.environ.get("BENCH_PLATFORM", "cpu"))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

LANE = 8  # the 8-chip lane of the acceptance criterion
JOBS = 8  # one one-chip-sized job per chip
N = 64  # matmul side: a genuinely SMALL array job (the ISSUE's premise —
ITERS = 4  # round-trip overhead, not FLOPs, dominates its serial cost)
# Dense N×N matmul = 2N³ FLOPs; ITERS of them per job.
FLOPS_PER_JOB = ITERS * 2 * N**3

# The one-chip-sized workload: a chained small matmul via plain jnp ops —
# their compiled executables live in jax's process-wide C++ dispatch cache,
# so after each leg's untimed warm run every job is compile-free (a
# per-job `jax.jit(lambda ...)` would retrace on every request, measuring
# single-threaded trace time instead of dispatch throughput). The fused
# dispatch pins each job's ops to its assigned device.
JOB_SOURCE = f"""
import jax.numpy as jnp
x = jnp.ones(({N}, {N}), dtype=jnp.float32)
y = jnp.eye({N}, dtype=jnp.float32)
for _ in range({ITERS}):
    x = x @ y
x.block_until_ready()
print("job done")
"""


def make_executor(tmp: Path, **overrides) -> CodeExecutor:
    defaults = dict(
        file_storage_path=str(tmp / "storage"),
        local_sandbox_root=str(tmp / "sandboxes"),
        # chips_per_host >= LANE keeps the 8-chip lane single-host (the
        # fused driver runs on one host's runner; multi-host slices stay
        # serial by design).
        tpu_chips_per_host=LANE,
        executor_reuse_sandboxes=True,
        executor_pod_queue_target_length=1,
        default_execution_timeout=600.0,
        compile_cache_prewarm=False,
        batch_max_jobs=JOBS,
        # Generous window so the 8 near-simultaneous submissions always
        # coalesce even on a loaded CI host; a FULL batch fires
        # immediately, so the window never shows up in the timing.
        batch_window_ms=2000.0,
    )
    defaults.update(overrides)
    config = Config(**defaults)
    backend = LocalSandboxBackend(config, warm_import_jax=True)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


async def settle(executor: CodeExecutor) -> None:
    """Wait out release/turnover/refill tasks so runs don't interleave."""
    for _ in range(400):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


def check_result(result, leg: str) -> dict:
    if result.exit_code != 0:
        raise RuntimeError(
            f"{leg} job failed (exit {result.exit_code}): {result.stderr[:500]}"
        )
    return {
        "exit_code": result.exit_code,
        "batch_jobs": int(result.phases.get("batch_jobs", 0.0)),
    }


async def serial_leg(executor: CodeExecutor, repeats: int) -> list[dict]:
    """JOBS sequential round-trips per repeat on one warm recycled sandbox.
    Wall clock spans the first submit to the LAST job's result, including
    the generation turnover between consecutive callers' jobs (the slice
    cannot start job k+1 until it is reset from job k — that reset is part
    of the serial round-trip the fused dispatch eliminates). The turnover
    after the last job is excluded, symmetric with the batched leg."""
    runs = []
    # Warm: spawn + first compile, untimed.
    check_result(await executor.execute(JOB_SOURCE, chip_count=LANE), "serial")
    await settle(executor)
    for _ in range(repeats):
        wall = 0.0
        jobs = []
        for i in range(JOBS):
            start = time.perf_counter()
            result = await executor.execute(JOB_SOURCE, chip_count=LANE)
            wall += time.perf_counter() - start
            jobs.append(check_result(result, "serial"))
            start = time.perf_counter()
            await settle(executor)
            if i < JOBS - 1:
                wall += time.perf_counter() - start
        runs.append(
            {
                "wall_s": round(wall, 4),
                "gflops": round(JOBS * FLOPS_PER_JOB / wall / 1e9, 3),
                "jobs": jobs,
            }
        )
    return runs


async def batched_leg(executor: CodeExecutor, repeats: int) -> list[dict]:
    """JOBS concurrent submissions per repeat: same tenant, same lane, same
    (empty) env/limits — one compatibility key, one fused dispatch."""

    async def burst() -> tuple[float, list[dict]]:
        start = time.perf_counter()
        results = await asyncio.gather(
            *(executor.execute(JOB_SOURCE, chip_count=LANE) for _ in range(JOBS))
        )
        wall = time.perf_counter() - start
        return wall, [check_result(r, "batched") for r in results]

    runs = []
    await burst()  # warm: spawn + first compile, untimed
    await settle(executor)
    for _ in range(repeats):
        wall, jobs = await burst()
        runs.append(
            {
                "wall_s": round(wall, 4),
                "gflops": round(JOBS * FLOPS_PER_JOB / wall / 1e9, 3),
                "jobs": jobs,
            }
        )
        await settle(executor)
    return runs


def p50(runs: list[dict], key: str) -> float:
    return round(statistics.median(r[key] for r in runs), 4)


async def run_bench(repeats: int) -> dict:
    tmp = Path(tempfile.mkdtemp(prefix="bench-batch-"))

    executor = make_executor(tmp / "serial", batching_enabled=False)
    try:
        serial_runs = await serial_leg(executor, repeats)
    finally:
        await executor.close()

    executor = make_executor(tmp / "batched")
    try:
        batched_runs = await batched_leg(executor, repeats)
    finally:
        await executor.close()

    # Collect subprocess transports while the loop is still alive: their
    # __del__ after asyncio.run() closes the loop prints a spurious
    # "Event loop is closed" traceback.
    import gc

    gc.collect()
    await asyncio.sleep(0)

    serial_gflops = p50(serial_runs, "gflops")
    batched_gflops = p50(batched_runs, "gflops")
    checks = {
        # THE acceptance criterion (ROADMAP verbatim): aggregate GFLOPS for
        # 8 concurrent 1-chip-sized jobs on the 8-chip lane, >= 4x serial.
        "batched_4x_serial": batched_gflops >= 4.0 * serial_gflops,
        # Every batched job actually rode a FULL fused dispatch — a silent
        # serial fallback must fail the gate, not hide inside wall-clock.
        "all_jobs_batched": all(
            job["batch_jobs"] == JOBS for run in batched_runs for job in run["jobs"]
        ),
        # The kill-switch leg never touched the batch path.
        "serial_path_untouched": all(
            job["batch_jobs"] == 0 for run in serial_runs for job in run["jobs"]
        ),
    }
    return {
        "metric": (
            "aggregate GFLOPS, 8 concurrent 1-chip-sized matmul jobs on an "
            "8-chip lane: one fused /execute-batch dispatch vs 8 serial "
            "sandbox round-trips"
        ),
        "config": {
            "repeats": repeats,
            "platform": os.environ.get("JAX_PLATFORMS", ""),
            "lane_chips": LANE,
            "jobs": JOBS,
            "kernel": f"{ITERS}x jnp matmul {N}x{N}",
            "flops_per_job": FLOPS_PER_JOB,
        },
        "serial": {
            "p50_gflops": serial_gflops,
            "p50_wall_s": p50(serial_runs, "wall_s"),
            "runs": serial_runs,
        },
        "batched": {
            "p50_gflops": batched_gflops,
            "p50_wall_s": p50(batched_runs, "wall_s"),
            "runs": batched_runs,
        },
        "speedup": round(batched_gflops / serial_gflops, 2)
        if serial_gflops
        else None,
        "checks": checks,
        "ok": all(checks.values()),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--out", default=str(REPO_ROOT / "BENCH_batch.json"))
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="two repeats per leg + hard-fail on invariant breakage (CI leg)",
    )
    args = parser.parse_args()
    if args.smoke:
        args.repeats = min(args.repeats, 2)
    blob = asyncio.run(run_bench(max(1, args.repeats)))
    Path(args.out).write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob))
    if not blob["ok"]:
        print("BATCH BENCH INVARIANT FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
