#!/usr/bin/env python3
"""Fleet compile-cache microbench: repeat-workload Execute latency on a
COLD sandbox (fresh process, empty local cache, seeded from the fleet
store) vs a WARM sandbox (recycled process) vs the no-cache cold baseline.

Drives the real local backend + C++ executor with the warm runner
importing jax (the production shape: the runner's jax.monitoring listener
is what reports per-request cache hits). The workload is the jit matmul
kernel from ``examples/benchmark-matmul.py``, distilled to one compile.
Every leg wipes / disposes so the sandbox topology is what the name says:

- ``baseline_cold`` — fleet cache DISABLED + local cache dir wiped before
  every run: each fresh sandbox pays the full XLA compile (the
  pre-this-PR pod reality; multi-second on TPU).
- ``seeded_cold``  — fleet cache ENABLED + local cache dir wiped before
  every run: each fresh sandbox is seeded from the fleet store at spawn
  and the kernel loads from cache (zero recompilation).
- ``warm``         — one sandbox recycled across runs (the best case the
  pool can ever offer).

Emits ``BENCH_compile.json``. The headline gate (the ISSUE acceptance
criterion): seeded-cold Execute exec-phase p50 within 1.25x of the warm
sandbox's, and every seeded-cold run reports cache HITS with zero new
cache entries (no recompilation). Timing separation from baseline_cold is
recorded but only meaningful on real TPU (CPU compiles are milliseconds —
the hit/miss counters are the CI-proof invariant). ``--smoke`` (CI)
shrinks repeats and hard-fails on any invariant breakage.

Usage:
    python scripts/bench_compile_cache.py [--repeats 3]
        [--out BENCH_compile.json] [--smoke]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# The bench must not fight a TPU plugin for the chip by default; on a real
# TPU host run with BENCH_PLATFORM=tpu to measure the multi-second compiles
# this cache exists for.
os.environ.setdefault("JAX_PLATFORMS", os.environ.get("BENCH_PLATFORM", "cpu"))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import (  # noqa: E402
    CodeExecutor,
)
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

# The matmul kernel from examples/benchmark-matmul.py, distilled to a
# single jit compile + dispatch (the bench measures compile amortization,
# not FLOPs).
MATMUL = """
import jax, jax.numpy as jnp
f = jax.jit(lambda a, b: a @ b)
x = jnp.ones((256, 256), dtype=jnp.float32)
f(x, x).block_until_ready()
print("ran")
"""


def make_executor(tmp: Path, cache_dir: Path, **overrides) -> CodeExecutor:
    defaults = dict(
        file_storage_path=str(tmp / "storage"),
        local_sandbox_root=str(tmp / "sandboxes"),
        # No warm pool and no reuse: every execute spawns a genuinely fresh
        # sandbox (the "cold" in cold-sandbox). The warm leg overrides.
        executor_pod_queue_target_length=0,
        executor_reuse_sandboxes=False,
        # The fleet-constant cache path production has (jax hashes the
        # cache-dir PATH into its cache key, so per-sandbox paths would
        # change the keys themselves).
        jax_compilation_cache_dir=str(cache_dir),
        default_execution_timeout=600.0,
        compile_cache_prewarm=False,
    )
    defaults.update(overrides)
    config = Config(**defaults)
    backend = LocalSandboxBackend(config, warm_import_jax=True)
    return CodeExecutor(backend, Storage(config.file_storage_path), config)


async def settle(executor: CodeExecutor) -> None:
    """Wait out release/harvest/refill tasks so legs don't interleave."""
    for _ in range(400):
        pending = list(executor._dispose_tasks) + list(executor._fill_tasks)
        if not pending:
            return
        await asyncio.gather(*pending, return_exceptions=True)


def wipe(cache_dir: Path) -> None:
    """Empty the sandbox-local cache dir: the next sandbox starts as cold
    as a fresh pod's emptyDir."""
    if cache_dir.exists():
        shutil.rmtree(cache_dir)


async def timed_run(executor: CodeExecutor, *, trusted: bool = False) -> dict:
    start = time.perf_counter()
    # trusted=True runs through the pre-warm mechanism (control-plane-
    # authored source, sandbox stays harvest-eligible); harvest admits
    # nothing else, so the prime leg MUST use it — tenant executes taint
    # their sandbox and never fill the fleet store.
    run = executor._execute_trusted if trusted else executor.execute
    result = await run(MATMUL)
    wall = time.perf_counter() - start
    if result.exit_code != 0:
        raise RuntimeError(f"bench execute failed: {result.stderr[:500]}")
    phases = result.phases
    return {
        "wall_s": round(wall, 4),
        "exec_s": round(phases.get("exec", 0.0), 4),
        "hits": int(phases.get("compile_cache_hits", 0.0)),
        "misses": int(phases.get("compile_cache_misses", 0.0)),
        "new_bytes": int(phases.get("compile_cache_new_bytes", 0.0)),
        "seeded_bytes": int(phases.get("compile_cache_seeded_bytes", 0.0)),
    }


def p50(runs: list[dict], key: str) -> float:
    return round(statistics.median(r[key] for r in runs), 4)


async def run_bench(repeats: int) -> dict:
    tmp = Path(tempfile.mkdtemp(prefix="bench-compile-"))
    cache_dir = tmp / "pod-cache-path"

    # --- baseline_cold: no fleet cache, every sandbox compiles from zero.
    baseline_runs = []
    executor = make_executor(tmp / "baseline", cache_dir, compile_cache_enabled=False)
    try:
        for _ in range(repeats):
            wipe(cache_dir)
            baseline_runs.append(await timed_run(executor))
            await settle(executor)
    finally:
        await executor.close()

    # --- prime + seeded_cold: one TRUSTED (pre-warm-style) sandbox run
    # compiles and is harvested at its teardown; every later TENANT sandbox
    # starts with a wiped local cache and is seeded from the fleet store.
    executor = make_executor(tmp / "fleet", cache_dir)
    seeded_runs = []
    try:
        wipe(cache_dir)
        prime = await timed_run(executor, trusted=True)
        await settle(executor)
        store_entries = executor.compile_cache.entry_count()
        store_bytes = executor.compile_cache.total_bytes()
        for _ in range(repeats):
            wipe(cache_dir)
            seeded_runs.append(await timed_run(executor))
            await settle(executor)
    finally:
        await executor.close()

    # --- warm: one recycled sandbox, repeat dispatches (local cache and
    # process survive turnover — the pool's best case).
    executor = make_executor(
        tmp / "warm",
        cache_dir,
        executor_reuse_sandboxes=True,
        executor_pod_queue_target_length=1,
    )
    warm_runs = []
    try:
        await timed_run(executor)  # spawn + first (cache-hit) dispatch
        await settle(executor)
        for _ in range(repeats):
            warm_runs.append(await timed_run(executor))
            await settle(executor)
    finally:
        await executor.close()

    # Collect subprocess transports while the loop is still alive: their
    # __del__ after asyncio.run() closes the loop prints a spurious
    # "Event loop is closed" traceback.
    import gc

    gc.collect()
    await asyncio.sleep(0)

    seeded_p50 = p50(seeded_runs, "exec_s")
    warm_p50 = p50(warm_runs, "exec_s")
    baseline_p50 = p50(baseline_runs, "exec_s")
    # 1.25x + a small epsilon: on CPU both paths run in a few hundred ms
    # and scheduler jitter on a loaded CI host must not flake the gate
    # (on TPU, where baseline is multi-second, the epsilon vanishes in
    # the margin).
    gate = warm_p50 * 1.25 + 0.15
    checks = {
        # THE acceptance criterion: a cold (fresh, empty-cache) sandbox
        # executes the repeat workload at warm-sandbox speed.
        "seeded_cold_within_1_25x_warm": seeded_p50 <= gate,
        # Zero recompilation, proven by counters, not clocks: every seeded
        # run hit the persistent cache and compiled nothing new.
        "seeded_runs_all_hit": all(r["hits"] > 0 for r in seeded_runs),
        "seeded_runs_no_recompile": all(
            r["new_bytes"] == 0 for r in seeded_runs
        ),
        "seeding_moved_bytes": all(
            r["seeded_bytes"] > 0 for r in seeded_runs
        ),
        # The prime run is where the fleet paid its one compile.
        "prime_compiled": prime["new_bytes"] > 0,
        "harvest_filled_store": store_entries > 0 and store_bytes > 0,
        # Baseline sanity: with the kill switch on, nothing reports cache
        # traffic and nothing reaches the store.
        "baseline_reports_no_cache": all(
            r["hits"] == 0 and r["seeded_bytes"] == 0 for r in baseline_runs
        ),
    }
    return {
        "metric": (
            "repeat-workload Execute exec-phase p50: cold-seeded sandbox "
            "vs warm sandbox vs no-cache cold baseline"
        ),
        "config": {
            "repeats": repeats,
            "platform": os.environ.get("JAX_PLATFORMS", ""),
            "kernel": "jit matmul 256x256 (examples/benchmark-matmul.py)",
        },
        "baseline_cold": {"p50_exec_s": baseline_p50, "runs": baseline_runs},
        "prime": prime,
        "store": {"entries": store_entries, "bytes": store_bytes},
        "seeded_cold": {"p50_exec_s": seeded_p50, "runs": seeded_runs},
        "warm": {"p50_exec_s": warm_p50, "runs": warm_runs},
        "gate_p50_s": round(gate, 4),
        "checks": checks,
        "ok": all(checks.values()),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--out", default=str(REPO_ROOT / "BENCH_compile.json"))
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="two repeats per leg + hard-fail on invariant breakage (CI leg)",
    )
    args = parser.parse_args()
    if args.smoke:
        args.repeats = min(args.repeats, 2)
    blob = asyncio.run(run_bench(max(1, args.repeats)))
    Path(args.out).write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob))
    if not blob["ok"]:
        print("COMPILE-CACHE BENCH INVARIANT FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
