#!/usr/bin/env bash
# Build both images, deploy to the current kube context, port-forward, tail.
# Reference parity: scripts/run.sh (build, re-apply, wait Ready, forward
# 8000 + 50051, tail logs).
set -euo pipefail
cd "$(dirname "$0")/.."

MANIFEST="${1:-k8s/local.yaml}"

docker build -t localhost/tpu-code-interpreter:local .
docker build -f executor/Dockerfile -t localhost/tpu-code-executor:local .

kubectl delete pod tpu-code-interpreter --ignore-not-found --wait=true
kubectl apply -f "$MANIFEST"
kubectl wait --for=condition=Ready pod/tpu-code-interpreter --timeout=180s

kubectl port-forward pod/tpu-code-interpreter 8000:8000 50051:50051 &
echo $! > .port-forward.pid
trap 'kill "$(cat .port-forward.pid)" 2>/dev/null || true' EXIT

echo "HTTP  : http://127.0.0.1:8000  (try: curl -s -X POST http://127.0.0.1:8000/v1/execute -H 'content-type: application/json' -d '{\"source_code\": \"print(21*2)\"}')"
echo "gRPC  : 127.0.0.1:50051 (reflection on; health check: python -m bee_code_interpreter_fs_tpu.health_check)"
kubectl logs -f tpu-code-interpreter
