"""Measure the five BASELINE.md benchmark configs through real Execute calls.

Runs on whatever accelerator the machine exposes (one TPU chip here; the
v5e-4 / multi-host shapes are validated structurally by the test suite's
CPU-mesh e2e). Prints one JSON object per config plus a summary table to
paste into BASELINE.md.

Usage: python benchmarks/run_configs.py [--quick]
"""

from __future__ import annotations

import asyncio
import json
import re
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor  # noqa: E402
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

MNIST_TRAIN = """
import time
import numpy as np
import jax, jax.numpy as jnp

# MNIST-shaped MLP train on synthetic data (no dataset egress in the
# sandbox): 784 -> 512 -> 10, jit+grad, batch 128.
key = jax.random.PRNGKey(0)
k1, k2, k3 = jax.random.split(key, 3)
params = {
    "w1": jax.random.normal(k1, (784, 512)) * 0.05,
    "b1": jnp.zeros((512,)),
    "w2": jax.random.normal(k2, (512, 10)) * 0.05,
    "b2": jnp.zeros((10,)),
}
x = jax.random.normal(k3, (128, 784))
y = jax.random.randint(jax.random.PRNGKey(1), (128,), 0, 10)

def loss_fn(p, x, y):
    h = jax.nn.relu(x @ p["w1"] + p["b1"])
    logits = h @ p["w2"] + p["b2"]
    return -jnp.mean(
        jax.nn.log_softmax(logits)[jnp.arange(x.shape[0]), y]
    )

@jax.jit
def step(p, x, y):
    loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
    return jax.tree.map(lambda w, g: w - 0.1 * g, p, grads), loss

params, loss = step(params, x, y)  # compile
jax.block_until_ready(params)
STEPS = 200
t0 = time.perf_counter()
for _ in range(STEPS):
    params, loss = step(params, x, y)
jax.block_until_ready(params)
dt = time.perf_counter() - t0
print(f"platform={jax.devices()[0].platform}")
print(f"final_loss={float(loss):.4f}")
print(f"steps_per_s={STEPS/dt:.1f}")
"""

LLAMA_DECODE = """
import time
import jax, jax.numpy as jnp
from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig, greedy_generate, init_params,
)

cfg = LlamaConfig.tiny(n_layers=4, dim=512, n_heads=8, n_kv_heads=8,
                       hidden_dim=1376, vocab_size=32000, max_seq_len=512)
B, PROMPT, NEW = 8, 64, 64
params = init_params(jax.random.PRNGKey(0), cfg)
prompt = jax.random.randint(jax.random.PRNGKey(1), (B, PROMPT), 0, cfg.vocab_size)
# The whole generation (prefill + KV-cache decode scan + token selection)
# is ONE jitted program -> one device dispatch, no per-token host trips.
out = greedy_generate(params, prompt, cfg, max_new_tokens=NEW)
_ = int(out[0, -1])  # compile + first run off the clock
t0 = time.perf_counter()
out = greedy_generate(params, prompt, cfg, max_new_tokens=NEW)
_ = int(out[0, -1])  # sync
dt = time.perf_counter() - t0
print(f"platform={jax.devices()[0].platform}")
print(f"decode_tokens_per_s={B * NEW / dt:.0f}")
"""

LLAMA_INFER = """
import time
import jax, jax.numpy as jnp
from bee_code_interpreter_fs_tpu.models.llama import LlamaConfig, init_params, forward

cfg = LlamaConfig.tiny(n_layers=4, dim=512, n_heads=8, n_kv_heads=8,
                       hidden_dim=1376, vocab_size=32000, max_seq_len=256)
params = init_params(jax.random.PRNGKey(0), cfg)
tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 256), 0, cfg.vocab_size)
fwd = jax.jit(lambda p, t: forward(p, t, cfg))
fwd(params, tokens).block_until_ready()  # compile
N = 20
t0 = time.perf_counter()
for _ in range(N):
    out = fwd(params, tokens)
out.block_until_ready()
dt = time.perf_counter() - t0
toks = N * tokens.size
print(f"platform={jax.devices()[0].platform}")
print(f"tokens_per_s={toks/dt:.0f}")
"""


def _extract(pattern: str, text: str) -> str:
    match = re.search(pattern, text)
    return match.group(1) if match else "?"


async def run_config(
    name: str,
    source: str,
    *,
    executor: CodeExecutor,
    timeout: float = 600.0,
    concurrency: int = 1,
) -> dict:
    print(f"# running {name} ...", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    results = await asyncio.gather(
        *(
            executor.execute(source, timeout=timeout)
            for _ in range(concurrency)
        )
    )
    wall = time.perf_counter() - t0
    bad = [r for r in results if r.exit_code != 0]
    if bad:
        result = {"config": name, "error": bad[0].stderr[-500:]}
    else:
        result = {
            "config": name,
            "wall_s": round(wall, 3),
            "concurrency": concurrency,
            "stdout": results[0].stdout.strip().splitlines(),
        }
    print(json.dumps(result), flush=True)
    return result


async def main() -> None:
    quick = "--quick" in sys.argv
    out: list[dict] = []
    with tempfile.TemporaryDirectory(prefix="benchcfg-") as tmp_str:
        tmp = Path(tmp_str)
        config = Config(
            file_storage_path=str(tmp / "storage"),
            local_sandbox_root=str(tmp / "sb"),
            executor_pod_queue_target_length=1,
            default_execution_timeout=600.0,
            max_execution_timeout=1200.0,
            jax_compilation_cache_dir=str(tmp / "jax-cache"),
        )
        backend = LocalSandboxBackend(config, warm_import_jax=True, numpy_dispatch=True)
        executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
        try:
            await executor.fill_pool()

            # -- config 1: benchmark-numpy through Execute --------------------
            src = (REPO_ROOT / "examples" / "benchmark-numpy.py").read_text()
            r = await run_config("1:benchmark-numpy", src, executor=executor)
            if "stdout" in r:
                r["gflops"] = float(_extract(r"GFLOPS=([0-9.]+)", "\n".join(r["stdout"])))
            out.append(r)

            # -- config 2: shim overhead on non-array code --------------------
            fib = (REPO_ROOT / "examples" / "benchmark-fib.py").read_text()
            imports = (REPO_ROOT / "examples" / "using_imports.py").read_text()
            r_on = await run_config("2:fib(dispatch-on)", fib, executor=executor)
            out.append(r_on)
            r_imp = await run_config("2:using_imports(dispatch-on)", imports, executor=executor)
            out.append(r_imp)

            # -- config 3: MNIST-shaped train, 1 chip -------------------------
            out.append(await run_config("3:mnist-train", MNIST_TRAIN, executor=executor))

            # -- config 4: ICI collectives (all local chips) ------------------
            psum = (REPO_ROOT / "examples" / "pmap_allreduce.py").read_text()
            out.append(await run_config("4:psum-allreduce", psum, executor=executor))

            # -- config 5a: Llama-class inference throughput, 1 chip ----------
            out.append(
                await run_config("5a:llama-infer-tpu-x1", LLAMA_INFER, executor=executor)
            )

            # -- config 5c: KV-cache incremental decode throughput ------------
            out.append(
                await run_config("5c:llama-decode-tpu-x1", LLAMA_DECODE, executor=executor)
            )

            # -- config 5d: int8 vs bf16 fused decode (weight-HBM bound) ------
            # -- config 5e: TRUE Llama-2-7B shape, int8, one chip -------------
            # (the north star's real 32-layer/4096-dim geometry; random
            # weights, identical code path — retires the scale-model caveat)
            # 5d/5e build GB-scale trees; 5f trains its draft/target pair
            # in-sandbox (~300 steps) then times four generations; 5g runs
            # two full engine replays plus a per-prompt-length sequential
            # compile pass — all too slow for a --quick pass, for
            # different reasons.
            if not quick:
                quant = (REPO_ROOT / "examples" / "benchmark-quant.py").read_text()
                out.append(
                    await run_config(
                        "5d:int8-decode-ratio", quant, executor=executor,
                        timeout=1200.0,
                    )
                )
                b7 = (REPO_ROOT / "examples" / "benchmark-7b.py").read_text()
                out.append(
                    await run_config(
                        "5e:llama2-7b-int8", b7, executor=executor, timeout=1200.0
                    )
                )

                # -- config 5f: speculative decoding (greedy + sampled) ------
                spec = (
                    REPO_ROOT / "examples" / "benchmark-speculative.py"
                ).read_text()
                out.append(
                    await run_config(
                        "5f:speculative", spec, executor=executor, timeout=1200.0
                    )
                )

                # -- config 5g: continuous-batching engine throughput --------
                serv = (
                    REPO_ROOT / "examples" / "benchmark-serving.py"
                ).read_text()
                out.append(
                    await run_config(
                        "5g:serving-engine", serv, executor=executor,
                        timeout=1200.0,
                    )
                )

                # -- config 5h: the capstone — 7B-int8 continuous batching ---
                # (one resident true-scale model, 16 concurrent requests;
                # VERDICT r4 #5's honest single-chip config-5)
                serv7b = (
                    REPO_ROOT / "examples" / "benchmark-serving-7b.py"
                ).read_text()
                out.append(
                    await run_config(
                        "5h:serving-7b-int8", serv7b, executor=executor,
                        timeout=1800.0,
                    )
                )
        finally:
            await executor.close()

        # -- config 5b: 16 concurrent Llama requests --------------------------
        # One tunneled chip cannot host 16 TPU-initialized sandboxes (on a
        # real v5e pool each sandbox owns its chips); measure the
        # orchestration path's concurrency on CPU-platform sandboxes instead.
        import os

        saved = os.environ.get("JAX_PLATFORMS")
        saved_pool = os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            config_cpu = Config(
                file_storage_path=str(tmp / "storage2"),
                local_sandbox_root=str(tmp / "sb2"),
                executor_pod_queue_target_length=4,
                default_execution_timeout=600.0,
                max_execution_timeout=1200.0,
                jax_compilation_cache_dir=str(tmp / "jax-cache-cpu"),
            )
            backend_cpu = LocalSandboxBackend(
                config_cpu, warm_import_jax=True, numpy_dispatch=True
            )
            executor_cpu = CodeExecutor(
                backend_cpu, Storage(config_cpu.file_storage_path), config_cpu
            )
            try:
                await executor_cpu.fill_pool()
                conc = 2 if quick else 16
                out.append(
                    await run_config(
                        "5b:llama-infer-cpu-x%d" % conc,
                        LLAMA_INFER,
                        executor=executor_cpu,
                        concurrency=conc,
                    )
                )
            finally:
                await executor_cpu.close()
        finally:
            if saved is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = saved
            if saved_pool is not None:
                os.environ["PALLAS_AXON_POOL_IPS"] = saved_pool

        # dispatch-off fib baseline needs its own backend (stock numpy path)
        backend_off = LocalSandboxBackend(
            config, warm_import_jax=False, numpy_dispatch=False
        )
        executor_off = CodeExecutor(
            backend_off, Storage(config.file_storage_path), config
        )
        try:
            await executor_off.fill_pool()
            fib = (REPO_ROOT / "examples" / "benchmark-fib.py").read_text()
            out.append(
                await run_config("2:fib(dispatch-off)", fib, executor=executor_off)
            )
        finally:
            await executor_off.close()

if __name__ == "__main__":
    asyncio.run(main())
