# Control-plane image: HTTP + gRPC APIs, orchestrator, Kubernetes backend.
#
# Reference parity: Dockerfile (poetry venv builder + slim runtime with
# kubectl, /storage prepared, `python -m code_interpreter` entrypoint).
# Simplifications: no poetry (plain pip install of the package), kubectl
# fetched from the official dl endpoint instead of an OS package.
#
# Build from the repo root:  docker build -t tpu-code-interpreter .

FROM python:3.12-slim-bookworm

ARG KUBECTL_VERSION=v1.31.0
ARG TARGETARCH=amd64
ADD https://dl.k8s.io/release/${KUBECTL_VERSION}/bin/linux/${TARGETARCH}/kubectl /usr/local/bin/kubectl
RUN chmod 0755 /usr/local/bin/kubectl

WORKDIR /app
COPY pyproject.toml README.md ./
COPY bee_code_interpreter_fs_tpu ./bee_code_interpreter_fs_tpu
COPY proto ./proto
RUN pip install --no-cache-dir .

# Shared file storage; chmod 777 so arbitrary-UID clusters can write
# (reference Dockerfile:21).
RUN mkdir -p /storage && chmod 777 /storage
ENV APP_FILE_STORAGE_PATH=/storage \
    APP_EXECUTOR_BACKEND=kubernetes

EXPOSE 8000 50051
ENTRYPOINT ["python", "-m", "bee_code_interpreter_fs_tpu"]
