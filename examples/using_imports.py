"""Preinstalled scientific stack probe (parity: reference
examples/using_imports.py — numpy/pandas/scipy t-test). Verifies the dispatch
shim coexists with pandas and scipy.
"""

import numpy as np
import pandas as pd
from scipy import stats

rng_a = np.random.normal(loc=5.0, scale=2.0, size=500)
rng_b = np.random.normal(loc=5.5, scale=2.0, size=500)

frame = pd.DataFrame({"a": np.asarray(rng_a), "b": np.asarray(rng_b)})
t_stat, p_value = stats.ttest_ind(frame["a"], frame["b"])
print(f"mean_a={frame['a'].mean():.3f} mean_b={frame['b'].mean():.3f}")
print(f"t={float(t_stat):.3f} p={float(p_value):.4f}")
print("ok")
