"""Workspace filesystem probe (parity: reference examples/files.py family —
write, list, read back)."""

import os

os.makedirs("out/nested", exist_ok=True)
with open("out/nested/report.txt", "w") as f:
    f.write("generated artifact\n")
with open("top.txt", "w") as f:
    f.write("top-level artifact\n")

for root, _dirs, files in os.walk("."):
    for name in sorted(files):
        print(os.path.join(root, name))
print(open("out/nested/report.txt").read().strip())
