"""Compute-bound benchmark: chained bf16 matmuls on the MXU.

BASELINE.json describes a matmul config and SURVEY.md §6 orders both shapes
measured; the sum-of-squares headline is HBM-bandwidth-bound, so this is the
number that shows whether Execute-submitted user code can reach the systolic
array's peak. Pure JAX user code (no numpy shim needed): a lax.fori_loop
chain of DIM×DIM @ DIM×DIM bf16 matmuls — each iteration consumes the
previous product, so XLA cannot collapse the chain — with one host sync at
the end. Reports achieved TFLOPS and model-flops-utilization against the
v5e bf16 peak (197 TFLOPS/chip).

On non-TPU backends (tests, CI) the shape shrinks so the script stays fast.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp

ON_TPU = jax.devices()[0].platform == "tpu"
DIM = 8192 if ON_TPU else 256
# Long enough that the rig's ~65 ms host<->device sync amortizes into noise:
# at 32 iters the sync was ~25% of the measurement and MFU read 65%; at 256
# the same chip reads 85% (measured sweep 32/128/256 -> 65/81.5/85.0%).
ITERS = 256 if ON_TPU else 2
V5E_BF16_PEAK_TFLOPS = 197.0


@partial(jax.jit, static_argnums=(1,))
def matmul_chain(a, iters):
    def body(_, b):
        # Rescale each product so bf16 stays in range across the chain:
        # per-iteration std grows by ~sqrt(DIM)*scale, so scale must sit at
        # or below 1/sqrt(DIM) ≈ 0.011 — 0.0100 decays gently (~1e-6 after
        # 256 iters, nowhere near bf16's underflow), where the old 0.0156
        # grew ~1.4x/iter and overflowed to inf/NaN past ~250 iterations.
        return (a @ b) * jnp.bfloat16(0.0100)

    b = jax.lax.fori_loop(0, iters, body, a)
    return b[0, 0].astype(jnp.float32)


key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (DIM, DIM), dtype=jnp.bfloat16)
probe = float(matmul_chain(a, ITERS))  # compile + first run off the clock
assert probe == probe, "matmul chain produced NaN — rescale is wrong"

best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    float(matmul_chain(a, ITERS))
    best = min(best, time.perf_counter() - t0)

tflops = ITERS * 2 * DIM**3 / best / 1e12
print(f"backend: {jax.devices()[0].platform} dim={DIM} iters={ITERS}")
print(f"elapsed_s={best:.4f}")
print(f"TFLOPS={tflops:.2f}")
if ON_TPU:
    print(f"MFU_vs_v5e_peak_pct={tflops / V5E_BF16_PEAK_TFLOPS * 100:.1f}")
