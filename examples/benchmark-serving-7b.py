"""The config-5 capstone at TRUE scale: Llama-2-7B (int8 weights, int8 KV)
resident on ONE v5e chip, serving 16 concurrent requests through the
continuous-batching engine — the honest single-chip version of BASELINE
config 5's "16 concurrent requests" (one resident model, 16 requests,
instead of 16 CPU sandboxes; VERDICT r4 #5).

Reports, from the chip:
  SERVING7B_TOKS           aggregate generated tok/s (submit -> drain)
  SERVING7B_PER_TOKEN_MS   median per-token streaming latency a client
                           sees (inter-chunk gap / chunk size via on_token)
  SERVING7B_UTILIZATION    mean active-slot fraction across scheduler syncs
  SERVING7B_SLOTS / _REQS  engine geometry for the BASELINE row

Memory budget (v5e 16 GB HBM): ~6.8 GB int8 weights + ~1.1 GB int8 KV
(8 slots x 512 ctx) + activations — the bf16 weight tree (13.5 GB) never
exists (models/quant.py random_quantized_params) and the bf16 KV cache
(2.1 GB) is halved by kv_quant. On CPU rigs a tiny config keeps the
script test-fast and verifies the engine's output token-exactly against
the whole-generation greedy decode on the SAME quantized tree.
"""

import os
import time
import statistics

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_fs_tpu.models import LlamaConfig
from bee_code_interpreter_fs_tpu.models.llama import greedy_generate
from bee_code_interpreter_fs_tpu.models.quant import (
    quantized_nbytes,
    random_quantized_params,
)
from bee_code_interpreter_fs_tpu.models.serving import ServingEngine

ON_TPU = jax.devices()[0].platform == "tpu"
if ON_TPU:
    cfg = LlamaConfig.llama2_7b()
    N_REQ, MAX_NEW, N_SLOTS, STEPS, MAX_LEN = 16, 64, 8, 16, 512
    PROMPT_RANGE = (48, 128)
else:  # correctness shapes for dev machines / CI
    cfg = LlamaConfig.tiny(dtype="float32", vocab_size=251)
    N_REQ, MAX_NEW, N_SLOTS, STEPS, MAX_LEN = 6, 12, 3, 4, 64
    PROMPT_RANGE = (4, 24)

t0 = time.perf_counter()
params = random_quantized_params(jax.random.PRNGKey(0), cfg, "int8")
jax.block_until_ready(params)
print(
    f"backend: {jax.devices()[0].platform} "
    f"model={'llama2_7b' if ON_TPU else 'tiny'} "
    f"params={quantized_nbytes(params) / 1e9:.2f}GB int8 "
    f"(built in {time.perf_counter() - t0:.1f}s)"
)

rng = np.random.RandomState(7)
traffic = [
    rng.randint(1, cfg.vocab_size - 1,
                size=rng.randint(*PROMPT_RANGE)).tolist()
    for _ in range(N_REQ)
]

eng = ServingEngine(
    params, cfg, n_slots=N_SLOTS, max_len=MAX_LEN, steps_per_sync=STEPS,
    kv_quant=True,
)

# Streaming sinks record (arrival time, chunk length) per request — the
# client-visible per-token latency is the inter-chunk gap spread over the
# chunk's tokens.
arrivals: dict[int, list] = {}

t0 = time.perf_counter()
rids = []
for p in traffic:
    chunks: list = []
    rid = eng.submit(
        p, MAX_NEW,
        on_token=lambda toks, c=chunks: c.append(
            (time.perf_counter(), len(toks))
        ),
    )
    arrivals[rid] = chunks
    rids.append(rid)
# Drive the scheduler step-by-step (instead of one run() call) to sample
# slot occupancy at every sync; the final run() on the drained engine
# just collects the results.
occupancy = []
while eng.stats()["queued"] or eng.stats()["occupied_slots"]:
    eng.step()
    occupancy.append(eng.stats()["active_slots"])
res = eng.run()
elapsed = time.perf_counter() - t0

total_tokens = sum(len(res[r]) for r in rids)
per_token_ms = []
for rid in rids:
    chunks = arrivals[rid]
    for (t_prev, _), (t_cur, n_cur) in zip(chunks, chunks[1:]):
        per_token_ms.extend([(t_cur - t_prev) * 1e3 / n_cur] * n_cur)

print(f"SERVING7B_SLOTS={N_SLOTS}")
print(f"SERVING7B_REQS={N_REQ}")
print(f"SERVING7B_TOKS={total_tokens / elapsed:.1f}  "
      f"(total={total_tokens}, wall={elapsed:.1f}s)")
if per_token_ms:
    print(f"SERVING7B_PER_TOKEN_MS={statistics.median(per_token_ms):.2f}")
active_sum = sum(occupancy)
print(f"SERVING7B_UTILIZATION={active_sum / (len(occupancy) * N_SLOTS):.3f}  "
      f"(syncs={len(occupancy)})")

if not ON_TPU:
    # Token-exactness: the engine's output on the quantized tree must match
    # the whole-generation fused greedy decode on the same tree.
    for p, rid in zip(traffic, rids):
        ref = np.asarray(
            greedy_generate(params, jnp.asarray([p], jnp.int32), cfg,
                            max_new_tokens=MAX_NEW)
        )[0, len(p):]
        np.testing.assert_array_equal(res[rid], ref)
    print("token-exact vs greedy_generate: OK")
