"""Headline array benchmark (parity: reference examples/benchmark-numpy.py —
sum of squares over 1e8 random doubles, self-reported wall clock).

Submitted through Execute, the sandbox's numpy dispatch shim routes the array
work onto the TPU. Two numbers are reported:

- GFLOPS (the BASELINE.json headline): steady-state throughput over ITERS
  data-DEPENDENT passes with one host sync at the end — each pass consumes
  the previous pass's array, so XLA cannot CSE the chain into one kernel,
  and the per-sync host round-trip (tens of ms through a tunneled test
  device; microseconds on directly-attached hardware) is amortized the way
  any pipelined workload amortizes it.
- GFLOPS_single_shot: one pass, one sync — the reference script's exact
  shape. On a directly-attached chip the two converge; a large gap between
  them measures the host↔device link latency, not the chip.
"""

import time

import numpy as np

N = 100_000_000

t0 = time.perf_counter()
a = np.random.rand(N)
# float() forces device sync, so the timings below exclude materialization.
_ = float(a[0])
t1 = time.perf_counter()

# Host numpy has no dispatch latency to amortize (steady == single shot);
# keep the CPU-baseline run short.
ITERS = 32 if type(a).__name__ == "TpuArray" else 4

# Reference-parity single shot: one full pass, one host sync.
s = float((a * a).sum())
t2 = time.perf_counter()

# Steady state: ITERS chained passes, one host sync. b feeds back into the
# next pass so every pass really runs (no CSE); acc folds every result into
# the final scalar so nothing is dead code.
acc = 0.0
b = a
for _ in range(ITERS):
    acc = acc + (b * b).sum()
    b = b + 1e-9
acc = float(acc)
t3 = time.perf_counter()

flops = 2 * N  # one multiply + one add per element per pass
print(f"backend: {type(a).__name__}")
print(f"sum(x*x) over {N:_} doubles = {s:.6f}")
print(
    f"alloc_s={t1 - t0:.4f} single_shot_s={t2 - t1:.4f} "
    f"steady_s={t3 - t2:.4f} (x{ITERS})"
)
print(f"GFLOPS_single_shot={flops / (t2 - t1) / 1e9:.3f}")
print(f"GFLOPS={flops * ITERS / (t3 - t2) / 1e9:.3f}")
