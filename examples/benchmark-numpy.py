"""Headline array benchmark (parity: reference examples/benchmark-numpy.py —
sum of squares over 1e8 random doubles, self-reported wall clock).

Submitted through Execute, the sandbox's numpy dispatch shim routes the array
work onto the TPU; the printed GFLOPS is the BASELINE.json headline metric.
"""

import time

import numpy as np

N = 100_000_000

t0 = time.perf_counter()
a = np.random.rand(N)
# float() forces device sync, so the timings below include materialization.
_ = float(a[0])
t1 = time.perf_counter()
s = float((a * a).sum())
t2 = time.perf_counter()

flops = 2 * N  # one multiply + one add per element
print(f"backend: {type(a).__name__}")
print(f"sum(x*x) over {N:_} doubles = {s:.6f}")
print(f"alloc_s={t1 - t0:.4f} compute_s={t2 - t1:.4f} total_s={t2 - t0:.4f}")
print(f"GFLOPS={flops / (t2 - t1) / 1e9:.3f}")
