"""ICI collectives smoke test (BASELINE.json config 4): psum across all chips
of the slice inside one sandbox. On a v5e-4 sandbox this exercises the ICI
mesh; on a single chip it degenerates gracefully."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
# Examples are standalone sandbox payloads — the control-plane package (and
# its parallel.mesh.shard_map compat wrapper) is not importable in the
# sandbox, so the jax-version fallback is inlined here.
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:
    from jax.experimental.shard_map import shard_map

devices = jax.devices()
n = len(devices)
mesh = Mesh(np.array(devices), ("chips",))


@jax.jit
def allreduce(x):
    def inner(block):
        return jax.lax.psum(block, "chips")

    return shard_map(inner, mesh=mesh, in_specs=P("chips"), out_specs=P())(x)


x = jnp.arange(n * 8, dtype=jnp.float32)
total = allreduce(x)
expected = x.reshape(n, -1).sum(axis=0)
print(f"chips={n} psum_ok={bool(jnp.allclose(total, expected))}")
