"""Continuous-batching serving benchmark: aggregate decode throughput of
the slot-scheduled engine vs one-request-at-a-time generation.

The engine (models/serving.py) is the framework's answer to concurrent
inference traffic (BASELINE config 5's 16-way leg serves requests in
separate sandboxes; this serves them in ONE resident model): requests
join/leave the running batch at token boundaries, prompts admit through
bucketed prefill, and decode runs in fused multi-step bursts. The same
traffic is then replayed sequentially (batch-1 greedy_generate per
request) — the measured ratio is the batching win at identical outputs,
which the script verifies token-exactly first.

The paged engine (models/paged.py) runs the same traffic on a block pool
sized well under dense residency — same tokens, less KV memory.

On TPU the model is Llama-shaped at ~0.3B so the bench fits beside other
suite legs; on CPU backends a tiny config keeps it test-fast.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_fs_tpu.models import LlamaConfig, init_params
from bee_code_interpreter_fs_tpu.models.llama import greedy_generate
from bee_code_interpreter_fs_tpu.models.paged import PagedServingEngine
from bee_code_interpreter_fs_tpu.models.serving import ServingEngine

ON_TPU = jax.devices()[0].platform == "tpu"
if ON_TPU:
    cfg = LlamaConfig(
        vocab_size=32000, dim=1024, n_layers=8, n_heads=8, n_kv_heads=8,
        hidden_dim=2816, max_seq_len=1024,
    )
    N_REQ, MAX_NEW, N_SLOTS, STEPS = 16, 96, 8, 16
    # Dense residency would be n_slots * max_len/16 = 512 blocks; the
    # traffic's worst-case reservation is (64+96)/16 = 10 blocks/request,
    # so 96 holds 8 concurrent requests with headroom at ~5x less KV HBM.
    N_BLOCKS = 96
else:
    cfg = LlamaConfig.tiny(dtype="float32", vocab_size=251)
    N_REQ, MAX_NEW, N_SLOTS, STEPS = 6, 12, 3, 4
    N_BLOCKS = 12  # half of the 24-block dense-equivalent pool

params = init_params(jax.random.PRNGKey(0), cfg)
rng = np.random.RandomState(1)
traffic = [
    rng.randint(1, cfg.vocab_size - 1, size=rng.randint(8, 64)).tolist()
    for _ in range(N_REQ)
]


def run_engine(make):
    eng = make()
    t0 = time.perf_counter()
    rids = [eng.submit(p, MAX_NEW) for p in traffic]
    res = eng.run()
    elapsed = time.perf_counter() - t0
    toks = sum(len(res[r]) for r in rids)
    return [res[r] for r in rids], toks / elapsed, elapsed


def run_sequential():
    outs = []
    t0 = time.perf_counter()
    for p in traffic:
        out = greedy_generate(
            params, jnp.asarray([p], jnp.int32), cfg, max_new_tokens=MAX_NEW
        )
        outs.append(np.asarray(out)[0, len(p):])
    return outs, time.perf_counter() - t0


mk_dense = lambda: ServingEngine(  # noqa: E731
    params, cfg, n_slots=N_SLOTS, max_len=cfg.max_seq_len,
    steps_per_sync=STEPS)
mk_paged = lambda: PagedServingEngine(  # noqa: E731
    params, cfg, n_slots=N_SLOTS, max_len=cfg.max_seq_len,
    steps_per_sync=STEPS, block_size=16, n_blocks=N_BLOCKS)

# Pass 1, untimed: every path compiles its programs (the sequential
# baseline compiles one generate per distinct prompt length — excluded
# from its clock exactly like the engines' bucket compiles are).
run_engine(mk_dense)
run_engine(mk_paged)
run_sequential()

# Pass 2, timed. Each marker flushes AS SOON as it is measured so a
# timeout mid-script still leaves every completed number in stdout (the
# driver bench parses whatever made it out).
print(f"backend: {jax.devices()[0].platform}", flush=True)
if not ON_TPU:
    # The tiny-CPU shape is a correctness smoke: host-side scheduling
    # dominates a model this small, so sequential fused generates win.
    # The batching case the engine exists for — decode bound by device
    # weight streaming, many concurrent requests — is the TPU config.
    print("note: tiny CPU config; ratios are not meaningful at this scale")
print(f"requests={N_REQ} max_new={MAX_NEW} slots={N_SLOTS}", flush=True)
dense_out, dense_tps, dense_s = run_engine(mk_dense)
print(f"ENGINE_TOKS_PER_S={dense_tps:.1f}", flush=True)
paged_out, paged_tps, paged_s = run_engine(mk_paged)
print(f"PAGED_TOKS_PER_S={paged_tps:.1f}", flush=True)
seq_outs, seq_s = run_sequential()
seq_toks = sum(len(o) for o in seq_outs)
print(f"SEQUENTIAL_TOKS_PER_S={seq_toks / seq_s:.1f}", flush=True)
print(f"ENGINE_SPEEDUP={dense_tps / (seq_toks / seq_s):.2f}", flush=True)

for got, ref in zip(dense_out, seq_outs):
    assert np.array_equal(got, ref), "engine output diverged from greedy"
for got, ref in zip(paged_out, seq_outs):
    assert np.array_equal(got, ref), "paged output diverged from greedy"
print("outputs: token-exact vs per-request greedy_generate")
