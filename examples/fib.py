"""Small pure-Python probe (parity: reference examples/fib.py) — the
minimal non-array workload; the dispatch shim must stay entirely off this
path."""


def fib(n: int) -> int:
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


print(fib(30))
