"""Network egress probe (parity: reference examples/tcp.py). In a properly
sandboxed deployment this should FAIL (no egress); locally it reports what it
can reach."""

import socket

try:
    with socket.create_connection(("1.1.1.1", 53), timeout=2):
        print("egress: OPEN (tcp 1.1.1.1:53 reachable)")
except OSError as e:
    print(f"egress: BLOCKED ({e})")
