"""Weight-only quantized decode benchmark: fused greedy decode tok/s for
bf16 vs int8 vs group-wise packed int4, same model / prompt / batch.

Autoregressive decode at small batch is weight-HBM-bound: every step
streams every matmul weight from HBM for a sliver of MXU work, so halving
the bytes per weight (models/quant.py: int8 + per-output-channel f32
scales, dequantize fused into the matmul operand path) should translate
directly into step rate. This measures that claim on the actual chip —
whole generations fused into one jitted program via greedy_generate, so
per-step host dispatch never touches the clock.

The reference has no quantized serving at all; this is a TPU-native
addition (SURVEY.md lists no counterpart).
"""

import time

import jax
import jax.numpy as jnp

from bee_code_interpreter_fs_tpu.models import (
    LlamaConfig,
    greedy_generate,
    init_params,
    quantize4_params,
    quantize_params,
    quantized_nbytes,
)

ON_TPU = jax.devices()[0].platform == "tpu"
if ON_TPU:
    # ~0.94B params: the bf16 (1.9 GB), int8 (1.0 GB), and int4 (~0.55 GB)
    # trees coexist in HBM so all three legs run in one process against
    # identical weights — size cfg with the SUM in mind.
    cfg = LlamaConfig(
        vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=16,
        hidden_dim=5504, max_seq_len=512,
    )
    NEW_TOKENS, BATCH = 128, 1
else:
    cfg = LlamaConfig.tiny(dtype="float32")
    NEW_TOKENS, BATCH = 8, 1

key = jax.random.PRNGKey(0)
params = init_params(key, cfg)
qparams = quantize_params(params)
prompt = jax.random.randint(
    jax.random.PRNGKey(1), (BATCH, 16), 0, cfg.vocab_size
)


def timed_best(fn, iters=3):
    jax.block_until_ready(fn())  # compile off the clock
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


t_bf16 = timed_best(
    lambda: greedy_generate(params, prompt, cfg, max_new_tokens=NEW_TOKENS)
)
t_int8 = timed_best(
    lambda: greedy_generate(qparams, prompt, cfg, max_new_tokens=NEW_TOKENS)
)

# bf16/int8 results go out BEFORE the int4 leg starts: a partial run
# (int4 OOM / timeout under bench.py's deadline) must still carry the
# measurements already made.
bf16_bytes = quantized_nbytes(params)
int8_bytes = quantized_nbytes(qparams)
print(f"backend: {jax.devices()[0].platform}", flush=True)
print(
    f"model: dim={cfg.dim} layers={cfg.n_layers} "
    f"weights bf16={bf16_bytes / 1e9:.2f}GB int8={int8_bytes / 1e9:.2f}GB"
)
print(f"batch={BATCH} new_tokens={NEW_TOKENS} (fused greedy decode)")
print(f"BF16_DECODE_TOKS={BATCH * NEW_TOKENS / t_bf16:.1f}")
print(f"INT8_DECODE_TOKS={BATCH * NEW_TOKENS / t_int8:.1f}")
print(f"INT8_DECODE_SPEEDUP={t_bf16 / t_int8:.2f}", flush=True)

q4params = quantize4_params(params)
t_int4 = timed_best(
    lambda: greedy_generate(q4params, prompt, cfg, max_new_tokens=NEW_TOKENS)
)
print(f"int4_weights_gb={quantized_nbytes(q4params) / 1e9:.2f}")
print(f"INT4_DECODE_TOKS={BATCH * NEW_TOKENS / t_int4:.1f}")
print(f"INT4_DECODE_SPEEDUP={t_bf16 / t_int4:.2f}")
