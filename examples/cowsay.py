"""Ad-hoc dependency auto-install probe (parity: reference examples/cowsay.py
— imports a package NOT in the preinstalled sandbox stack, exercising the
deps.py AST-scan + pip-install path that replaces the reference's upm)."""

import cowsay

cowsay.cow("moo from the TPU sandbox")
