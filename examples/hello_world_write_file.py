"""Write a workspace file; the changed-file scan ships it back as a content
hash the client can thread into the next Execute (parity: reference
examples/hello_world_write_file.py)."""

with open("hello.txt", "w") as f:
    f.write("Hello, World!\n")
print("wrote hello.txt")
