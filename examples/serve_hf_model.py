"""End-to-end Llama-COMPATIBILITY demo: a HuggingFace transformers model,
converted and served by this framework's continuous-batching engine, with
the output checked token-for-token against transformers' own generate().

No network involved: the script builds a small random-weight
`LlamaForCausalLM` in memory (the same model class real checkpoints load
into — swap in `from_pretrained(...)` and a bigger `LlamaConfig` to serve
a real one; `models/quant.py` int8/int4 fits 7B/13B on one v5e chip).

The path exercised is the production one end to end:
  transformers state_dict
    -> models/hf_convert.from_hf_state_dict   (naming + RoPE unpermute)
    -> models/serving.ServingEngine           (continuous batching,
       bucketed prefill, fused decode bursts, streaming callback)
and the final check is EXACT agreement with
`transformers.generate(do_sample=False)` on every request.
"""

import numpy as np
import torch
import transformers

import jax.numpy as jnp

from bee_code_interpreter_fs_tpu.models import LlamaConfig
from bee_code_interpreter_fs_tpu.models.hf_convert import from_hf_state_dict
from bee_code_interpreter_fs_tpu.models.serving import ServingEngine

# -- a Llama-architecture model from the HF ecosystem (random weights) ----
hf_cfg = transformers.LlamaConfig(
    vocab_size=512, hidden_size=128, intermediate_size=256,
    num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
    max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10000.0,
    attention_bias=False, mlp_bias=False, tie_word_embeddings=False,
)
torch.manual_seed(7)
hf_model = transformers.LlamaForCausalLM(hf_cfg).float().eval()

cfg = LlamaConfig(
    vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=4,
    hidden_dim=256, max_seq_len=256, dtype="float32",
)
params = from_hf_state_dict(hf_model.state_dict(), cfg)

# -- serve a batch of prompts through the engine, streaming as we go ------
rng = np.random.default_rng(3)
prompts = [rng.integers(1, 511, size=int(n)).tolist() for n in (5, 17, 9, 2)]
MAX_NEW = 24

# eos matches the HF config's so both sides stop at the same place (the
# engine emits the eos token then stops; generate() does the same).
eng = ServingEngine(params, cfg, n_slots=2, max_len=128, steps_per_sync=6,
                    eos_id=hf_cfg.eos_token_id)
streamed: dict[int, list] = {}
rids = []
for p in prompts:
    rid = eng.submit(
        p, MAX_NEW,
        on_token=lambda toks, key=len(rids): streamed.setdefault(
            key, []
        ).extend(toks),
    )
    rids.append(rid)
results = eng.run()

# -- the ground truth: transformers' own greedy generate ------------------
ok = 0
for i, (rid, p) in enumerate(zip(rids, prompts)):
    with torch.no_grad():
        ref = hf_model.generate(
            torch.tensor([p]), max_new_tokens=MAX_NEW, do_sample=False,
            pad_token_id=0,
        )[0, len(p):].numpy()
    got = results[rid]
    assert np.array_equal(got, ref), (i, got, ref)
    assert streamed[i] == got.tolist(), "streamed chunks != final result"
    ok += 1

print(f"backend: {jnp.zeros(1).devices()}")
print(f"served {ok}/{len(prompts)} requests from a transformers "
      f"LlamaForCausalLM, token-exact vs transformers.generate, "
      f"streaming verified")
