"""List the sandbox workspace (parity: reference examples/ls.py)."""

import os

for entry in sorted(os.listdir(".")):
    kind = "dir " if os.path.isdir(entry) else "file"
    print(f"{kind} {entry}")
