"""Long-context fused attention benchmark: the Pallas flash kernel as an
ordinary Execute payload. Causal attention at t=16384 — a sequence length
whose dense score matrix (t² floats per head) would be gigabytes — runs in
one kernel with K/V tiles streaming through VMEM. Steady state over chained
iterations (each consumes the previous output as queries) with one final
sync, per the rig's benchmarking methodology."""

import os
import time
from functools import partial

import jax
import jax.numpy as jnp

from bee_code_interpreter_fs_tpu.ops.flash_attention import flash_attention

ON_TPU = jax.devices()[0].platform == "tpu"
B, T, H, D = (1, 16384, 4, 128) if ON_TPU else (1, 128, 2, 16)
# Tile-sweep knobs (powers of two; see flash_attention's clamp rule).
BLOCK_Q = int(os.environ.get("BENCH_BLOCK_Q", "512"))
BLOCK_K = int(os.environ.get("BENCH_BLOCK_K", "1024"))
T = int(os.environ.get("BENCH_SEQ_LEN", str(T)))
# Enough chained iterations that the rig's ~65 ms host<->device sync is
# amortized into noise (at 4 iters the sync dominated and underreported the
# kernel ~8x).
ITERS = 32 if ON_TPU else 2

key = jax.random.PRNGKey(0)
q, k, v = (
    jax.random.normal(kk, (B, T, H, D), jnp.bfloat16)
    for kk in jax.random.split(key, 3)
)


@jax.jit
def chain(q, k, v):
    def body(_, q):
        return flash_attention(
            q, k, v, block_q=BLOCK_Q, block_k=BLOCK_K, interpret=not ON_TPU
        ).astype(q.dtype)

    out = jax.lax.fori_loop(0, ITERS, body, q)
    return out[0, 0, 0, 0].astype(jnp.float32)


float(chain(q, k, v))  # compile + first run off the clock
best = float("inf")
for _ in range(2):
    t0 = time.perf_counter()
    float(chain(q, k, v))
    best = min(best, time.perf_counter() - t0)

# Causal attention flops: QK^T + PV, each 2*b*h*(t^2/2)*d.
flops = ITERS * 4 * B * H * (T * T / 2) * D
# Report the EFFECTIVE tile sizes (after the kernel's clamp-to-t +
# power-of-two rounding), not the requested ones — sweep data points must
# be labeled with the configuration that actually ran.
from bee_code_interpreter_fs_tpu.ops.flash_attention import effective_blocks

eff_q, eff_k = effective_blocks(T, BLOCK_Q, BLOCK_K)
print(
    f"backend: {jax.devices()[0].platform} t={T} iters={ITERS} "
    f"blocks={eff_q}x{eff_k}"
)
print(f"ATTN_TFLOPS={flops / best / 1e12:.2f}")
