"""Speculative decoding benchmark: a 1-layer draft model accelerates the
4-layer target's greedy decode with EXACTLY identical output.

Random weights would demo nothing (a random draft never agrees with a random
target, so every pass rejects), so both models first train briefly on a
learnable synthetic pattern (arithmetic token sequences): the draft learns
it too, proposals agree, and each target pass emits several tokens. The
script verifies token-exact equality with plain greedy_generate before
reporting throughput — the draft decides speed, never content.

Single-sequence (b=1) decoding is the latency case speculation exists for:
each greedy step is one tiny matmul chain that cannot saturate the chip, so
trading γ cheap draft steps for one (γ+1)-token target pass wins ~3x here.
"""

import time

import jax
import jax.numpy as jnp
import optax

from bee_code_interpreter_fs_tpu.models import (
    LlamaConfig,
    greedy_generate,
    init_params,
    make_train_step,
    speculative_generate,
)

ON_TPU = jax.devices()[0].platform == "tpu"
V = 256
if ON_TPU:
    cfg_t = LlamaConfig.tiny(
        vocab_size=V, dim=512, n_layers=4, n_heads=8, n_kv_heads=8,
        hidden_dim=1024, max_seq_len=512,
    )
    cfg_d = LlamaConfig.tiny(
        vocab_size=V, dim=256, n_layers=1, n_heads=4, n_kv_heads=4,
        hidden_dim=512, max_seq_len=512,
    )
    TRAIN_STEPS, NEW_TOKENS, GAMMA = 150, 256, 6
else:
    cfg_t = LlamaConfig.tiny(vocab_size=V, dtype="float32")
    cfg_d = LlamaConfig.tiny(vocab_size=V, dtype="float32", n_layers=1)
    TRAIN_STEPS, NEW_TOKENS, GAMMA = 30, 16, 3


def make_batch(key, b, t):
    k1, k2 = jax.random.split(key)
    start = jax.random.randint(k1, (b, 1), 0, V)
    stride = jax.random.randint(k2, (b, 1), 1, 7)
    return (start + stride * jnp.arange(t)[None, :]) % V


def train(cfg, steps, key):
    params = init_params(key, cfg)
    opt = optax.adamw(3e-3)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt))
    for i in range(steps):
        batch = {"tokens": make_batch(jax.random.fold_in(key, i), 32, 128)}
        params, opt_state, loss = step(params, opt_state, batch)
    return params, float(loss)


t0 = time.perf_counter()
target, loss_t = train(cfg_t, TRAIN_STEPS, jax.random.PRNGKey(0))
draft, loss_d = train(cfg_d, TRAIN_STEPS, jax.random.PRNGKey(1))
print(
    f"trained target(loss={loss_t:.3f}) draft(loss={loss_d:.3f}) "
    f"in {time.perf_counter() - t0:.1f}s"
)

prompt = make_batch(jax.random.PRNGKey(42), 1, 32)


def timed(fn):
    out = fn()
    jax.block_until_ready(out)  # compile off the clock
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


greedy_out, t_greedy = timed(
    lambda: greedy_generate(target, prompt, cfg_t, max_new_tokens=NEW_TOKENS)
)
spec_out, t_spec = timed(
    lambda: speculative_generate(
        draft, target, prompt, cfg_d, cfg_t,
        max_new_tokens=NEW_TOKENS, gamma=GAMMA,
    )
)

assert (spec_out == greedy_out).all(), "speculative output diverged from greedy"
print(f"backend: {jax.devices()[0].platform} gamma={GAMMA} new_tokens={NEW_TOKENS}")
print(f"exact_match=True")
print(f"GREEDY_TOKS={NEW_TOKENS / t_greedy:.1f}")
print(f"SPEC_TOKS={NEW_TOKENS / t_spec:.1f}")
print(f"SPEC_SPEEDUP={t_greedy / t_spec:.2f}")

# Sampled variant (accept/resample at temperature): same trained pair, so
# proposals still mostly agree; baseline is plain fused ancestral sampling.
from bee_code_interpreter_fs_tpu.models import (  # noqa: E402
    sample_generate,
    speculative_sample_generate,
)

TEMP = 0.8
key = jax.random.PRNGKey(5)
_, t_sample = timed(
    lambda: sample_generate(
        target, prompt, key, cfg_t, max_new_tokens=NEW_TOKENS,
        temperature=TEMP,
    )
)
_, t_spec_sample = timed(
    lambda: speculative_sample_generate(
        draft, target, prompt, key, cfg_d, cfg_t,
        max_new_tokens=NEW_TOKENS, gamma=GAMMA, temperature=TEMP,
    )
)
print(f"SAMPLE_TOKS={NEW_TOKENS / t_sample:.1f}")
print(f"SPEC_SAMPLE_TOKS={NEW_TOKENS / t_spec_sample:.1f}")
print(f"SPEC_SAMPLE_SPEEDUP={t_sample / t_spec_sample:.2f}")
