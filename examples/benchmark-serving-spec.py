"""Speculative decoding INSIDE the continuous-batching engine: per-slot
draft/verify (models/spec_serving.py) vs the plain engine on identical
traffic, at low and moderate slot occupancy.

Like benchmark-speculative.py, both models first train briefly on a
learnable synthetic pattern so the draft actually agrees with the target
(random weights would reject every proposal); outputs are verified
token-exact against the plain engine before any throughput is reported.

Low occupancy (few active slots) is where composing the two wins: decode
at small active-batch is weight-HBM-bound, so γ cheap draft steps + one
(γ+1)-token target chunk reads the target weights once where plain decode
reads them γ+1 times. At higher occupancy the plain burst is already
denser; the two rows let you see the crossover on your hardware.

Prints:
  SPEC_ENGINE_LOW_TOKS / PLAIN_ENGINE_LOW_TOKS   (2 requests)
  SPEC_ENGINE_LOW_SPEEDUP
  SPEC_ENGINE_MID_TOKS / PLAIN_ENGINE_MID_TOKS   (8 requests, 4 slots)
  SPEC_ENGINE_MID_SPEEDUP
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bee_code_interpreter_fs_tpu.models import (
    LlamaConfig,
    init_params,
    make_train_step,
)
from bee_code_interpreter_fs_tpu.models.serving import ServingEngine
from bee_code_interpreter_fs_tpu.models.spec_serving import (
    SpeculativeServingEngine,
)

ON_TPU = jax.devices()[0].platform == "tpu"
V = 256
if ON_TPU:
    cfg_t = LlamaConfig.tiny(
        vocab_size=V, dim=512, n_layers=4, n_heads=8, n_kv_heads=8,
        hidden_dim=1024, max_seq_len=512,
    )
    cfg_d = LlamaConfig.tiny(
        vocab_size=V, dim=256, n_layers=1, n_heads=4, n_kv_heads=4,
        hidden_dim=512, max_seq_len=512,
    )
    TRAIN_STEPS, NEW_TOKENS, GAMMA, MAX_LEN, STEPS = 150, 192, 6, 512, 4
else:
    cfg_t = LlamaConfig.tiny(vocab_size=V, dtype="float32")
    cfg_d = LlamaConfig.tiny(vocab_size=V, dtype="float32", n_layers=1)
    TRAIN_STEPS, NEW_TOKENS, GAMMA, MAX_LEN, STEPS = 30, 16, 3, 64, 2


def make_batch(key, b, t):
    k1, k2 = jax.random.split(key)
    start = jax.random.randint(k1, (b, 1), 0, V)
    stride = jax.random.randint(k2, (b, 1), 1, 7)
    return (start + stride * jnp.arange(t)[None, :]) % V


def train(cfg, steps, key):
    params = init_params(key, cfg)
    opt = optax.adamw(3e-3)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt))
    for i in range(steps):
        batch = {"tokens": make_batch(jax.random.fold_in(key, i), 32, 128)}
        params, opt_state, loss = step(params, opt_state, batch)
    return params, float(loss)


t0 = time.perf_counter()
target, loss_t = train(cfg_t, TRAIN_STEPS, jax.random.PRNGKey(0))
draft, loss_d = train(cfg_d, TRAIN_STEPS, jax.random.PRNGKey(1))
print(
    f"trained target(loss={loss_t:.3f}) draft(loss={loss_d:.3f}) "
    f"in {time.perf_counter() - t0:.1f}s"
)


def drive(make, traffic, label):
    """One warm-up replay (compiles) then one timed replay."""
    outs = None
    for timed_run in (False, True):
        eng = make()
        t0 = time.perf_counter()
        rids = [eng.submit(p, NEW_TOKENS) for p in traffic]
        res = eng.run()
        dt = time.perf_counter() - t0
        outs = [res[r] for r in rids]
    toks = sum(len(o) for o in outs)
    print(f"{label}={toks / dt:.1f}  (total={toks}, wall={dt:.2f}s)")
    return outs, toks / dt


def mk_plain(n_slots):
    return lambda: ServingEngine(
        target, cfg_t, n_slots=n_slots, max_len=MAX_LEN,
        steps_per_sync=STEPS * (GAMMA + 1))


def mk_spec(n_slots):
    # steps_per_sync scaled so both engines sync at comparable token
    # granularity (a spec pass emits up to GAMMA+1 tokens).
    return lambda: SpeculativeServingEngine(
        target, cfg_t, draft_params=draft, draft_cfg=cfg_d, gamma=GAMMA,
        n_slots=n_slots, max_len=MAX_LEN, steps_per_sync=STEPS)


rng = np.random.RandomState(3)
low = [make_batch(jax.random.PRNGKey(40 + i), 1, 24)[0].tolist()
       for i in range(2)]
mid = [make_batch(jax.random.PRNGKey(60 + i), 1, 24)[0].tolist()
       for i in range(8)]

plain_low, p_low = drive(mk_plain(2), low, "PLAIN_ENGINE_LOW_TOKS")
spec_low, s_low = drive(mk_spec(2), low, "SPEC_ENGINE_LOW_TOKS")
for a, b in zip(plain_low, spec_low):
    np.testing.assert_array_equal(a, b)
print(f"SPEC_ENGINE_LOW_SPEEDUP={s_low / p_low:.2f}")

plain_mid, p_mid = drive(mk_plain(4), mid, "PLAIN_ENGINE_MID_TOKS")
spec_mid, s_mid = drive(mk_spec(4), mid, "SPEC_ENGINE_MID_TOKS")
for a, b in zip(plain_mid, spec_mid):
    np.testing.assert_array_equal(a, b)
print(f"SPEC_ENGINE_MID_SPEEDUP={s_mid / p_mid:.2f}")
print("token-exact vs plain engine: OK")
