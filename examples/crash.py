"""Nonzero-exit probe (parity: reference examples/crash.py)."""

import sys

print("about to crash")
sys.exit(3)
