"""Read a file the previous Execute produced — run hello_world_write_file.py
first and pass its returned hash as files={"/workspace/hello.txt": <hash>}
(parity: reference examples/hello_world_read_file.py; session state =
the files map, SURVEY.md §3.4)."""

print(open("hello.txt").read().strip())
