"""TPU visibility probe: what devices does user code see in the sandbox?"""

import jax

devices = jax.devices()
print(f"backend={devices[0].platform if devices else 'none'} count={len(devices)}")
for d in devices:
    print(f"  {d.id}: {d.device_kind}")
