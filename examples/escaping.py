"""Quoting/escaping safety probe (parity: reference examples/escaping.py —
the reference ran code via xonsh, where quoting was a real hazard; we run
plain CPython, so this documents that gnarly strings survive unmangled)."""

tricky = "quotes: ' \" backtick: ` dollar: $HOME newline-escape: \\n brace: {x}"
print(tricky)
print(f"f-string ok: {1 + 1}")
