"""Llama-family autoregressive generation inside the sandbox (BASELINE
config 5 flavor): prefill + KV-cache decode + token selection fused into one
jitted program (models.llama.greedy_generate), running on whatever
accelerator the sandbox exposes. Submitted through Execute like any user
payload — demonstrates that serving-style inference code needs nothing
special from the framework."""

import time

import jax

from bee_code_interpreter_fs_tpu.models import (
    LlamaConfig,
    greedy_generate,
    init_params,
)

cfg = LlamaConfig.tiny(
    n_layers=4, dim=512, n_heads=8, n_kv_heads=8, hidden_dim=1376,
    vocab_size=32000, max_seq_len=512,
)
B, PROMPT, NEW = 4, 32, 32
params = init_params(jax.random.PRNGKey(0), cfg)
prompt = jax.random.randint(jax.random.PRNGKey(1), (B, PROMPT), 0, cfg.vocab_size)

out = greedy_generate(params, prompt, cfg, max_new_tokens=NEW)
_ = int(out[0, -1])  # compile + run off the clock
t0 = time.perf_counter()
out = greedy_generate(params, prompt, cfg, max_new_tokens=NEW)
_ = int(out[0, -1])
dt = time.perf_counter() - t0

print(f"platform={jax.devices()[0].platform}")
print(f"generated shape={tuple(out.shape)}")
print(f"tokens_per_s={B * NEW / dt:.0f}")
