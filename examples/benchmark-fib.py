"""Pure-Python control benchmark (parity: reference examples/benchmark-fib.py
— 1000 iterations of iterative fib(10000)). No arrays: measures interpreter
speed and proves the numpy dispatch shim costs nothing for non-array code.
"""

import time


def fib(n: int) -> int:
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


t0 = time.perf_counter()
for _ in range(1000):
    result = fib(10000)
t1 = time.perf_counter()

print(f"fib(10000) x1000 = {str(result)[:10]}... in {t1 - t0:.4f}s")
