"""True-7B-scale Llama serving on ONE TPU v5e chip via weight-only int8.

The BASELINE north star (config 5) names Llama-2-7B. At bf16 the 6.7B-param
tree is ~13.5 GB — it cannot coexist with a KV cache, activations, and a
second quantization copy inside a v5e's 16 GB HBM. Int8 weights
(models/quant.py) are ~6.8 GB including scales, so the REAL 7B shape
(LlamaConfig.llama2_7b = dim 4096 / 32 layers / hidden 11008 / vocab 32000)
serves on one chip, retiring the round-3 "scale model" caveat.

Weights are random: throughput is the measurement, and the code path
(models/llama.py forward/prefill/decode + the quantized-leaf `_w` accessor)
is byte-for-byte the one real checkpoints take. The quantized tree is built
DIRECTLY — jax.eval_shape gives every leaf's shape, then each quantized
weight materializes as {int8 q, f32 s} on device — so the bf16 tree never
exists and peak HBM stays at the int8 footprint.
"""

import os
import time

import jax
import jax.numpy as jnp

from bee_code_interpreter_fs_tpu.models import (
    LlamaConfig,
    forward,
    greedy_generate,
    init_params,
    quantized_nbytes,
)
from bee_code_interpreter_fs_tpu.models.quant import random_quantized_params

ON_TPU = jax.devices()[0].platform == "tpu"
# BENCH_MODEL picks the geometry; BENCH_PRECISION picks int8 (default) or
# group-wise packed int4. One-v5e-chip (16 GB HBM) footprints incl. the
# bf16 embed table (full precision): llama2_7b ~6.8 GB int8 / ~3.6 GB
# int4; llama3_8b ~8.6 / ~4.8; llama2_13b ~6.9 GB at int4 ONLY (13 GB at
# int8 leaves no activation headroom). mixtral_8x7b deliberately NOT
# offered: 46.7B params can't fit one chip at any supported precision.
PRESETS = ("llama2_7b", "llama3_8b", "llama2_13b")
MODEL = os.environ.get("BENCH_MODEL", "llama2_7b")
PRECISION = os.environ.get("BENCH_PRECISION", "int8")
if MODEL not in PRESETS:
    raise SystemExit(f"BENCH_MODEL must be one of {PRESETS}, got {MODEL!r}")
if PRECISION not in ("int8", "int4"):
    raise SystemExit(f"BENCH_PRECISION must be int8 or int4, got {PRECISION!r}")
if MODEL == "llama2_13b" and PRECISION != "int4":
    raise SystemExit("llama2_13b only fits one chip at BENCH_PRECISION=int4")
if ON_TPU:
    cfg = getattr(LlamaConfig, MODEL)()
    PREFILL_T, NEW_TOKENS, BATCH = 512, 64, 1
else:  # correctness-check shapes for dev machines / CI
    cfg = LlamaConfig.tiny(dtype="float32")
    PREFILL_T, NEW_TOKENS, BATCH = 32, 8, 1


# The quantized-tree builder lives in the framework (models/quant.py
# random_quantized_params) so every true-scale bench shares one recipe.
build_quantized_params = random_quantized_params


t0 = time.perf_counter()
params = build_quantized_params(jax.random.PRNGKey(0), cfg, PRECISION)
jax.block_until_ready(params)
nbytes = quantized_nbytes(params)
print(
    f"backend: {jax.devices()[0].platform} model={MODEL if ON_TPU else 'tiny'} "
    f"params={nbytes / 1e9:.2f}GB {PRECISION} "
    f"(built in {time.perf_counter() - t0:.1f}s)"
)

def timed_best(fn, iters=3):
    jax.block_until_ready(fn())  # compile + first run off the clock
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


# --- prefill throughput: one full forward over PREFILL_T tokens -----------
prefill_tokens = jax.random.randint(
    jax.random.PRNGKey(1), (BATCH, PREFILL_T), 0, cfg.vocab_size
)
fwd = jax.jit(lambda p, t: forward(p, t, cfg))
best = timed_best(lambda: fwd(params, prefill_tokens))
print(f"PREFILL_TOKS={BATCH * PREFILL_T / best:.1f}  (t={PREFILL_T})")

# --- fused greedy decode tok/s -------------------------------------------
prompt = prefill_tokens[:, :64]
best = timed_best(
    lambda: greedy_generate(params, prompt, cfg, max_new_tokens=NEW_TOKENS)
)
toks = BATCH * NEW_TOKENS / best
print(f"DECODE_TOKS={toks:.1f}  (batch={BATCH}, new={NEW_TOKENS}, fused)")
mem = jax.devices()[0].memory_stats() or {}
if "bytes_in_use" in mem:
    print(f"hbm_in_use_gb={mem['bytes_in_use'] / 1e9:.2f}")
