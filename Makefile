# Dev task runner (parity: the reference's poe tasks — run, health_check,
# test; pyproject.toml:45-57 — done as make targets since this project is
# setuptools-based).

.PHONY: all executor run health-check test test-sanitizers bench proto clean

all: executor

executor:
	$(MAKE) -C executor

run: executor
	APP_EXECUTOR_BACKEND=local python -m bee_code_interpreter_fs_tpu

health-check:
	python -m bee_code_interpreter_fs_tpu.health_check

test: executor
	python -m pytest tests/ -q

test-sanitizers:
	$(MAKE) -C executor asan tsan
	ASAN_OPTIONS=detect_leaks=1 TEST_EXECUTOR_BINARY=$(CURDIR)/executor/build/executor-server-asan \
		python -m pytest tests/unit/test_executor_server.py -q
	TSAN_OPTIONS=halt_on_error=1 TEST_EXECUTOR_BINARY=$(CURDIR)/executor/build/executor-server-tsan \
		python -m pytest tests/unit/test_executor_server.py -q

bench: executor
	python bench.py

proto:
	scripts/genproto.sh

clean:
	$(MAKE) -C executor clean
