# Dev task runner (parity: the reference's poe tasks — run, health_check,
# test; pyproject.toml:45-57 — done as make targets since this project is
# setuptools-based).

# verify uses bash-only ${PIPESTATUS[0]} (the ROADMAP tier-1 command verbatim).
SHELL := /bin/bash

.PHONY: all executor run health-check test test-sanitizers verify bench proto clean

all: executor

executor:
	$(MAKE) -C executor

run: executor
	APP_EXECUTOR_BACKEND=local python -m bee_code_interpreter_fs_tpu

health-check:
	python -m bee_code_interpreter_fs_tpu.health_check

test: executor
	python -m pytest tests/ -q

# The ROADMAP.md "Tier-1 verify" command, verbatim ($ doubled for make):
# the acceptance gate every PR must keep no worse than the seed. CI calls
# this so local `make verify` and the workflow can never drift apart.
verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

test-sanitizers:
	$(MAKE) -C executor asan tsan
	ASAN_OPTIONS=detect_leaks=1 TEST_EXECUTOR_BINARY=$(CURDIR)/executor/build/executor-server-asan \
		python -m pytest tests/unit/test_executor_server.py tests/unit/test_executor_limits.py tests/unit/test_executor_cgroup.py tests/unit/test_executor_perf.py -q
	TSAN_OPTIONS=halt_on_error=1 TEST_EXECUTOR_BINARY=$(CURDIR)/executor/build/executor-server-tsan \
		python -m pytest tests/unit/test_executor_server.py tests/unit/test_executor_limits.py tests/unit/test_executor_cgroup.py tests/unit/test_executor_perf.py -q

bench: executor
	python bench.py

proto:
	scripts/genproto.sh

clean:
	$(MAKE) -C executor clean
