// Small threaded HTTP/1.1 server for the in-sandbox executor.
//
// Design notes (TPU build): the executor serves one sandbox — a handful of
// concurrent file transfers plus one /execute at a time — so a clear,
// auditable thread-per-connection loop beats an async state machine. Bodies
// stream to/from disk (uploads can be model checkpoints), with both
// Content-Length and chunked transfer encodings supported.
#pragma once

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <functional>
#include <map>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

namespace minihttp {

inline std::string lower(std::string s) {
  for (auto& c : s) c = static_cast<char>(tolower(static_cast<unsigned char>(c)));
  return s;
}

struct Request {
  std::string method;
  std::string target;  // raw path (no query handling beyond split)
  std::string query;
  std::map<std::string, std::string> headers;  // lowercased names
  std::string header(const std::string& name, const std::string& dflt = "") const {
    auto it = headers.find(lower(name));
    return it == headers.end() ? dflt : it->second;
  }
};

// Reads from a connection, buffered; decodes request bodies.
class Conn {
 public:
  explicit Conn(int fd) : fd_(fd) {}
  ~Conn() {
    if (fd_ >= 0) ::close(fd_);
  }
  Conn(const Conn&) = delete;
  Conn& operator=(const Conn&) = delete;

  int fd() const { return fd_; }

  // Returns false on clean EOF before any byte of a new request.
  bool read_request(Request& req) {
    std::string line;
    if (!read_line(line, /*eof_ok=*/true)) return false;
    if (line.empty()) {
      if (!read_line(line, true)) return false;  // tolerate stray CRLF
    }
    size_t sp1 = line.find(' ');
    size_t sp2 = line.rfind(' ');
    if (sp1 == std::string::npos || sp2 == sp1)
      throw std::runtime_error("bad request line");
    req.method = line.substr(0, sp1);
    std::string target = line.substr(sp1 + 1, sp2 - sp1 - 1);
    size_t q = target.find('?');
    if (q != std::string::npos) {
      req.query = target.substr(q + 1);
      target = target.substr(0, q);
    }
    req.target = target;
    req.headers.clear();
    while (true) {
      if (!read_line(line, false)) throw std::runtime_error("eof in headers");
      if (line.empty()) break;
      size_t colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string name = lower(line.substr(0, colon));
      size_t vstart = line.find_first_not_of(" \t", colon + 1);
      req.headers[name] = vstart == std::string::npos ? "" : line.substr(vstart);
    }
    init_body(req);
    return true;
  }

  // Read next chunk of the current request body into `out` (appends).
  // Returns number of bytes read; 0 at end of body.
  size_t read_body_some(std::string& out, size_t max = 1 << 16) {
    if (chunked_) return read_chunked_some(out, max);
    if (remaining_ == 0) return 0;
    size_t want = std::min(max, remaining_);
    size_t got = read_some_into(out, want);
    remaining_ -= got;
    if (got == 0 && remaining_ > 0) throw std::runtime_error("eof in body");
    return got;
  }

  std::string read_body(size_t limit = 64ull << 20) {
    std::string body;
    std::string chunk;
    while (true) {
      chunk.clear();
      if (read_body_some(chunk) == 0) break;
      body += chunk;
      if (body.size() > limit) throw std::runtime_error("body too large");
    }
    return body;
  }

  // Stream body to an open fd; returns total bytes.
  size_t read_body_to_fd(int out_fd) {
    size_t total = 0;
    std::string chunk;
    while (true) {
      chunk.clear();
      if (read_body_some(chunk, 1 << 20) == 0) break;
      size_t off = 0;
      while (off < chunk.size()) {
        ssize_t n = ::write(out_fd, chunk.data() + off, chunk.size() - off);
        if (n < 0) {
          if (errno == EINTR) continue;
          throw std::runtime_error("write failed");
        }
        off += static_cast<size_t>(n);
      }
      total += chunk.size();
    }
    return total;
  }

  void drain_body() {
    std::string sink;
    while (read_body_some(sink, 1 << 16) != 0) sink.clear();
  }

  // ---- responses ----
  void send_response(int status, const std::string& content_type,
                     const std::string& body,
                     const std::vector<std::pair<std::string, std::string>>& extra = {}) {
    std::string head = "HTTP/1.1 " + std::to_string(status) + " " + reason(status) +
                       "\r\nContent-Type: " + content_type +
                       "\r\nContent-Length: " + std::to_string(body.size()) + "\r\n";
    for (const auto& [k, v] : extra) head += k + ": " + v + "\r\n";
    head += "\r\n";
    write_all(head);
    write_all(body);
  }

  // ---- chunked (streaming) responses ----
  // begin_chunked + N× send_chunk + end_chunked emit one valid HTTP/1.1
  // chunked response; used by /execute/stream to push stdout/stderr while
  // user code is still running.
  void begin_chunked(int status, const std::string& content_type) {
    std::string head = "HTTP/1.1 " + std::to_string(status) + " " +
                       reason(status) + "\r\nContent-Type: " + content_type +
                       "\r\nTransfer-Encoding: chunked\r\n\r\n";
    write_all(head);
  }

  void send_chunk(const std::string& data) {
    if (data.empty()) return;  // an empty chunk would terminate the body
    char size_hex[32];
    snprintf(size_hex, sizeof(size_hex), "%zx\r\n", data.size());
    write_all(size_hex);
    write_all(data);
    write_all("\r\n");
  }

  void end_chunked() { write_all("0\r\n\r\n"); }

  // Sends a file with sendfile(2); returns false if open/stat fails.
  bool send_file(const std::string& path) {
    int f = ::open(path.c_str(), O_RDONLY | O_NOFOLLOW);
    if (f < 0) return false;
    return send_file_fd(f);
  }

  // Same, from an already-open fd (always closes it).
  bool send_file_fd(int f) {
    struct stat st;
    if (fstat(f, &st) != 0 || !S_ISREG(st.st_mode)) {
      ::close(f);
      return false;
    }
    std::string head =
        "HTTP/1.1 200 OK\r\nContent-Type: application/octet-stream\r\nContent-Length: " +
        std::to_string(st.st_size) + "\r\n\r\n";
    write_all(head);
    off_t offset = 0;
    while (offset < st.st_size) {
      ssize_t n = ::sendfile(fd_, f, &offset, static_cast<size_t>(st.st_size - offset));
      if (n < 0) {
        if (errno == EINTR) continue;
        ::close(f);
        throw std::runtime_error("sendfile failed");
      }
      if (n == 0) break;
    }
    ::close(f);
    return true;
  }

 private:
  void init_body(const Request& req) {
    chunked_ = lower(req.header("transfer-encoding")) == "chunked";
    chunk_remaining_ = 0;
    chunked_done_ = false;
    remaining_ = 0;
    if (!chunked_) {
      std::string cl = req.header("content-length", "0");
      remaining_ = cl.empty() ? 0 : std::stoull(cl);
    }
  }

  size_t read_chunked_some(std::string& out, size_t max) {
    if (chunked_done_) return 0;
    if (chunk_remaining_ == 0) {
      std::string line;
      if (!read_line(line, false)) throw std::runtime_error("eof in chunk size");
      if (line.empty() && !read_line(line, false))
        throw std::runtime_error("eof in chunk size");
      chunk_remaining_ = std::stoull(line, nullptr, 16);
      if (chunk_remaining_ == 0) {
        // trailing headers until blank line
        while (read_line(line, false) && !line.empty()) {
        }
        chunked_done_ = true;
        return 0;
      }
    }
    size_t want = std::min(max, chunk_remaining_);
    size_t got = read_some_into(out, want);
    if (got == 0) throw std::runtime_error("eof in chunk");
    chunk_remaining_ -= got;
    if (chunk_remaining_ == 0) {
      std::string crlf;
      read_line(crlf, false);  // consume trailing CRLF
    }
    return got;
  }

  bool fill() {
    char tmp[1 << 16];
    ssize_t n;
    do {
      n = ::recv(fd_, tmp, sizeof(tmp), 0);
    } while (n < 0 && errno == EINTR);
    if (n <= 0) return false;
    buf_.append(tmp, static_cast<size_t>(n));
    return true;
  }

  bool read_line(std::string& line, bool eof_ok) {
    while (true) {
      size_t nl = buf_.find('\n', pos_);
      if (nl != std::string::npos) {
        line = buf_.substr(pos_, nl - pos_);
        if (!line.empty() && line.back() == '\r') line.pop_back();
        pos_ = nl + 1;
        compact();
        return true;
      }
      if (!fill()) {
        if (eof_ok && pos_ >= buf_.size()) return false;
        throw std::runtime_error("eof mid-line");
      }
    }
  }

  size_t read_some_into(std::string& out, size_t want) {
    if (pos_ >= buf_.size()) {
      buf_.clear();
      pos_ = 0;
      if (!fill()) return 0;
    }
    size_t avail = buf_.size() - pos_;
    size_t take = std::min(avail, want);
    out.append(buf_, pos_, take);
    pos_ += take;
    compact();
    return take;
  }

  void compact() {
    if (pos_ > (1 << 20)) {
      buf_.erase(0, pos_);
      pos_ = 0;
    }
  }

  void write_all(const std::string& data) {
    size_t off = 0;
    while (off < data.size()) {
      ssize_t n = ::send(fd_, data.data() + off, data.size() - off, MSG_NOSIGNAL);
      if (n < 0) {
        if (errno == EINTR) continue;
        throw std::runtime_error("send failed");
      }
      off += static_cast<size_t>(n);
    }
  }

  static const char* reason(int status) {
    switch (status) {
      case 200: return "OK";
      case 201: return "Created";
      case 304: return "Not Modified";
      case 400: return "Bad Request";
      case 403: return "Forbidden";
      case 404: return "Not Found";
      case 408: return "Request Timeout";
      case 500: return "Internal Server Error";
      case 503: return "Service Unavailable";
      default: return "Unknown";
    }
  }

  int fd_;
  std::string buf_;
  size_t pos_ = 0;
  bool chunked_ = false;
  bool chunked_done_ = false;
  size_t chunk_remaining_ = 0;
  size_t remaining_ = 0;
};

using Handler = std::function<void(const Request&, Conn&)>;

class Server {
 public:
  // addr "host:port"; port 0 picks an ephemeral port (reported by port()).
  explicit Server(const std::string& addr, Handler handler)
      : handler_(std::move(handler)) {
    signal(SIGPIPE, SIG_IGN);
    size_t colon = addr.rfind(':');
    std::string host = colon == std::string::npos ? "0.0.0.0" : addr.substr(0, colon);
    int port = colon == std::string::npos ? 8000 : std::stoi(addr.substr(colon + 1));
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) throw std::runtime_error("socket failed");
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host.c_str(), &sa.sin_addr) != 1)
      throw std::runtime_error("bad listen host: " + host);
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0)
      throw std::runtime_error("bind failed: " + addr);
    if (listen(listen_fd_, 64) != 0) throw std::runtime_error("listen failed");
    socklen_t len = sizeof(sa);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&sa), &len);
    port_ = ntohs(sa.sin_port);
  }

  int port() const { return port_; }

  [[noreturn]] void serve_forever() {
    while (true) {
      int cfd = ::accept(listen_fd_, nullptr, nullptr);
      if (cfd < 0) {
        if (errno == EINTR) continue;
        throw std::runtime_error("accept failed");
      }
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::thread([this, cfd] { handle_conn(cfd); }).detach();
    }
  }

 private:
  void handle_conn(int cfd) {
    Conn conn(cfd);
    try {
      Request req;
      while (conn.read_request(req)) {
        handler_(req, conn);
        // Consume any body bytes the handler didn't read (e.g. GET with a
        // body) so the next keep-alive request parses from a clean boundary.
        conn.drain_body();
        if (lower(req.header("connection")) == "close") break;
      }
    } catch (const std::exception&) {
      // connection-level error: drop the connection
    }
  }

  Handler handler_;
  int listen_fd_;
  int port_ = 0;
};

}  // namespace minihttp
