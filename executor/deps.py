"""Dependency auto-install scanner: prints pip package names a script needs
but the sandbox lacks, one per line.

TPU-native replacement for the reference's `upm guess` subprocess + sqlite
import→package DB (executor/server.rs:174-195, executor/Dockerfile:122-124):
an AST walk over the user script collects imported top-level modules, filters
the stdlib (sys.stdlib_module_names) and anything already importable, then
maps import names to pip names via a small alias table. A skip list
(requirements-skip.txt in the runtime-packages dir, reference parity:
executor/requirements-skip.txt) suppresses OS-packaged aliases.

Usage: python deps.py <script.py> [runtime_packages_dir]
"""

import ast
import importlib.util
import re
import sys
from pathlib import Path

# import name -> pip distribution name, for the common divergent cases
# (curated equivalent of upm's pypi_map.sqlite import->package DB the
# reference shipped, executor/Dockerfile:122-124; None = never install).
IMPORT_TO_PIP = {
    "cv2": "opencv-python-headless",
    "PIL": "pillow",
    "sklearn": "scikit-learn",
    "skimage": "scikit-image",
    "bs4": "beautifulsoup4",
    "yaml": "pyyaml",
    "Crypto": "pycryptodome",
    "nacl": "pynacl",
    "fitz": "pymupdf",
    "dateutil": "python-dateutil",
    "docx": "python-docx",
    "pptx": "python-pptx",
    "kubernetes": "kubernetes",
    "serial": "pyserial",
    "OpenSSL": "pyopenssl",
    "jwt": "pyjwt",
    "magic": "python-magic",
    "Levenshtein": "python-Levenshtein",
    "moviepy": "moviepy",
    "attr": "attrs",
    "cairo": "pycairo",
    "dotenv": "python-dotenv",
    "fake_useragent": "fake-useragent",
    "flask_cors": "flask-cors",
    "flask_sqlalchemy": "flask-sqlalchemy",
    "github": "PyGithub",
    "grpc": "grpcio",
    "igraph": "python-igraph",
    "jose": "python-jose",
    "mpl_toolkits": "matplotlib",
    "mysql": "mysql-connector-python",
    "osgeo": "gdal",
    "psycopg2": "psycopg2-binary",
    "requests_html": "requests-html",
    "rest_framework": "djangorestframework",
    "sentence_transformers": "sentence-transformers",
    "slugify": "python-slugify",
    "socks": "pysocks",
    "telegram": "python-telegram-bot",
    "typing_extensions": "typing-extensions",
    "websocket": "websocket-client",
    "zmq": "pyzmq",
    "gi": None,  # system-only
    "libtpu": None,
    "_curses": None,
}


def imported_top_modules(source: str) -> set[str]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return set()
    mods: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mods.add(alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0:
                mods.add(node.module.split(".")[0])
    return mods


def load_skip_list(runtime_packages: Path) -> set[str]:
    skip: set[str] = set()
    for name in ("requirements.txt", "requirements-skip.txt"):
        p = runtime_packages / name
        if not p.exists():
            continue
        for line in p.read_text().splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            # strip extras/version specifiers: "pandas[excel]>=2" -> "pandas"
            pkg = re.split(r"[\[<>=!~;]", line, 1)[0].strip().lower()
            if pkg:
                skip.add(pkg)
    return skip


def main() -> None:
    script = Path(sys.argv[1])
    runtime_packages = Path(sys.argv[2]) if len(sys.argv) > 2 else None
    mods = imported_top_modules(script.read_text())
    skip = load_skip_list(runtime_packages) if runtime_packages else set()
    missing: list[str] = []
    for mod in sorted(mods):
        if mod in sys.stdlib_module_names:
            continue
        if importlib.util.find_spec(mod) is not None:
            continue
        pip_name = IMPORT_TO_PIP.get(mod, mod)
        if pip_name is None:
            continue
        if pip_name.lower() in skip or mod.lower() in skip:
            continue
        missing.append(pip_name)
    print("\n".join(missing))


if __name__ == "__main__":
    main()
