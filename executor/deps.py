"""Dependency auto-install scanner: prints pip package names a script needs
but the sandbox lacks, one per line.

TPU-native replacement for the reference's `upm guess` subprocess + sqlite
import→package DB (executor/server.rs:174-195, executor/Dockerfile:122-124):
an AST walk over the user script collects imported top-level modules, filters
the stdlib (sys.stdlib_module_names) and anything already importable, then
maps import names to pip names via a data-file table (pypi_imports.tsv,
~400 divergent import→distribution mappings — the equivalent of upm's
pypi_map.sqlite) with the identity mapping as fallback. A skip list
(requirements-skip.txt in the runtime-packages dir, reference parity:
executor/requirements-skip.txt) suppresses OS-packaged aliases; entries may
carry extras/version pins ("pandas[excel]>=2"), which are stripped.

Usage: python deps.py <script.py> [runtime_packages_dir]
"""

import ast
import importlib.util
import re
import sys
from pathlib import Path

# Mappings that must hold even if the data file is missing/corrupt (the
# sandbox's most common divergent imports). The data file extends this table;
# these entries win on conflict. None = never install (system-only).
IMPORT_TO_PIP: dict[str, str | None] = {
    "cv2": "opencv-python-headless",
    "PIL": "pillow",
    "sklearn": "scikit-learn",
    "skimage": "scikit-image",
    "bs4": "beautifulsoup4",
    "yaml": "pyyaml",
    "Crypto": "pycryptodome",
    "fitz": "pymupdf",
    "dateutil": "python-dateutil",
    "docx": "python-docx",
    "pptx": "python-pptx",
    "gi": None,  # system-only
    "libtpu": None,
    "_curses": None,
}

DATA_FILE = Path(__file__).resolve().parent / "pypi_imports.tsv"


def load_import_map() -> dict[str, str | None]:
    """Data-file mappings, overlaid by the built-in table."""
    table: dict[str, str | None] = {}
    try:
        lines = DATA_FILE.read_text().splitlines()
    except OSError:
        lines = []
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split("\t")
        if len(parts) != 2:
            continue
        import_name, pip_name = parts[0].strip(), parts[1].strip()
        if not import_name or not pip_name:
            continue
        table[import_name] = None if pip_name == "-" else pip_name
    table.update(IMPORT_TO_PIP)
    return table


def imported_modules(source: str) -> set[str]:
    """Full dotted module paths the script imports. `from google.cloud
    import bigquery` yields both "google.cloud" and "google.cloud.bigquery"
    — namespace packages (google.*, azure.*) distribute per SUBpackage, so
    the top-level name alone cannot identify the distribution."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return set()
    mods: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mods.add(alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0:
                mods.add(node.module)
                for alias in node.names:
                    if alias.name != "*":
                        mods.add(f"{node.module}.{alias.name}")
    return mods


def imported_top_modules(source: str) -> set[str]:
    return {path.split(".")[0] for path in imported_modules(source)}


def _base_name(requirement: str) -> str:
    """Strip extras/version specifiers: 'pandas[excel]>=2' -> 'pandas'."""
    return re.split(r"[\[<>=!~;@\s]", requirement, 1)[0].strip().lower()


def load_skip_list(runtime_packages: Path) -> set[str]:
    skip: set[str] = set()
    for name in ("requirements.txt", "requirements-skip.txt"):
        p = runtime_packages / name
        if not p.exists():
            continue
        for line in p.read_text().splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            pkg = _base_name(line)
            if pkg:
                skip.add(pkg)
    return skip


def _find_spec_safe(name: str):
    """find_spec on a dotted path imports parent packages, which can raise
    arbitrarily for half-present namespaces — treat any failure as absent."""
    try:
        return importlib.util.find_spec(name)
    except Exception:  # noqa: BLE001
        return None


def missing_packages(
    source: str, runtime_packages: Path | None = None
) -> list[str]:
    mods = imported_modules(source)
    skip = load_skip_list(runtime_packages) if runtime_packages else set()
    import_map = load_import_map()
    missing: list[str] = []
    seen: set[str] = set()
    for mod_path in sorted(mods):
        top = mod_path.split(".")[0]
        if top in sys.stdlib_module_names:
            continue
        # Longest-prefix lookup: "google.cloud.bigquery" matches its own map
        # row even though the top-level "google" namespace is importable.
        parts = mod_path.split(".")
        key = None
        for i in range(len(parts), 0, -1):
            candidate = ".".join(parts[:i])
            if candidate in import_map:
                key = candidate
                break
        if key is None:
            key = top  # identity mapping on the top-level name
        pip_name = import_map.get(key, key)
        if pip_name is None:
            continue
        if _find_spec_safe(key) is not None:
            continue
        if _base_name(pip_name) in skip or key.lower() in skip:
            continue
        if pip_name not in seen:
            seen.add(pip_name)
            missing.append(pip_name)
    return missing


def main() -> None:
    script = Path(sys.argv[1])
    runtime_packages = Path(sys.argv[2]) if len(sys.argv) > 2 else None
    print("\n".join(missing_packages(script.read_text(), runtime_packages)))


if __name__ == "__main__":
    main()
